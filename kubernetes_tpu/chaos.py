"""Chaos harness for crash, failover, and restart recovery.

The PR-1 fault injector proves the scheduler survives a *solver* that
times out, crashes, or lies. This module proves the *process* layer:
the scheduler can die at any instant — between ``binder.bind()``
committing at the hub and ``cache.finish_binding()`` arming the TTL,
mid-solve, between cycles — lose its lease to a standby, or lose its
accelerator, and the system still upholds the invariant triple:

1. **no pod is ever double-bound** (the hub CAS is the truth floor;
   fenced binds + takeover reconciliation keep retries from even
   reaching it);
2. **no assumption is ever leaked** (every assumed pod either confirms
   via the watch or is forgotten by reconciliation / TTL reaping);
3. **every schedulable pod is eventually bound** (crashed-over pods
   requeue; nothing is stranded outside all queues).

Two harnesses, both deterministic under a seed:

- :class:`CrashLoop` — kill/restart a single scheduler against one
  shared :class:`~kubernetes_tpu.sim.HollowCluster` hub, with
  :class:`SchedulerKilled` fired from seeded crash points
  (``bind:pre`` / ``bind:post`` / ``solve:mid`` / ``cycle:pre``). Each
  kill abandons the incarnation's torn local state — exactly like a
  SIGKILL — and a fresh incarnation cold-starts: relist nodes, then
  :meth:`Scheduler.reconcile` against the relisted pod truth.
- :class:`HAReplica` — one member of a dual-scheduler failover pair:
  elector (``LeaseLock`` CASing the hub), reflector-fed scheduler, and
  the full recovery protocol attached (bind fence, takeover
  reconciliation with a hub relist, stopped-leading drain). Tests kill
  the leader mid-churn and inject CAS races; see
  tests/test_crash_recovery.py.

The NETWORK layer (PR 15) gets its own harness trio, all deterministic
under a seed:

- :class:`AmbiguousBinder` — the hub Binding RPC behind an injected
  network: ``rpc_error`` (definitely not committed), ``rpc_timeout``
  (AMBIGUOUS — the commit-coin decides whether the hub applied the
  bind before the response was lost), ``latency``. Counts every bind
  RPC that reaches the hub for an already-bound pod
  (``double_bind_attempts``) — the invariant the scheduler's
  read-your-write protocol must keep at exactly 0.
- :class:`FuzzedCursor` — a watch stream that drops, duplicates, and
  reorders frames, and can force 410/Compacted (the relist-storm
  trigger); the hardened Reflector's resourceVersion-monotonic dedupe
  + progress deadline must make all of it converge.
- :class:`NetChaos` — the composed run: reflector-fed scheduler over
  the fuzzed stream, ambiguous binds, a mid-run relist storm, periodic
  resync relists (the SharedInformer period that heals dropped
  frames), and the state-conservation auditor
  (:class:`~kubernetes_tpu.obs.audit.StateAuditor`) run against the
  hub truth after EVERY cycle. See tests/test_net_chaos.py.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from kubernetes_tpu.testing import make_node, make_pod


class SchedulerKilled(BaseException):
    """A hard process kill at an injected crash point.

    Derives from ``BaseException`` deliberately: every ``except
    Exception`` in the scheduler (bind-error rejects, the solver
    ladder's per-tier catch) must NOT be able to absorb it — the
    incarnation dies with whatever torn local state it had, exactly
    like a SIGKILL between two statements. Only the harness catches it.
    """


class CrashPlan:
    """Seeded crash-point decider shared by every kill site.

    ``fire(site)`` rolls the private RNG stream against ``kill_rate``
    for armed sites; total kills are bounded by ``max_kills`` so a run
    always terminates with a healthy tail that can converge."""

    def __init__(self, seed: int = 0, sites=("bind:pre", "bind:post",
                                             "solve:mid", "cycle:pre"),
                 kill_rate: float = 0.15, max_kills: int = 6) -> None:
        self.rng = random.Random(seed)
        self.sites = set(sites)
        self.kill_rate = kill_rate
        self.max_kills = max_kills
        self.kills = 0
        #: site -> kills fired there (assertable by the chaos tests)
        self.fired: Dict[str, int] = {}

    def fire(self, site: str) -> bool:
        if site not in self.sites or self.kills >= self.max_kills:
            return False
        if self.rng.random() >= self.kill_rate:
            return False
        self.kills += 1
        self.fired[site] = self.fired.get(site, 0) + 1
        return True


class KillingBinder:
    """Binder wrapper with the two bind-side crash windows:

    - ``bind:pre`` — killed before the hub commit: the assumption is
      held locally, nothing is durable. Restart must requeue and bind.
    - ``bind:post`` — killed AFTER ``confirm_binding`` committed at the
      hub but before the driver's ``finish_binding``/bookkeeping ran:
      the hub says bound, the dead incarnation's cache said "assumed,
      bind in flight". Restart must ADOPT, never re-bind (a re-bind
      would hit the hub CAS as "already assigned").
    """

    def __init__(self, inner, plan: CrashPlan) -> None:
        self.inner = inner
        self.plan = plan

    def bind(self, pod, node_name: str) -> None:
        if self.plan.fire("bind:pre"):
            raise SchedulerKilled(f"killed before hub commit of "
                                  f"{pod.key()} -> {node_name}")
        self.inner.bind(pod, node_name)
        if self.plan.fire("bind:post"):
            raise SchedulerKilled(f"killed after hub commit of "
                                  f"{pod.key()} -> {node_name}, before "
                                  "finish_binding")


class _KillingInjector:
    """Duck-typed FaultInjector exposing only the hooks the crash loop
    uses: ``solver_hook`` kills at ``solve:mid`` (a process death while
    the device result is in flight); the device seam stays quiet."""

    def __init__(self, plan: CrashPlan) -> None:
        self.plan = plan

    def solver_hook(self, site, assigned, usage, rounds, n_nodes):
        if self.plan.fire("solve:mid"):
            raise SchedulerKilled(f"killed mid-solve at {site}")
        return assigned, usage, rounds

    def device_hook(self, site):
        return None


class CrashLoop:
    """Kill/restart chaos against one shared sim hub.

    Drives successive ``Scheduler`` incarnations: each runs cycles
    until a seeded crash point fires (:class:`SchedulerKilled`), the
    torn incarnation is abandoned, and a fresh one cold-starts —
    relist nodes from truth, :meth:`Scheduler.reconcile` against the
    relisted pods — with the hub's watch feed re-pointed at it. After
    the kill budget is spent, the final incarnation converges and
    :meth:`run` asserts-by-report the invariant triple."""

    def __init__(self, hub, seed: int = 0, kill_rate: float = 0.2,
                 max_kills: int = 5, scheduler_kw: Optional[dict] = None,
                 ttl_s: float = 30.0) -> None:
        self.hub = hub
        self.plan = CrashPlan(seed=seed, kill_rate=kill_rate,
                              max_kills=max_kills)
        self.scheduler_kw = dict(scheduler_kw or {})
        self.ttl_s = ttl_s
        self.incarnations = 0
        self.sched = None

    def new_incarnation(self):
        """Cold-start a fresh scheduler against the shared hub: new
        cache/queue (the old process's memory is gone), the hub's watch
        feed re-pointed here, relist + reconcile before the first
        cycle."""
        from kubernetes_tpu.cache import SchedulerCache
        from kubernetes_tpu.scheduler import Scheduler

        hub = self.hub
        sched = Scheduler(
            binder=KillingBinder(hub.binder, self.plan),
            clock=hub.clock,
            cache=SchedulerCache(clock=hub.clock, ttl_s=self.ttl_s),
            enable_preemption=False,
            fault_injector=_KillingInjector(self.plan),
            **self.scheduler_kw,
        )
        # the hub delivers watch events to `hub.sched` at emit time —
        # re-pointing it is the "new process connected its informers"
        # step (the dead incarnation receives nothing, like a dead
        # process)
        hub.sched = sched
        for node in hub.truth_nodes.values():
            sched.on_node_add(node)
        sched.reconcile(list(hub.truth_pods.values()))
        self.incarnations += 1
        self.sched = sched
        return sched

    def run(self, n_pods: int = 32, n_nodes: int = 6,
            pod_cpu: float = 500.0, max_steps: int = 400) -> dict:
        """Create ``n_pods`` schedulable pods, then crash-loop until
        every one is bound (or ``max_steps`` cycles elapse). Returns the
        invariant report the chaos tests assert on."""
        hub = self.hub
        for i in range(n_nodes):
            hub.add_node(make_node(f"cl-n{i}", cpu_milli=16000,
                                   pods=max(n_pods, 110)))
        sched = self.new_incarnation()
        for i in range(n_pods):
            hub.create_pod(make_pod(f"cl-p{i}", cpu_milli=pod_cpu))
        steps = 0
        while steps < max_steps:
            steps += 1
            if self.plan.fire("cycle:pre"):
                # killed between cycles — consistent local state, but
                # the restart still must not re-bind anything
                sched = self.new_incarnation()
                continue
            try:
                sched.schedule_cycle()
            except SchedulerKilled:
                sched = self.new_incarnation()
                continue
            hub.clock.advance(0.5)
            if all(p.node_name for p in hub.truth_pods.values()):
                # drain the assume TTLs + settle the cache state machine
                hub.clock.advance(self.ttl_s + 1)
                sched.idle_tick()
                break
        bound = {k: p.node_name for k, p in hub.truth_pods.items()}
        return {
            "steps": steps,
            "incarnations": self.incarnations,
            "kills": self.plan.kills,
            "kill_sites": dict(self.plan.fired),
            # invariant 1: the hub committed each pod exactly once
            "bound_total": hub.bound_total,
            "n_pods": n_pods,
            "all_bound": all(bound.values()),
            "conflicts": hub.binder.conflicts,
            # invariant 2: nothing left assumed after convergence
            "leaked_assumptions": list(self.sched.cache.assumed_keys()),
            "bound": bound,
        }


class MeshChaos:
    """Shard-loss chaos for the sharded backend, arm-able MID-CHURN.

    The mesh tests lose a device between fake-clock cycles; the
    composed serving mode needs the same fault while a real serving
    loop is draining a doorbell on another thread. This helper owns a
    :class:`~kubernetes_tpu.faults.FaultInjector` wired into the
    scheduler's device seam and arms a bounded ``shard_lost`` burst on
    demand: the next ``recovery.device_reset_limit + 1`` snapshots
    raise :class:`~kubernetes_tpu.faults.ShardLost`, which exhausts the
    per-cycle rebuild budget and pushes the scheduler into host-mode
    snapshots for ``device_cooloff_s`` — after which the heal probe
    re-places the resident table SHARDED (cache.set_mesh seam). The
    doorbell loop never stalls: the fault surfaces inside
    ``_device_snapshot_recovering``, which falls back instead of
    raising out of the cycle.

    Arming mutates only the injector's rule list (appends; the GIL
    makes that safe against a concurrent ``pick``), so callers may arm
    from a producer thread without the ingest lock. ``observe`` feeds
    per-cycle snapshot provenance in; :meth:`report` summarizes the
    loss -> host-mode -> healed-sharded arc for bench records."""

    def __init__(self, sched, shard: int = 0) -> None:
        from kubernetes_tpu.faults import FaultInjector

        if sched.fault_injector is None:
            sched.fault_injector = FaultInjector(seed=0)
            # the cache hook is normally attached at construction;
            # late-attached injectors need the same seam
            if getattr(sched.cache, "fault_injector", "absent") is None:
                sched.cache.fault_injector = sched.fault_injector
        self.sched = sched
        self.injector = sched.fault_injector
        self.shard = shard
        self.lost_at: Optional[float] = None
        self.host_cycles = 0
        self.healed_at: Optional[float] = None
        self._was_lost = False

    def lose_shard(self, clock_now: Optional[float] = None) -> None:
        """Arm the loss: enough one-shot ``shard_lost`` faults at the
        snapshot seam to blow the rebuild budget in one cycle (budget
        + 1 — the scheduler retries the rebuild ``device_reset_limit``
        times before cooling off)."""
        shots = self.sched.recovery.device_reset_limit + 1
        self.injector.arm("snapshot:device", "shard_lost", count=shots,
                          shard=self.shard)
        self.lost_at = clock_now
        self._was_lost = True
        self.healed_at = None

    def observe(self, res, clock_now: Optional[float] = None) -> None:
        """Feed one CycleResult: tracks host-mode cycles and stamps the
        heal (first sharded-resident snapshot after a loss)."""
        if not self._was_lost or self.healed_at is not None:
            return
        if res.snapshot_mode == "host":
            self.host_cycles += 1
        elif res.snapshot_mode in ("full", "delta", "clean") \
                and self.host_cycles:
            self.healed_at = clock_now

    def report(self) -> dict:
        heal_s = None
        if (self.healed_at is not None and self.lost_at is not None):
            heal_s = self.healed_at - self.lost_at
        return {
            "shard": self.shard,
            "shard_losses_fired": self.injector.fired_total(
                "snapshot:device"),
            "host_mode_cycles": self.host_cycles,
            "healed_sharded": self.healed_at is not None,
            "shard_heal_s": (round(heal_s, 3)
                             if heal_s is not None else None),
        }


#: the sites the composed network-fault load arms — the disarm half of
#: the phase window removes exactly these, leaving any other rules
#: (shard bursts, crash plans) untouched
NET_FAULT_SITES = ("rpc:bind", "rpc:get", "watch:event", "watch:batch")


def arm_net_fault_load(injector, bind_timeout_rate: float = 0.10,
                       bind_error_rate: float = 0.05,
                       get_timeout_rate: float = 0.08,
                       drop_rate: float = 0.04,
                       dup_rate: float = 0.06,
                       reorder_rate: float = 0.15) -> int:
    """Arm the full network-fault load (ambiguous bind timeouts, bind
    errors, read timeouts, watch drop/duplicate/reorder) on an EXISTING
    injector — the phase-scoped entry half of the window a soak phase
    opens; :func:`disarm_net_fault_load` is the exit half. A zero rate
    skips its rule. Returns the number of rules armed."""
    n0 = len(injector.rules)
    if bind_timeout_rate > 0:
        injector.arm("rpc:bind", "rpc_timeout", rate=bind_timeout_rate)
    if bind_error_rate > 0:
        injector.arm("rpc:bind", "rpc_error", rate=bind_error_rate)
    if get_timeout_rate > 0:
        injector.arm("rpc:get", "rpc_timeout", rate=get_timeout_rate)
    if dup_rate > 0:
        injector.arm("watch:event", "duplicate", rate=dup_rate)
    if drop_rate > 0:
        injector.arm("watch:event", "drop", rate=drop_rate)
    if reorder_rate > 0:
        injector.arm("watch:batch", "reorder", rate=reorder_rate)
    return len(injector.rules) - n0


def disarm_net_fault_load(injector) -> int:
    """Close the network-fault window: remove every rule on the
    :data:`NET_FAULT_SITES` sites (all kinds), whoever armed them.
    Other sites' rules survive. Returns rules removed."""
    return sum(injector.disarm(site) for site in NET_FAULT_SITES)


def raise_injected_rpc(injector, site: str) -> None:
    """Roll the injector at a read/GET RPC site: raise the injected
    :class:`~kubernetes_tpu.faults.RPCError` / ``RPCTimeout``, or
    return for the caller to proceed — the one spelling of the flaky-
    GET seam shared by :class:`NetChaos` and the bench harnesses (the
    verification GET rides the same faulty network as the bind it
    verifies, which is what exercises the deferred/parked path)."""
    out = injector.rpc_hook(site)
    if out is None:
        return
    from kubernetes_tpu.faults import RPCError, RPCTimeout

    kind = out[0]
    if kind == "rpc_error":
        raise RPCError(f"injected rpc error at {site}")
    if kind == "rpc_timeout":
        raise RPCTimeout(f"injected timeout at {site}")


class AmbiguousBinder:
    """The hub Binding RPC behind an injected network (site
    ``rpc:bind``). ``rpc_error`` raises BEFORE the hub acts;
    ``rpc_timeout`` rolls the rule's commit-coin, applies the bind at
    the hub iff it came up committed, then raises
    :class:`~kubernetes_tpu.faults.RPCTimeout` either way — the caller
    can never tell the two apart, which is the whole point.

    ``double_bind_attempts`` counts bind RPCs that REACH the hub for an
    already-bound pod — the measured no-double-place invariant (a
    blind retry of a committed-but-timed-out bind lands here)."""

    def __init__(self, hub, injector, latency_sleep=None) -> None:
        self.hub = hub
        self.injector = injector
        #: None = never sleep (fake-clock runs); else time.sleep-like
        self.latency_sleep = latency_sleep
        self.double_bind_attempts = 0
        self.commits = 0
        self.binds_attempted = 0
        self.timeouts_committed = 0
        self.timeouts_uncommitted = 0
        self.rpc_errors = 0

    def _commit(self, pod, node_name: str) -> None:
        """Apply the bind at the truth — override point for harnesses
        with a different truth store (bench_churn's NetTruth). Must
        account double-bind ATTEMPTS (a bind RPC reaching the truth
        for an already-bound pod) before rejecting them."""
        cur = self.hub.truth_pods.get(pod.key())
        if cur is not None and cur.node_name:
            # a bind RPC for an already-bound pod reached the hub: the
            # CAS rejects it, but the ATTEMPT is the invariant breach
            self.double_bind_attempts += 1
        self.hub.confirm_binding(pod, node_name)
        self.commits += 1

    def bind(self, pod, node_name: str) -> None:
        from kubernetes_tpu.faults import RPCError, RPCTimeout

        self.binds_attempted += 1
        out = self.injector.rpc_hook("rpc:bind")
        if out is None:
            self._commit(pod, node_name)
            return
        kind, rule, committed = out
        if kind == "rpc_error":
            self.rpc_errors += 1
            raise RPCError("injected rpc error at rpc:bind "
                           "(not committed)")
        if kind == "rpc_timeout":
            if committed:
                self.timeouts_committed += 1
                try:
                    self._commit(pod, node_name)
                except Exception:
                    # even the conflict answer was lost on the wire —
                    # the client still just sees a timeout
                    pass
            else:
                self.timeouts_uncommitted += 1
            raise RPCTimeout("injected ambiguous bind timeout at "
                             "rpc:bind")
        if kind == "latency" and self.latency_sleep is not None:
            self.latency_sleep(rule.latency_s)
        self._commit(pod, node_name)


class FuzzedCursor:
    """Watch-stream fuzzer over a sim WatchCursor: consults the
    injector per frame (site ``watch:event``: ``drop`` / ``duplicate``)
    and per poll (site ``watch:batch``: ``reorder`` — seeded shuffle —
    or ``compacted`` — a forced 410). The hardened Reflector must make
    duplicates and reorders no-ops (resourceVersion-monotonic dedupe),
    heal drops via resync/stall relists, and absorb 410 storms through
    the jittered relist backoff."""

    def __init__(self, inner, injector, seed: int = 0) -> None:
        self.inner = inner
        self.injector = injector
        self.rng = random.Random(seed)
        self.dropped = 0
        self.duplicated = 0
        self.reordered = 0
        self.forced_410 = 0

    @property
    def rev(self) -> int:
        return self.inner.rev

    def poll(self):
        from kubernetes_tpu.sim import Compacted

        # the two batch kinds roll SEPARATELY: a 410 can hit any poll
        # (a storm reaches idle watchers too), but a reorder only rolls
        # when there are >= 2 frames to shuffle — so a one-shot reorder
        # rule is never burned on an empty poll and a recorded
        # watch:batch:reorder firing always means frames really moved
        if self.injector.pick("watch:batch",
                              kinds=("compacted",)) == "compacted":
            self.forced_410 += 1
            raise Compacted("injected watch 410 (relist storm)")
        events = self.inner.poll()
        out = []
        for e in events:
            kind = self.injector.pick("watch:event")
            if kind == "drop":
                self.dropped += 1
                continue
            out.append(e)
            if kind == "duplicate":
                self.duplicated += 1
                out.append(e)
        if len(out) > 1 and self.injector.pick(
                "watch:batch", kinds=("reorder",)) == "reorder":
            self.reordered += 1
            self.rng.shuffle(out)
        return out


class NetChaos:
    """Network-fault chaos against one shared sim hub: a reflector-fed
    scheduler whose bind RPCs time out ambiguously, whose watch stream
    drops/duplicates/reorders frames, and whose hub gets one forced
    relist storm mid-run — while the state-conservation auditor checks
    the invariant set against the hub truth after EVERY cycle.

    The run converges iff the ambiguous-outcome bind protocol and the
    reflector hardening both work: every schedulable pod eventually
    bound, zero bind RPCs reaching the hub for an already-bound pod,
    zero auditor violations, nothing left assumed."""

    def __init__(self, hub, seed: int = 0,
                 bind_timeout_rate: float = 0.10,
                 bind_error_rate: float = 0.05,
                 get_timeout_rate: float = 0.08,
                 drop_rate: float = 0.04,
                 dup_rate: float = 0.06,
                 reorder_rate: float = 0.15,
                 progress_deadline_s: float = 4.0,
                 resync_every_s: float = 6.0,
                 scheduler_kw=None) -> None:
        from kubernetes_tpu.faults import FaultInjector, RetryPolicy
        from kubernetes_tpu.obs.audit import StateAuditor
        from kubernetes_tpu.scheduler import Scheduler
        from kubernetes_tpu.sim import Reflector

        self.hub = hub
        inj = FaultInjector(seed=seed)
        arm_net_fault_load(
            inj, bind_timeout_rate=bind_timeout_rate,
            bind_error_rate=bind_error_rate,
            get_timeout_rate=get_timeout_rate,
            drop_rate=drop_rate, dup_rate=dup_rate,
            reorder_rate=reorder_rate)
        self.injector = inj
        self.binder = AmbiguousBinder(hub, inj)

        def pod_reader(key):
            raise_injected_rpc(inj, "rpc:get")
            return hub.truth_pods.get(key)

        self.sched = Scheduler(
            binder=self.binder, clock=hub.clock, pod_reader=pod_reader,
            enable_preemption=False, retry_sleep=lambda _s: None,
            jitter_seed=seed,
            **(scheduler_kw or {}),
        )
        self.auditor = self.sched.attach_auditor(StateAuditor())
        self.reflector = Reflector(
            hub, self.sched, clock=hub.clock,
            progress_deadline_s=progress_deadline_s,
            relist_backoff=RetryPolicy(base_s=0.5, max_s=4.0,
                                       jitter=0.5, seed=seed),
            cursor_wrap=lambda c: FuzzedCursor(c, inj, seed=seed),
        )
        self.reflector.list_and_watch()
        self.resync_every_s = resync_every_s
        self.violations = []

    def relist_storm(self) -> None:
        """Force a 410 on the watch: compact the hub's history AND arm
        a one-shot ``compacted`` rule (a caught-up cursor sits exactly
        AT the compaction floor and would never trip it on its own) —
        the forced-410 storm every replica sees at once; the jittered
        relist backoff is what keeps the relists from stampeding."""
        self.hub.compact(self.hub._revision)
        self.injector.arm("watch:batch", "compacted", count=1)

    def run(self, n_pods: int = 48, n_nodes: int = 8,
            pod_cpu: float = 500.0, max_steps: int = 400,
            storm_step: int = 12) -> dict:
        """Create ``n_pods`` schedulable pods and drive reflector-fed
        cycles under the armed network faults until every one is bound
        and no ambiguous bind is left parked (or ``max_steps`` elapse).
        Returns the invariant report the chaos tests assert on."""
        from kubernetes_tpu.testing import make_node, make_pod

        hub = self.hub
        for i in range(n_nodes):
            hub.add_node(make_node(f"nc-n{i}", cpu_milli=16000,
                                   pods=max(n_pods, 110)))
        for i in range(n_pods):
            hub.create_pod(make_pod(f"nc-p{i}", cpu_milli=pod_cpu))
        steps = 0
        last_resync = hub.clock()
        converged = False
        while steps < max_steps:
            steps += 1
            if steps == storm_step:
                self.relist_storm()
            if hub.clock() - last_resync >= self.resync_every_s:
                # the SharedInformer resync/relist period: the only
                # healer for selectively DROPPED frames (stall relists
                # cover total silence, not partial loss)
                self.reflector.list_and_watch()
                last_resync = hub.clock()
            self.reflector.pump()
            self.sched.schedule_cycle()
            self.violations.extend(self.auditor.audit(
                self.sched, truth_pods=list(hub.truth_pods.values())))
            hub.clock.advance(0.5)
            if all(p.node_name for p in hub.truth_pods.values()) \
                    and not self.sched._ambiguous_binds:
                converged = True
                break
        # settle: relist once more (heal any dropped confirmations),
        # drain TTLs, and run two final truth audits so the two-strike
        # checks get their confirming pass on a stable state
        self.reflector.list_and_watch()
        hub.clock.advance(self.sched.cache.ttl_s + 1)
        self.sched.idle_tick()
        for _ in range(2):
            self.violations.extend(self.auditor.audit(
                self.sched, truth_pods=list(hub.truth_pods.values())))
        bound = {k: p.node_name for k, p in hub.truth_pods.items()}
        return {
            "steps": steps,
            "converged": converged,
            "n_pods": n_pods,
            "all_bound": all(bound.values()),
            "bound_total": hub.bound_total,
            "double_bind_attempts": self.binder.double_bind_attempts,
            "binds_attempted": self.binder.binds_attempted,
            "ambiguous_timeouts": (self.binder.timeouts_committed
                                   + self.binder.timeouts_uncommitted),
            "timeouts_committed": self.binder.timeouts_committed,
            "timeouts_uncommitted": self.binder.timeouts_uncommitted,
            "faults_fired": {f"{s}:{k}": n
                             for (s, k), n in self.injector.fired.items()},
            "watch_deduped": self.reflector.deduped,
            "relists": self.reflector.relists,
            "stalled_relists": self.reflector.stalled_relists,
            "invariant_violations": len(self.violations),
            "violations": [
                {"invariant": v.invariant, "subject": v.subject}
                for v in self.violations[:8]
            ],
            "leaked_assumptions": list(self.sched.cache.assumed_keys()),
            "parked_ambiguous": list(self.sched._ambiguous_binds),
        }


class HAReplica:
    """One member of a dual-scheduler failover pair: elector
    (``LeaseLock`` CASing the hub's coordination Lease), reflector-fed
    scheduler, and the full recovery protocol attached — the elector
    fences every bind, acquiring the lease reconciles against a hub
    relist, losing it drains in-flight state. ``kill()`` stops the
    replica cold (lease decays; no graceful release), ``shutdown()``
    releases the lease like a clean SIGTERM."""

    def __init__(self, name: str, hub, le_config=None,
                 scheduler_kw: Optional[dict] = None) -> None:
        from kubernetes_tpu.leaderelection import LeaderElector, LeaseLock
        from kubernetes_tpu.scheduler import Scheduler
        from kubernetes_tpu.sim import Reflector

        self.name = name
        self.hub = hub
        self.sched = Scheduler(binder=hub.binder, clock=hub.clock,
                               enable_preemption=False,
                               **(scheduler_kw or {}))
        # clock wired so robustness.watchProgressDeadline (inherited
        # from the sink scheduler's config) can break a silently
        # stalled watch instead of idling a standby forever
        self.reflector = Reflector(hub, self.sched, clock=hub.clock)
        self.reflector.list_and_watch()
        self.elector = LeaderElector(name, LeaseLock(hub), le_config,
                                     hub.clock)
        self.sched.attach_elector(
            self.elector,
            lister=lambda: list(hub.truth_pods.values()))
        self.dead = False
        self.cycles = 0

    def tick(self) -> bool:
        """One replica heartbeat: pump informers (leaders AND standbys
        run them), tick the elector, schedule while leading. Returns
        whether a cycle ran."""
        if self.dead:
            return False
        self.reflector.pump()
        if self.elector.tick():
            self.sched.schedule_cycle()
            self.cycles += 1
            return True
        return False

    def kill(self) -> None:
        """Hard death: stops ticking; the lease decays on its own."""
        self.dead = True

    def revive(self) -> None:
        self.dead = False

    def shutdown(self) -> None:
        """Clean SIGTERM: drain via the elector callbacks and release
        the lease so the standby takes over immediately."""
        self.dead = True
        self.elector.release()
