"""Chaos harness for crash, failover, and restart recovery.

The PR-1 fault injector proves the scheduler survives a *solver* that
times out, crashes, or lies. This module proves the *process* layer:
the scheduler can die at any instant — between ``binder.bind()``
committing at the hub and ``cache.finish_binding()`` arming the TTL,
mid-solve, between cycles — lose its lease to a standby, or lose its
accelerator, and the system still upholds the invariant triple:

1. **no pod is ever double-bound** (the hub CAS is the truth floor;
   fenced binds + takeover reconciliation keep retries from even
   reaching it);
2. **no assumption is ever leaked** (every assumed pod either confirms
   via the watch or is forgotten by reconciliation / TTL reaping);
3. **every schedulable pod is eventually bound** (crashed-over pods
   requeue; nothing is stranded outside all queues).

Two harnesses, both deterministic under a seed:

- :class:`CrashLoop` — kill/restart a single scheduler against one
  shared :class:`~kubernetes_tpu.sim.HollowCluster` hub, with
  :class:`SchedulerKilled` fired from seeded crash points
  (``bind:pre`` / ``bind:post`` / ``solve:mid`` / ``cycle:pre``). Each
  kill abandons the incarnation's torn local state — exactly like a
  SIGKILL — and a fresh incarnation cold-starts: relist nodes, then
  :meth:`Scheduler.reconcile` against the relisted pod truth.
- :class:`HAReplica` — one member of a dual-scheduler failover pair:
  elector (``LeaseLock`` CASing the hub), reflector-fed scheduler, and
  the full recovery protocol attached (bind fence, takeover
  reconciliation with a hub relist, stopped-leading drain). Tests kill
  the leader mid-churn and inject CAS races; see
  tests/test_crash_recovery.py.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from kubernetes_tpu.testing import make_node, make_pod


class SchedulerKilled(BaseException):
    """A hard process kill at an injected crash point.

    Derives from ``BaseException`` deliberately: every ``except
    Exception`` in the scheduler (bind-error rejects, the solver
    ladder's per-tier catch) must NOT be able to absorb it — the
    incarnation dies with whatever torn local state it had, exactly
    like a SIGKILL between two statements. Only the harness catches it.
    """


class CrashPlan:
    """Seeded crash-point decider shared by every kill site.

    ``fire(site)`` rolls the private RNG stream against ``kill_rate``
    for armed sites; total kills are bounded by ``max_kills`` so a run
    always terminates with a healthy tail that can converge."""

    def __init__(self, seed: int = 0, sites=("bind:pre", "bind:post",
                                             "solve:mid", "cycle:pre"),
                 kill_rate: float = 0.15, max_kills: int = 6) -> None:
        self.rng = random.Random(seed)
        self.sites = set(sites)
        self.kill_rate = kill_rate
        self.max_kills = max_kills
        self.kills = 0
        #: site -> kills fired there (assertable by the chaos tests)
        self.fired: Dict[str, int] = {}

    def fire(self, site: str) -> bool:
        if site not in self.sites or self.kills >= self.max_kills:
            return False
        if self.rng.random() >= self.kill_rate:
            return False
        self.kills += 1
        self.fired[site] = self.fired.get(site, 0) + 1
        return True


class KillingBinder:
    """Binder wrapper with the two bind-side crash windows:

    - ``bind:pre`` — killed before the hub commit: the assumption is
      held locally, nothing is durable. Restart must requeue and bind.
    - ``bind:post`` — killed AFTER ``confirm_binding`` committed at the
      hub but before the driver's ``finish_binding``/bookkeeping ran:
      the hub says bound, the dead incarnation's cache said "assumed,
      bind in flight". Restart must ADOPT, never re-bind (a re-bind
      would hit the hub CAS as "already assigned").
    """

    def __init__(self, inner, plan: CrashPlan) -> None:
        self.inner = inner
        self.plan = plan

    def bind(self, pod, node_name: str) -> None:
        if self.plan.fire("bind:pre"):
            raise SchedulerKilled(f"killed before hub commit of "
                                  f"{pod.key()} -> {node_name}")
        self.inner.bind(pod, node_name)
        if self.plan.fire("bind:post"):
            raise SchedulerKilled(f"killed after hub commit of "
                                  f"{pod.key()} -> {node_name}, before "
                                  "finish_binding")


class _KillingInjector:
    """Duck-typed FaultInjector exposing only the hooks the crash loop
    uses: ``solver_hook`` kills at ``solve:mid`` (a process death while
    the device result is in flight); the device seam stays quiet."""

    def __init__(self, plan: CrashPlan) -> None:
        self.plan = plan

    def solver_hook(self, site, assigned, usage, rounds, n_nodes):
        if self.plan.fire("solve:mid"):
            raise SchedulerKilled(f"killed mid-solve at {site}")
        return assigned, usage, rounds

    def device_hook(self, site):
        return None


class CrashLoop:
    """Kill/restart chaos against one shared sim hub.

    Drives successive ``Scheduler`` incarnations: each runs cycles
    until a seeded crash point fires (:class:`SchedulerKilled`), the
    torn incarnation is abandoned, and a fresh one cold-starts —
    relist nodes from truth, :meth:`Scheduler.reconcile` against the
    relisted pods — with the hub's watch feed re-pointed at it. After
    the kill budget is spent, the final incarnation converges and
    :meth:`run` asserts-by-report the invariant triple."""

    def __init__(self, hub, seed: int = 0, kill_rate: float = 0.2,
                 max_kills: int = 5, scheduler_kw: Optional[dict] = None,
                 ttl_s: float = 30.0) -> None:
        self.hub = hub
        self.plan = CrashPlan(seed=seed, kill_rate=kill_rate,
                              max_kills=max_kills)
        self.scheduler_kw = dict(scheduler_kw or {})
        self.ttl_s = ttl_s
        self.incarnations = 0
        self.sched = None

    def new_incarnation(self):
        """Cold-start a fresh scheduler against the shared hub: new
        cache/queue (the old process's memory is gone), the hub's watch
        feed re-pointed here, relist + reconcile before the first
        cycle."""
        from kubernetes_tpu.cache import SchedulerCache
        from kubernetes_tpu.scheduler import Scheduler

        hub = self.hub
        sched = Scheduler(
            binder=KillingBinder(hub.binder, self.plan),
            clock=hub.clock,
            cache=SchedulerCache(clock=hub.clock, ttl_s=self.ttl_s),
            enable_preemption=False,
            fault_injector=_KillingInjector(self.plan),
            **self.scheduler_kw,
        )
        # the hub delivers watch events to `hub.sched` at emit time —
        # re-pointing it is the "new process connected its informers"
        # step (the dead incarnation receives nothing, like a dead
        # process)
        hub.sched = sched
        for node in hub.truth_nodes.values():
            sched.on_node_add(node)
        sched.reconcile(list(hub.truth_pods.values()))
        self.incarnations += 1
        self.sched = sched
        return sched

    def run(self, n_pods: int = 32, n_nodes: int = 6,
            pod_cpu: float = 500.0, max_steps: int = 400) -> dict:
        """Create ``n_pods`` schedulable pods, then crash-loop until
        every one is bound (or ``max_steps`` cycles elapse). Returns the
        invariant report the chaos tests assert on."""
        hub = self.hub
        for i in range(n_nodes):
            hub.add_node(make_node(f"cl-n{i}", cpu_milli=16000,
                                   pods=max(n_pods, 110)))
        sched = self.new_incarnation()
        for i in range(n_pods):
            hub.create_pod(make_pod(f"cl-p{i}", cpu_milli=pod_cpu))
        steps = 0
        while steps < max_steps:
            steps += 1
            if self.plan.fire("cycle:pre"):
                # killed between cycles — consistent local state, but
                # the restart still must not re-bind anything
                sched = self.new_incarnation()
                continue
            try:
                sched.schedule_cycle()
            except SchedulerKilled:
                sched = self.new_incarnation()
                continue
            hub.clock.advance(0.5)
            if all(p.node_name for p in hub.truth_pods.values()):
                # drain the assume TTLs + settle the cache state machine
                hub.clock.advance(self.ttl_s + 1)
                sched.idle_tick()
                break
        bound = {k: p.node_name for k, p in hub.truth_pods.items()}
        return {
            "steps": steps,
            "incarnations": self.incarnations,
            "kills": self.plan.kills,
            "kill_sites": dict(self.plan.fired),
            # invariant 1: the hub committed each pod exactly once
            "bound_total": hub.bound_total,
            "n_pods": n_pods,
            "all_bound": all(bound.values()),
            "conflicts": hub.binder.conflicts,
            # invariant 2: nothing left assumed after convergence
            "leaked_assumptions": list(self.sched.cache.assumed_keys()),
            "bound": bound,
        }


class MeshChaos:
    """Shard-loss chaos for the sharded backend, arm-able MID-CHURN.

    The mesh tests lose a device between fake-clock cycles; the
    composed serving mode needs the same fault while a real serving
    loop is draining a doorbell on another thread. This helper owns a
    :class:`~kubernetes_tpu.faults.FaultInjector` wired into the
    scheduler's device seam and arms a bounded ``shard_lost`` burst on
    demand: the next ``recovery.device_reset_limit + 1`` snapshots
    raise :class:`~kubernetes_tpu.faults.ShardLost`, which exhausts the
    per-cycle rebuild budget and pushes the scheduler into host-mode
    snapshots for ``device_cooloff_s`` — after which the heal probe
    re-places the resident table SHARDED (cache.set_mesh seam). The
    doorbell loop never stalls: the fault surfaces inside
    ``_device_snapshot_recovering``, which falls back instead of
    raising out of the cycle.

    Arming mutates only the injector's rule list (appends; the GIL
    makes that safe against a concurrent ``pick``), so callers may arm
    from a producer thread without the ingest lock. ``observe`` feeds
    per-cycle snapshot provenance in; :meth:`report` summarizes the
    loss -> host-mode -> healed-sharded arc for bench records."""

    def __init__(self, sched, shard: int = 0) -> None:
        from kubernetes_tpu.faults import FaultInjector

        if sched.fault_injector is None:
            sched.fault_injector = FaultInjector(seed=0)
            # the cache hook is normally attached at construction;
            # late-attached injectors need the same seam
            if getattr(sched.cache, "fault_injector", "absent") is None:
                sched.cache.fault_injector = sched.fault_injector
        self.sched = sched
        self.injector = sched.fault_injector
        self.shard = shard
        self.lost_at: Optional[float] = None
        self.host_cycles = 0
        self.healed_at: Optional[float] = None
        self._was_lost = False

    def lose_shard(self, clock_now: Optional[float] = None) -> None:
        """Arm the loss: enough one-shot ``shard_lost`` faults at the
        snapshot seam to blow the rebuild budget in one cycle (budget
        + 1 — the scheduler retries the rebuild ``device_reset_limit``
        times before cooling off)."""
        shots = self.sched.recovery.device_reset_limit + 1
        self.injector.arm("snapshot:device", "shard_lost", count=shots,
                          shard=self.shard)
        self.lost_at = clock_now
        self._was_lost = True
        self.healed_at = None

    def observe(self, res, clock_now: Optional[float] = None) -> None:
        """Feed one CycleResult: tracks host-mode cycles and stamps the
        heal (first sharded-resident snapshot after a loss)."""
        if not self._was_lost or self.healed_at is not None:
            return
        if res.snapshot_mode == "host":
            self.host_cycles += 1
        elif res.snapshot_mode in ("full", "delta", "clean") \
                and self.host_cycles:
            self.healed_at = clock_now

    def report(self) -> dict:
        heal_s = None
        if (self.healed_at is not None and self.lost_at is not None):
            heal_s = self.healed_at - self.lost_at
        return {
            "shard": self.shard,
            "shard_losses_fired": self.injector.fired_total(
                "snapshot:device"),
            "host_mode_cycles": self.host_cycles,
            "healed_sharded": self.healed_at is not None,
            "shard_heal_s": (round(heal_s, 3)
                             if heal_s is not None else None),
        }


class HAReplica:
    """One member of a dual-scheduler failover pair: elector
    (``LeaseLock`` CASing the hub's coordination Lease), reflector-fed
    scheduler, and the full recovery protocol attached — the elector
    fences every bind, acquiring the lease reconciles against a hub
    relist, losing it drains in-flight state. ``kill()`` stops the
    replica cold (lease decays; no graceful release), ``shutdown()``
    releases the lease like a clean SIGTERM."""

    def __init__(self, name: str, hub, le_config=None,
                 scheduler_kw: Optional[dict] = None) -> None:
        from kubernetes_tpu.leaderelection import LeaderElector, LeaseLock
        from kubernetes_tpu.scheduler import Scheduler
        from kubernetes_tpu.sim import Reflector

        self.name = name
        self.hub = hub
        self.sched = Scheduler(binder=hub.binder, clock=hub.clock,
                               enable_preemption=False,
                               **(scheduler_kw or {}))
        self.reflector = Reflector(hub, self.sched)
        self.reflector.list_and_watch()
        self.elector = LeaderElector(name, LeaseLock(hub), le_config,
                                     hub.clock)
        self.sched.attach_elector(
            self.elector,
            lister=lambda: list(hub.truth_pods.values()))
        self.dead = False
        self.cycles = 0

    def tick(self) -> bool:
        """One replica heartbeat: pump informers (leaders AND standbys
        run them), tick the elector, schedule while leading. Returns
        whether a cycle ran."""
        if self.dead:
            return False
        self.reflector.pump()
        if self.elector.tick():
            self.sched.schedule_cycle()
            self.cycles += 1
            return True
        return False

    def kill(self) -> None:
        """Hard death: stops ticking; the lease decays on its own."""
        self.dead = True

    def revive(self) -> None:
        self.dead = False

    def shutdown(self) -> None:
        """Clean SIGTERM: drain via the elector callbacks and release
        the lease so the standby takes over immediately."""
        self.dead = True
        self.elector.release()
