"""The doorbell — wake-on-event for the serving loop.

The cycle-oriented driver sleeps a fixed ``--cycle-interval`` between
polls (cli.py), paying up to one full interval of create-to-bind latency
on a bursty queue and minting wakeups on an idle one. The doorbell is
the replacement signal: every source of schedulable work — the
SchedulingQueue's incoming events (PodAdd, PodUpdate, BackoffComplete,
the move-to-active sweeps the informer paths trigger), bind-path cache
invalidations, REST mutation handlers — rings it, and the serving loop
blocks on :meth:`Doorbell.wait` instead of a timer.

Semantics are level-triggered with a pending count (not edge-triggered):
a ring while nobody is waiting is remembered, so the classic lost-wakeup
race (event lands between the loop's depth check and its wait) cannot
drop work. ``ScheduleAttemptFailure`` deliberately does NOT ring — it is
the scheduler's own output, and ringing on it would spin the loop
against a queue of unschedulable pods that no cluster event has touched.

Thread-safe; waiting rides a ``threading.Condition`` (real time — the
serving loop is a real thread), but the ring/pending counters are
inspectable without blocking (``pending()`` / ``consume()``) so
fake-clock tests never sleep.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class Doorbell:
    """Level-triggered wakeup signal with per-reason ring accounting."""

    def __init__(self, metrics=None) -> None:
        self._cond = threading.Condition()
        self._pending = 0
        #: lifetime rings (monotone; pending is the unconsumed slice)
        self.rings_total = 0
        self.rings_by_reason: Dict[str, int] = {}
        #: optional SchedulerMetrics — drives
        #: scheduler_doorbell_rings_total{reason}
        self.metrics = metrics

    def ring(self, reason: str = "") -> None:
        """Signal that schedulable work may exist. Never blocks; safe
        from any thread (informer pumps, REST handler threads, the
        queue's own mutation paths)."""
        with self._cond:
            self._pending += 1
            self.rings_total += 1
            self.rings_by_reason[reason] = (
                self.rings_by_reason.get(reason, 0) + 1)
            self._cond.notify_all()
        m = self.metrics
        if m is not None:
            m.doorbell_rings.inc(reason=reason)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until rung or ``timeout`` (seconds; None = forever).
        Consumes every pending ring. Returns True when at least one ring
        arrived (before or during the wait), False on a clean timeout."""
        with self._cond:
            if self._pending == 0:
                self._cond.wait(timeout)
            rung = self._pending > 0
            self._pending = 0
            return rung

    def consume(self) -> int:
        """Non-blocking drain: pending ring count, resetting it to zero
        (the legacy serve loop's 'has anything happened since my last
        look' check; also what fake-clock tests poll)."""
        with self._cond:
            n, self._pending = self._pending, 0
            return n

    def pending(self) -> int:
        """Unconsumed rings (no reset)."""
        with self._cond:
            return self._pending
