"""Streaming serving mode — the event-driven layer between the queue and
the batched solver (ROADMAP item 3: cycles -> a streaming scheduler under
production churn).

Four pieces, each usable standalone:

- :mod:`kubernetes_tpu.serving.doorbell` — a condition-variable doorbell
  the SchedulingQueue, informer/bind paths, and REST mutation handlers
  ring on activity; replaces the fixed-interval sleep in ``cli.run`` with
  wake-on-event.
- :mod:`kubernetes_tpu.serving.microbatch` — the adaptive accumulation
  window (min/max wait, flush targets snapped to the PR-5 AOT warmup
  buckets so steady-state churn never retraces) and the
  :class:`ServingLoop` that drives ``Scheduler`` cycles from it.
- :mod:`kubernetes_tpu.serving.fairness` — API-priority-and-fairness-
  style load shedding for the REST facades (per-flow-schema concurrency
  limits, bounded FIFO queues, 429 + Retry-After on overload) and the
  bounded-buffer watch fan-out hub (a slow watcher is disconnected with
  410 Gone instead of stalling the publisher).
- :mod:`kubernetes_tpu.serving.compose` — :class:`ServingRuntime`, the
  COMPOSED production posture: the serving loop on the sharded mesh
  backend with the crash/failover protocol, APF shedding wired to the
  scheduler's real backend pressure, and takeover-relisted watch
  fan-out — one constructor shared by ``cli.run --serving`` and the
  churn benches.
"""

from kubernetes_tpu.serving.compose import ServingRuntime
from kubernetes_tpu.serving.doorbell import Doorbell
from kubernetes_tpu.serving.fairness import (
    FlowController,
    FlowSchema,
    RequestRejected,
    WatcherGone,
    WatchHub,
)
from kubernetes_tpu.serving.microbatch import (
    MicroBatchWindow,
    ServingLoop,
    WindowDecision,
)

__all__ = [
    "Doorbell",
    "FlowController",
    "FlowSchema",
    "MicroBatchWindow",
    "RequestRejected",
    "ServingLoop",
    "ServingRuntime",
    "WatcherGone",
    "WatchHub",
    "WindowDecision",
]
