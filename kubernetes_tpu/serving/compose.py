"""The composed serving runtime — streaming serving ON the sharded
mesh backend WITH the crash/failover protocol, as one first-class seam.

PRs 6 (micro-batch serving), 8 (fenced binds + takeover
reconciliation), and 9 (node-axis mesh backend) each work alone;
production needs them in ONE process: a doorbell-driven loop flushing
warmed micro-batches into a GSPMD-sharded solve, an APF layer shedding
from the scheduler's REAL state, watch fan-out that survives a
takeover, and an elector whose leadership side-effects (reconcile,
drain, re-warm, mesh re-placement) serialize against the ingest lock.
Before this module, cli.run hand-assembled that composition and the
benches re-assembled it slightly differently; :class:`ServingRuntime`
is the one constructor both use, so "the composed configuration" means
the same wiring everywhere.

What composing changes (vs. the pieces in isolation):

- **warmup**: the serving grid extends down to micro-batch buckets
  (min bucket 8), and — when a mesh is on — the single-device
  host-mode signatures warm TOO (``warmup.host_fallback``), so a shard
  lost mid-churn degrades through the cooloff without a hot-path
  compile or a retrace;
- **APF shedding**: the mutating flow's saturation probe is
  :meth:`Scheduler.backend_pressure` — active-queue depth INFLATED
  while the ladder runs degraded, the device cools off, or the perf
  ledger's SLO watchdog is burning (obs/ledger.py: eroding
  create-to-bind p99 or drifting cycle cost reads as a degraded
  backend) — not bare queue length, so a limping backend sheds
  earlier at the same depth;
- **takeover**: ``attach_elector`` chains the scheduler's recovery
  callbacks (fenced binds, reconcile-onto-the-mesh, stopped-leading
  drain) AND the watch hub's relist eviction — watchers of a deposed
  or newly-elected replica get 410 Gone + the relist hint instead of
  silently straddling two leaderships — and :meth:`gate` runs the
  elector tick under the loop's ingest lock, exactly the serialization
  the PR-8 review hardening demands.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

from kubernetes_tpu.serving.doorbell import Doorbell
from kubernetes_tpu.serving.fairness import (
    FlowController,
    WatchHub,
    default_flows,
)
from kubernetes_tpu.serving.microbatch import MIN_BUCKET, ServingLoop


class ServingRuntime:
    """One serving replica, fully composed: scheduler (mesh-backed or
    not), doorbell, micro-batch loop, APF flow controller with the
    backend-pressure probe wired, and the watch fan-out hub.

    ``sched`` may be any constructed Scheduler — including one whose
    ``parallel.mesh`` built a device mesh; the runtime adapts (warmed
    grid, host-fallback warmup, saturation wiring) instead of asking
    the caller to remember the composition rules."""

    def __init__(
        self,
        sched,
        serving=None,
        warmup=None,
        clock: Callable[[], float] = time.monotonic,
        on_cycle: Optional[Callable] = None,
    ) -> None:
        from kubernetes_tpu.config import ServingConfig

        self.sched = sched
        self.config = serving if serving is not None else ServingConfig()
        self.clock = clock
        # -- warmed-grid adaptation (was inline in cli.run) ---------------
        wu = warmup if warmup is not None else sched.warmup_config
        if wu.enabled:
            if not wu.pod_buckets and wu.min_bucket > MIN_BUCKET:
                # the streaming path presents SMALL buckets
                # (micro-batches pad to bucket_size(depth), floor 8);
                # the batch-mode default min_bucket=256 would leave
                # them unwarmed and every trickle cycle would retrace
                wu = dataclasses.replace(wu, min_bucket=MIN_BUCKET)
            if sched.mesh is not None and not wu.host_fallback:
                # composed mode: a shard loss mid-churn must not pay a
                # hot-path compile — warm the host-mode fallback shapes
                wu = dataclasses.replace(wu, host_fallback=True)
        sched.warmup_config = wu
        self._warmup_pending = wu.enabled
        # -- the loop + doorbell ------------------------------------------
        self.bell = sched.attach_doorbell(Doorbell())
        self.loop = ServingLoop(sched, self.bell, self.config,
                                on_cycle=on_cycle, clock=clock)
        # -- APF admission with the REAL saturation probe -----------------
        self.flow = FlowController(
            flows=default_flows(
                concurrency=self.config.flow_concurrency,
                queue_length=self.config.flow_queue_length,
                watch_concurrency=self.config.watch_concurrency,
                queue_timeout_s=self.config.queue_timeout_s),
            retry_after_s=self.config.retry_after_s,
            metrics=sched.metrics)
        factor = self.config.degraded_pressure_factor
        self.flow.set_saturation(
            "mutating",
            lambda: sched.backend_pressure(degraded_factor=factor),
            maximum=float(self.shed_bound()))
        # -- perf ledger / SLO watchdog ------------------------------------
        #: the composed runtime's SLO surface (obs/ledger.py): the
        #: serving loop's per-pod create-to-bind latencies feed the
        #: watchdog through end_cycle, and a sustained burn inflates
        #: the backend_pressure probe wired above — the online "p99 is
        #: eroding" -> "shed earlier" loop. Exposed here so benches and
        #: operators reach the arm summary without digging through obs.
        #: getattr: duck-typed scheduler fakes stay valid.
        self.ledger = getattr(getattr(sched, "obs", None), "ledger", None)
        # -- watch fan-out -------------------------------------------------
        self.hub = WatchHub(buffer=self.config.watch_buffer,
                            metrics=sched.metrics)
        # -- state-conservation auditor (obs/audit.py) ---------------------
        #: runs the structural invariants (multi-state, capacity,
        #: truthless conservation) every ``observability.
        #: audit_interval_s`` seconds BETWEEN loop iterations, under the
        #: ingest lock (never mid-cycle). 0 = off (the default: chaos
        #: suites and benches attach their own). Violations land on
        #: scheduler_invariant_violations_total, a spam-filtered
        #: InvariantViolation event, and the invariants= flight flag.
        self.auditor = None
        obs_cfg = getattr(getattr(sched, "obs", None), "config", None)
        self._audit_interval = float(
            getattr(obs_cfg, "audit_interval_s", 0.0) or 0.0)
        self._next_audit = 0.0
        if self._audit_interval > 0:
            from kubernetes_tpu.obs.audit import StateAuditor

            self.auditor = sched.attach_auditor(StateAuditor())
            self.add_maintenance(self.maybe_audit)

    def add_maintenance(self, fn: Callable[[], object]) -> Callable:
        """CHAIN a per-iteration maintenance hook onto the serving loop
        (run between run_once iterations, never mid-cycle). Chaining —
        not assignment — is the contract: the audit sweep, the soak
        engine's sentinel cadence, and a bench's own probe must
        compose on one runtime without knowing about each other (the
        same prev-then-ours idiom attach_elector uses for leadership
        callbacks). Hooks run in attachment order. Returns ``fn``."""
        prev = self.loop.maintenance

        def chained() -> None:
            if prev is not None:
                prev()
            fn()

        self.loop.maintenance = chained
        return fn

    def maybe_audit(self) -> int:
        """The low-frequency state-conservation sweep: run the
        structural invariants when the interval elapsed, under the
        ingest lock so producers and leadership side-effects are
        quiesced. Returns violations found this call (0 = clean or not
        due yet)."""
        if self.auditor is None:
            return 0
        now = self.clock()
        if now < self._next_audit:
            return 0
        self._next_audit = now + self._audit_interval
        with self.loop.lock:
            return len(self.auditor.audit(self.sched))

    def shed_bound(self) -> int:
        """The mutating flow's pressure bound: configured, or auto =
        two full accumulation targets of headroom (one window in
        flight, one accumulating)."""
        if self.config.shed_queue_bound > 0:
            return self.config.shed_queue_bound
        return 2 * self.loop.window.target_bucket

    # -- failover wiring ----------------------------------------------------

    def attach_elector(self, elector, lister=None):
        """Scheduler recovery wiring (fenced binds, takeover
        reconciliation onto the mesh, stopped-leading drain) PLUS the
        serving layer's own transition duty: every leadership change
        relists this replica's watchers — their event stream straddles
        two write histories, so they get 410 Gone + the relist hint
        rather than a silent seam. Returns the elector."""
        self.sched.attach_elector(elector, lister=lister)
        hub = self.hub
        prev_start = elector.on_started_leading
        prev_stop = elector.on_stopped_leading

        def started():
            prev_start()
            hub.evict_all("leadership change (takeover): relist")

        def stopped():
            prev_stop()
            hub.evict_all("leadership change (deposed): relist")

        elector.on_started_leading = started
        elector.on_stopped_leading = stopped
        return elector

    # -- the per-iteration admission gate ------------------------------------

    def warm_if_pending(self, sample_pods=None) -> int:
        """Lazy AOT warmup, first node sync permitting — callers hold
        the ingest lock (the gate below does). ``sample_pods`` overrides
        the queue-derived sample (benches warm with a representative
        pod before any producer starts). Returns shapes compiled this
        call (0 when already warm / still no nodes)."""
        if not self._warmup_pending or not self.sched.cache.node_count():
            return 0
        if sample_pods is None:
            pp = getattr(self.sched.queue, "pending_pods", None)
            sample_pods = pp().get("active", [])[:64] if pp else []
        n = self.sched.warmup(sample_pods=sample_pods)
        self._warmup_pending = False
        return n

    def gate(self, stop, elector=None, retry_period_s: float = 1.0):
        """Build the per-iteration admission callable for
        :meth:`ServingLoop.run`: tick the elector and run the lazy
        warmup UNDER THE INGEST LOCK (leadership side-effects —
        reconcile, drain, warmup, mesh re-placement — mutate the
        queue/cache that producer threads feed through the same lock;
        ticking unlocked races them exactly at takeover)."""
        loop = self.loop

        def _gate() -> bool:
            if elector is not None:
                with loop.lock:
                    leading = elector.tick()
                if not leading:
                    stop.wait(retry_period_s)
                    return False
            if self._warmup_pending:
                # check the flag OUTSIDE the lock: once warm, the gate
                # must not contend with producers on every iteration
                with loop.lock:
                    self.warm_if_pending()
            return True

        return _gate

    def run(self, stop, elector=None, retry_period_s: float = 1.0) -> None:
        """Serve until ``stop``: the composed loop with the gate
        installed (cli.run's serving branch, and the benches')."""
        self.loop.run(stop, gate=self.gate(stop, elector, retry_period_s))
