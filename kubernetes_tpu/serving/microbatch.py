"""Adaptive micro-batch accumulation window + the event-driven serving
loop.

The batched solver is at its best when a cycle carries a full
power-of-two pod bucket: ``pods_to_device`` pads every batch to
``bucket_size(len(batch))`` (utils/interner — the PR-5 shape grid the
AOT warmup compiles), so a batch of 17 pods pays the 32-bucket solve
anyway. The window therefore trades a bounded amount of queueing latency
for shape-perfect batches:

- the window OPENS on the first pending pod (doorbell-driven, not
  polled);
- it flushes IMMEDIATELY when the accumulated depth fills a warmed
  bucket — either the configured accumulation cap (``target_bucket``),
  or, once ``min_wait`` has elapsed, any exact power-of-two boundary
  (zero padding waste; waiting longer only adds latency until a 2x
  larger bucket could fill);
- it flushes unconditionally at ``max_wait`` — the latency ceiling a
  trickle workload pays.

Steady-state churn therefore presents only bucket shapes the warmup
already compiled: zero solve-site retraces
(``scheduler_jax_retrace_total`` flat), which is what makes wake-on-
event viable at production rates.

:class:`MicroBatchWindow` is pure decision logic on an injected clock
(fake-clock testable, no threads); :class:`ServingLoop` is the real
serve loop that marries it to a :class:`~kubernetes_tpu.serving.
doorbell.Doorbell` and a ``Scheduler``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from kubernetes_tpu.utils.interner import bucket_size

#: the padding grid's smallest bucket (pods_to_device's bucket_size
#: minimum) — depths below it can never sit on a warmed boundary
MIN_BUCKET = 8


@dataclass
class WindowDecision:
    """What the window wants done right now."""

    flush: bool = False
    #: why ("bucket-fill" | "max-wait"); "" when not flushing
    trigger: str = ""
    #: when not flushing: how long the loop may wait before the next
    #: decision point (doorbell rings cut it short)
    wait_s: float = 0.0


class MicroBatchWindow:
    """Accumulation-window state machine (decision logic only)."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        min_wait_s: float = 0.005,
        max_wait_s: float = 0.05,
        target_bucket: int = 1024,
    ) -> None:
        if min_wait_s < 0 or max_wait_s < min_wait_s:
            raise ValueError(
                "microbatch window needs 0 <= min_wait <= max_wait")
        self.clock = clock
        self.min_wait_s = float(min_wait_s)
        self.max_wait_s = float(max_wait_s)
        #: accumulation cap, snapped DOWN to the padding grid (snapping
        #: up would chase a bucket the warmup never compiled)
        tb = bucket_size(max(int(target_bucket), MIN_BUCKET))
        self.target_bucket = tb if tb <= target_bucket else tb // 2
        #: None = closed; else the clock stamp of the first pending pod
        self.opened_at: Optional[float] = None

    @property
    def open(self) -> bool:
        return self.opened_at is not None

    def reset(self) -> None:
        self.opened_at = None

    def close(self, now: Optional[float] = None) -> float:
        """Close the window (the caller is about to flush); returns the
        accumulation duration actually spent."""
        now = self.clock() if now is None else now
        w = now - self.opened_at if self.opened_at is not None else 0.0
        self.opened_at = None
        return max(w, 0.0)

    def observe(self, depth: int, now: Optional[float] = None) -> WindowDecision:
        """One look at the active-queue depth -> flush / wait verdict."""
        now = self.clock() if now is None else now
        if depth <= 0:
            # nothing pending: an open window with zero depth means the
            # pods left by another path (delete, competing binder) —
            # close it rather than flushing an empty cycle at max_wait
            self.opened_at = None
            return WindowDecision()
        if self.opened_at is None:
            self.opened_at = now
        if depth >= self.target_bucket:
            return WindowDecision(flush=True, trigger="bucket-fill")
        elapsed = now - self.opened_at
        if elapsed >= self.max_wait_s:
            return WindowDecision(flush=True, trigger="max-wait")
        if (elapsed >= self.min_wait_s and depth >= MIN_BUCKET
                and bucket_size(depth) == depth):
            # the depth sits exactly on a warmed power-of-two boundary:
            # flushing now wastes zero padding, and any further
            # accumulation re-pays latency until a 2x bucket could fill
            return WindowDecision(flush=True, trigger="bucket-fill")
        deadline = self.opened_at + self.max_wait_s
        if elapsed < self.min_wait_s:
            deadline = min(deadline, self.opened_at + self.min_wait_s)
        return WindowDecision(wait_s=max(deadline - now, 0.0))


class ServingLoop:
    """The event-driven replacement for ``cli.run``'s fixed-interval
    loop: block on the doorbell, accumulate through the micro-batch
    window, drive ``Scheduler.schedule_cycle`` on flush.

    Idle behavior: with nothing in activeQ and the window closed, the
    loop parks on the doorbell up to ``idle_wait_s`` and runs
    ``Scheduler.idle_tick`` (queue maintenance only — backoff and
    unschedulable flushes, which themselves ring the bell when they move
    pods) on each timeout, so an idle cluster costs ~2 wakeups/second
    instead of one full solve-path poll per ``--cycle-interval``."""

    def __init__(
        self,
        sched,
        doorbell,
        config=None,
        on_cycle: Optional[Callable] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        if config is None:
            from kubernetes_tpu.config import ServingConfig

            config = ServingConfig()
        self.sched = sched
        self.bell = doorbell
        self.config = config
        #: injectable for fake-clock tests (the window's flush decisions
        #: ride it); the DOORBELL waits stay real-time — a fake-clock
        #: caller drives run_once directly instead of blocking in run()
        self.clock = clock
        self.window = MicroBatchWindow(
            clock=self.clock,
            min_wait_s=config.min_wait_s,
            max_wait_s=config.max_wait_s,
            target_bucket=min(config.target_bucket,
                              getattr(sched, "max_batch", config.target_bucket)),
        )
        # shape discipline under floods: the window decides WHEN to
        # flush, but schedule_cycle pops up to max_batch — an overload
        # burst would otherwise present one giant unwarmed bucket and
        # retrace on the hot path. Clamp pops to the warmed accumulation
        # target; the residue stays in activeQ and re-flushes
        # immediately (depth >= target is a bucket-fill).
        if getattr(sched, "max_batch", None) is not None:
            sched.max_batch = min(sched.max_batch,
                                  self.window.target_bucket)
        #: per-flush callback (bench/tests): receives the CycleResult
        self.on_cycle = on_cycle
        #: per-iteration maintenance hook run by :meth:`run` BETWEEN
        #: run_once iterations (never mid-cycle): the composed runtime
        #: parks its low-frequency state-conservation audit here so it
        #: survives benches overwriting ``on_cycle``
        self.maintenance: Optional[Callable[[], None]] = None
        self.cycles = 0
        #: serializes the solve against cross-thread event feeds: the
        #: scheduler's queue/cache are single-writer structures, so an
        #: informer pump (or a bench producer) running on another thread
        #: must ingest through this lock (use :meth:`ingest`). Doorbell
        #: waits happen OUTSIDE it — feeding never blocks on a solve's
        #: wall time only on its critical sections. Built through the
        #: scheduler's lock sanitizer when one is armed: this is the
        #: outermost lock in the serving stack, exactly where a
        #: cross-class ordering inversion would close a deadlock cycle.
        san = getattr(sched, "lock_sanitizer", None)
        self.lock = (san.make_lock("serving.loop", "rlock")
                     if san is not None else threading.RLock())

    def ingest(self, fn, *args, **kwargs):
        """Run an event-feed callable (scheduler.on_pod_add, ...) under
        the loop's ingest lock — the thread-safe seam for producers
        living on other threads."""
        with self.lock:
            return fn(*args, **kwargs)

    def _depth(self) -> int:
        return self.sched.queue.pending_counts()["active"]

    def run_once(self):
        """One wait/decide/flush iteration; returns the CycleResult when
        a cycle ran, else None. Bounded blocking (<= idle_wait_s)."""
        depth = self._depth()
        if depth == 0 and not self.window.open:
            if not self.bell.wait(self.config.idle_wait_s):
                # clean timeout: queue maintenance so parked backoff /
                # unschedulable pods still resurface; any pod it moves
                # rings the bell and the next iteration schedules it
                with self.lock:
                    self.sched.idle_tick()
            return None
        dec = self.window.observe(depth)
        if not dec.flush:
            self.bell.wait(dec.wait_s)
            return None
        window_s = self.window.close()
        with self.lock:
            res = self.sched.schedule_cycle(
                flush_trigger=dec.trigger, window_s=window_s)
        self.cycles += 1
        m = getattr(self.sched, "metrics", None)
        if m is not None:
            m.microbatch_flushes.inc(trigger=dec.trigger)
            m.microbatch_window.observe(window_s)
        if self.on_cycle is not None:
            self.on_cycle(res)
        return res

    def run(self, stop, gate: Optional[Callable[[], bool]] = None) -> None:
        """Serve until ``stop`` (threading.Event) is set. ``gate`` is
        the per-iteration admission hook (leader election + lazy warmup
        in cli.run): returning False skips this iteration — the gate is
        expected to pace itself (e.g. stop.wait(retry_period))."""
        while not stop.is_set():
            if gate is not None and not gate():
                continue
            self.run_once()
            if self.maintenance is not None:
                self.maintenance()
