"""API-priority-and-fairness-style load shedding + watch fan-out
hardening for the REST facades.

The reference apiserver's APF layer (staging/.../flowcontrol: FlowSchema
matches requests into priority levels, each with a concurrency limit and
bounded per-level queues; overload answers 429 with Retry-After) exists
so one noisy client class cannot starve the rest, and so overload
degrades by SHEDDING instead of by queue collapse. This module is the
capability analog at this framework's scale:

- :class:`FlowSchema` — one request class (name, seat count, bounded
  FIFO queue, queue timeout). The default schemas split traffic the way
  the reference's mandatory flow schemas do: ``exempt`` (health/metrics/
  debug — never queued), ``watch``, ``readonly``, ``mutating``.
- :class:`FlowController` — classify + admit/release. A request beyond
  the seat limit waits in the flow's bounded FIFO; a full queue or a
  blown queue-timeout raises :class:`RequestRejected` (the 429 +
  Retry-After answer). A flow may also carry a SATURATION probe (e.g.
  the scheduler's pending-pod depth): admission sheds mutating traffic
  while the backend is drowning, which is what keeps "no unbounded
  queue growth" true under a 4x-overload churn storm.
- :class:`WatchHub` — bounded-buffer watch fan-out. Each watcher owns a
  bounded send buffer; a publisher NEVER blocks on a slow consumer —
  when a watcher's buffer fills, the watcher is marked gone (its next
  poll raises :class:`WatcherGone`, the 410-relist signal) instead of
  stalling the hub for everyone else.

Everything is thread-safe and lock-scoped small; queue waits ride real
time (these are real HTTP handler threads), but every shed path is
reachable with ``queue_timeout_s=0`` so tests stay sleep-free.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


class RequestRejected(Exception):
    """Admission refused — answer 429 TooManyRequests + Retry-After."""

    def __init__(self, flow: str, reason: str, retry_after_s: float) -> None:
        super().__init__(
            f"too many requests in flight for flow {flow!r} ({reason}); "
            f"retry after {retry_after_s:g}s")
        self.flow = flow
        self.reason = reason
        self.retry_after_s = retry_after_s


@dataclass(frozen=True)
class FlowSchema:
    """One request class: seats + a bounded FIFO of waiters."""

    name: str
    #: concurrent requests admitted (the priority level's seat count)
    concurrency: int = 16
    #: waiters held beyond the seats; the queue bound that turns
    #: overload into 429s instead of unbounded handler-thread pileup
    queue_length: int = 64
    #: longest a queued request waits for a seat before shedding
    queue_timeout_s: float = 1.0
    #: exempt flows (health/metrics/debug) bypass seats entirely —
    #: the probes that diagnose an overload must survive it
    exempt: bool = False


def default_flows(concurrency: int = 16, queue_length: int = 64,
                  watch_concurrency: int = 8,
                  queue_timeout_s: float = 1.0) -> List[FlowSchema]:
    """The mandatory-flow-schema analog: split watch fan-out from
    reads from writes so none can starve the others."""
    return [
        FlowSchema("exempt", exempt=True),
        FlowSchema("watch", concurrency=watch_concurrency,
                   queue_length=max(queue_length // 4, 1),
                   queue_timeout_s=queue_timeout_s),
        FlowSchema("readonly", concurrency=concurrency,
                   queue_length=queue_length,
                   queue_timeout_s=queue_timeout_s),
        FlowSchema("mutating", concurrency=concurrency,
                   queue_length=queue_length,
                   queue_timeout_s=queue_timeout_s),
    ]


#: paths that classify exempt regardless of verb
_EXEMPT_PREFIXES = ("/healthz", "/metrics", "/version", "/debug/")


class _FlowState:
    __slots__ = ("schema", "inflight", "queue", "saturation_fn",
                 "max_saturation")

    def __init__(self, schema: FlowSchema) -> None:
        self.schema = schema
        self.inflight = 0
        self.queue: deque = deque()  # ticket ids, FIFO
        #: optional backend-pressure probe: admission sheds when
        #: saturation_fn() > max_saturation (e.g. scheduler queue depth)
        self.saturation_fn: Optional[Callable[[], float]] = None
        self.max_saturation: float = 0.0


class FlowController:
    """Classify + admit/release with per-flow seats and bounded FIFO
    queues; rejection carries the Retry-After the facade should send."""

    def __init__(self, flows: Optional[List[FlowSchema]] = None,
                 retry_after_s: float = 1.0, metrics=None) -> None:
        self._cond = threading.Condition()
        self._seq = itertools.count()
        self.retry_after_s = retry_after_s
        self.metrics = metrics
        self._flows: Dict[str, _FlowState] = {}
        for fs in (flows if flows is not None else default_flows()):
            self._flows[fs.name] = _FlowState(fs)
        # counters (exposed via stats(); also mirrored to metrics when
        # a SchedulerMetrics is attached)
        self.admitted: Dict[str, int] = {}
        self.rejected: Dict[str, int] = {}  # key "flow/reason"
        self.queued_total = 0

    # -- classification ------------------------------------------------------

    @staticmethod
    def classify(http_verb: str, path: str) -> str:
        """Request -> flow name, the FlowSchema-matching step. Watch is
        split out positionally (the RequestInfo rule: 'watch' right
        after the version prefix); exempt prefixes cover the probes."""
        p = path.split("?", 1)[0]
        if p.startswith(_EXEMPT_PREFIXES) or p in ("/api", "/apis",
                                                   "/openapi/v2"):
            return "exempt"
        parts = [s for s in p.split("/") if s]
        # "watch" counts only POSITIONALLY, right after the version
        # prefix (the RequestInfo rule) — a namespace or pod literally
        # named "watch" stays in its verb's flow
        if ((parts[:2] == ["api", "v1"] and parts[2:3] == ["watch"])
                or (parts[:1] == ["apis"] and parts[3:4] == ["watch"])):
            return "watch"
        return "readonly" if http_verb in ("GET", "HEAD") else "mutating"

    # -- saturation wiring ---------------------------------------------------

    def set_saturation(self, flow: str, fn: Callable[[], float],
                       maximum: float) -> None:
        """Attach a backend-pressure probe to a flow: admission sheds
        with 429 while ``fn() > maximum``. This is how the mutating flow
        is tied to the scheduler's pending-pod depth — the bounded-queue
        guarantee under sustained overload."""
        with self._cond:
            st = self._flows[flow]
            st.saturation_fn = fn
            st.max_saturation = float(maximum)

    # -- admit / release -----------------------------------------------------

    def _reject(self, flow: str, reason: str) -> RequestRejected:
        key = f"{flow}/{reason}"
        self.rejected[key] = self.rejected.get(key, 0) + 1
        if self.metrics is not None:
            self.metrics.apf_rejected.inc(flow=flow, reason=reason)
        return RequestRejected(flow, reason, self.retry_after_s)

    def acquire(self, flow: str) -> str:
        """Take a seat in ``flow`` (blocking in its bounded FIFO if the
        seats are full); raises :class:`RequestRejected` on overload.
        Returns the flow name to pass back to :meth:`release`."""
        with self._cond:
            st = self._flows.get(flow)
            if st is None or st.schema.exempt:
                # an unconfigured flow name admits unmetered (matching
                # release's no-op) rather than borrowing another flow's
                # seats — misclassification must never deadlock a seat
                self.admitted[flow] = self.admitted.get(flow, 0) + 1
                return flow
            flow = st.schema.name
            if (st.saturation_fn is not None
                    and st.saturation_fn() > st.max_saturation):
                raise self._reject(flow, "saturated")
            if st.inflight < st.schema.concurrency and not st.queue:
                st.inflight += 1
                self._admitted(flow, st)
                return flow
            if len(st.queue) >= st.schema.queue_length:
                raise self._reject(flow, "queue-full")
            ticket = next(self._seq)
            st.queue.append(ticket)
            self.queued_total += 1
            deadline = time.monotonic() + st.schema.queue_timeout_s
            while True:
                if st.queue and st.queue[0] == ticket \
                        and st.inflight < st.schema.concurrency:
                    st.queue.popleft()
                    st.inflight += 1
                    self._admitted(flow, st)
                    # the next waiter may also have a free seat
                    self._cond.notify_all()
                    return flow
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    try:
                        st.queue.remove(ticket)
                    except ValueError:
                        pass
                    raise self._reject(flow, "timeout")
                self._cond.wait(remaining)

    def _admitted(self, flow: str, st: _FlowState) -> None:
        self.admitted[flow] = self.admitted.get(flow, 0) + 1
        if self.metrics is not None:
            self.metrics.apf_inflight.set(st.inflight, flow=flow)

    def release(self, flow: str) -> None:
        with self._cond:
            st = self._flows.get(flow)
            if st is None or st.schema.exempt:
                return
            st.inflight = max(st.inflight - 1, 0)
            if self.metrics is not None:
                self.metrics.apf_inflight.set(st.inflight, flow=flow)
            self._cond.notify_all()

    def admit(self, flow: str):
        """Context-manager form: ``with ctrl.admit(flow): handle()``."""
        ctrl = self

        class _Seat:
            def __enter__(self_s):
                self_s.flow = ctrl.acquire(flow)
                return self_s

            def __exit__(self_s, *exc):
                ctrl.release(self_s.flow)
                return False

        return _Seat()

    def stats(self) -> dict:
        with self._cond:
            return {
                "admitted": dict(self.admitted),
                "rejected": dict(self.rejected),
                "queued_total": self.queued_total,
                "inflight": {name: st.inflight
                             for name, st in self._flows.items()},
            }


# ---------------------------------------------------------------------------
# watch fan-out hardening
# ---------------------------------------------------------------------------


class WatcherGone(Exception):
    """This watcher fell too far behind and was disconnected — the
    410-Gone / relist signal (cacher.go's terminateAllWatchers answer to
    a blocked send buffer)."""


class Watcher:
    """One consumer's bounded send buffer on a :class:`WatchHub`."""

    __slots__ = ("_hub", "buf", "gone", "gone_reason", "dropped",
                 "delivered")

    def __init__(self, hub: "WatchHub") -> None:
        self._hub = hub
        self.buf: deque = deque()
        self.gone = False
        #: why the hub cut this watcher loose ("" while live) — carried
        #: into the WatcherGone message so the 410 answer names the
        #: right relist cause (buffer overflow vs. takeover relist)
        self.gone_reason = ""
        #: buffered-but-never-delivered events discarded at eviction —
        #: the accounting that makes the drop VISIBLE (it used to
        #: vanish: eviction cleared the buffer and counted nothing)
        self.dropped = 0
        self.delivered = 0

    def poll(self) -> list:
        """Drain buffered events; raises :class:`WatcherGone` once the
        hub evicted this watcher (consumer must relist + re-register).
        The raise is sticky: EVERY poll after eviction raises — an
        eviction racing a concurrent drain can therefore never read as
        a clean empty stream."""
        with self._hub._lock:
            if self.gone:
                reason = self.gone_reason or (
                    f"send buffer overflowed (bound {self._hub.buffer})")
                raise WatcherGone(
                    f"watcher evicted: {reason} "
                    f"({self.dropped} buffered events dropped); "
                    "relist and re-watch")
            out = list(self.buf)
            self.buf.clear()
            self.delivered += len(out)
            return out

    def lag(self) -> int:
        with self._hub._lock:
            return len(self.buf)

    def close(self) -> None:
        self._hub.unregister(self)


class WatchHub:
    """Bounded-buffer event fan-out: publish never blocks, slow
    watchers are evicted (Gone) instead of stalling the publisher."""

    def __init__(self, buffer: int = 1024, metrics=None) -> None:
        self.buffer = max(1, int(buffer))
        self.metrics = metrics
        self._lock = threading.Lock()
        self._watchers: List[Watcher] = []
        self.published = 0
        self.evicted = 0
        #: buffered events discarded by evictions (accounting for what
        #: eviction drops — the relist covers the GAP, but the hub must
        #: still know how much it threw away)
        self.events_dropped = 0
        self.max_lag = 0

    def register(self) -> Watcher:
        w = Watcher(self)
        with self._lock:
            self._watchers.append(w)
        return w

    def unregister(self, w: Watcher) -> None:
        with self._lock:
            try:
                self._watchers.remove(w)
            except ValueError:
                pass

    def _evict_locked(self, w: Watcher, reason: str) -> None:
        """Cut one watcher loose (callers hold ``_lock``): sticky Gone
        with the reason the 410 should carry, dropped-event accounting
        instead of a silent clear."""
        w.gone = True
        w.gone_reason = reason
        w.dropped += len(w.buf)
        self.events_dropped += len(w.buf)
        w.buf.clear()
        self.evicted += 1
        if self.metrics is not None:
            self.metrics.watch_evictions.inc()

    def publish(self, event) -> None:
        with self._lock:
            self.published += 1
            for w in self._watchers:
                if w.gone:
                    continue
                if len(w.buf) >= self.buffer:
                    # the slow watcher is cut loose, never the hub: its
                    # buffer is dropped (counted) and every later poll
                    # gets Gone with the overflow reason
                    self._evict_locked(
                        w, f"send buffer overflowed (bound {self.buffer})")
                    continue
                w.buf.append(event)
                if len(w.buf) > self.max_lag:
                    self.max_lag = len(w.buf)

    def evict_all(self, reason: str) -> int:
        """Evict EVERY live watcher with ``reason`` — the takeover /
        deposition relist broadcast: a leadership change splices two
        write histories, so a watcher that straddles it must relist
        from truth rather than trust its buffered tail. Each evicted
        watcher's next poll raises :class:`WatcherGone` carrying the
        reason (the 410 + relist-hint answer), never a silent drop —
        and the race with a concurrent in-flight ``poll`` is benign by
        construction: both sides serialize on the hub lock, and the
        Gone flag is sticky, so the watcher either drains first and
        gets Gone on its NEXT poll, or gets Gone immediately.
        Returns how many watchers were evicted."""
        with self._lock:
            n = 0
            for w in self._watchers:
                if w.gone:
                    continue
                self._evict_locked(w, reason)
                n += 1
            return n

    def stats(self) -> dict:
        with self._lock:
            return {
                "watchers": len(self._watchers),
                "published": self.published,
                "evicted": self.evicted,
                "events_dropped": self.events_dropped,
                "max_lag": self.max_lag,
            }
