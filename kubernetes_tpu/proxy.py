"""Service virtual-IP dataplane — the kube-proxy analog (SURVEY §2.2
"kube-proxy: Service VIP dataplane (iptables/ipvs rule compilers)",
reference ``pkg/proxy/iptables/proxier.go:283`` syncProxyRules and the
endpoints controller ``pkg/controller/endpoint/endpoints_controller.go``).

Three pieces, mirroring the reference's split:

- :class:`Service` / :class:`Endpoints` — the API objects (the v1 slice
  the proxy consumes: selector, ports, ClusterIP, NodePort, session
  affinity).
- :class:`EndpointsController` — control-plane reconciler: for every
  service, the ready addresses are the bound, live pods matching the
  selector (endpoints_controller.go syncService: pods from the selector,
  readiness split). Runs in the hub's controller-manager pass.
- :class:`ServiceProxy` — the per-node dataplane. The reference compiles
  the full iptables table from scratch on every sync (proxier.go:283 —
  one giant rule rewrite, versioned by endpoints/service change counts);
  here the analog is a deterministic routing table rebuilt from the
  (services, endpoints) snapshot: per-service backend lists plus a
  ClientIP affinity map with TTL. ``resolve`` implements the iptables
  ``-m statistic --mode random --probability 1/n`` chain as a seeded
  uniform pick, so distribution properties are testable.

The proxy is hollow the same way kubemark's hollow-proxy is (SURVEY §2.2
kubemark row: real proxy logic, fake iptables): the rule table is real
and queryable, no packets move.
"""

from __future__ import annotations

import dataclasses
import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Pod

# ---------------------------------------------------------------------------
# API objects (v1.Service / v1.Endpoints slice)
# ---------------------------------------------------------------------------

AFFINITY_NONE = "None"
AFFINITY_CLIENT_IP = "ClientIP"

#: default ClientIP stickiness window — v1.DefaultClientIPServiceAffinitySeconds
DEFAULT_AFFINITY_SECONDS = 3 * 60 * 60


@dataclass(frozen=True)
class ServicePort:
    """One spec.ports entry: the VIP-side port and the pod-side target."""

    name: str = ""
    port: int = 0
    target_port: int = 0
    protocol: str = "TCP"
    node_port: int = 0  # 0 = not a NodePort service port


@dataclass
class Service:
    name: str
    namespace: str = "default"
    selector: Dict[str, str] = field(default_factory=dict)
    cluster_ip: str = ""  # assigned by the hub on create (apiserver analog)
    ports: Tuple[ServicePort, ...] = ()
    session_affinity: str = AFFINITY_NONE
    affinity_seconds: int = DEFAULT_AFFINITY_SECONDS
    #: spec.type — ClusterIP/NodePort/LoadBalancer; LoadBalancer
    #: additionally gets an external balancer from the service
    #: controller when a cloud is attached (cloud.ServiceLBController)
    type: str = "ClusterIP"
    #: status.loadBalancer.ingress[0], written by the service controller
    load_balancer_ingress: str = ""

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def selects(self, pod: Pod) -> bool:
        if not self.selector or pod.namespace != self.namespace:
            return False
        return all(pod.labels.get(k) == v for k, v in self.selector.items())


@dataclass(frozen=True)
class EndpointAddress:
    """One ready/not-ready address: the pod and where it runs (the slice
    of v1.EndpointAddress the proxy consumes: IP→pod identity, nodeName)."""

    pod_key: str
    node_name: str


@dataclass
class Endpoints:
    """v1.Endpoints, flattened: one subset, ready/not-ready address lists
    (the reference's per-port subsets collapse here because hollow pods
    serve every target port)."""

    name: str
    namespace: str = "default"
    ready: Tuple[EndpointAddress, ...] = ()
    not_ready: Tuple[EndpointAddress, ...] = ()

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


# ---------------------------------------------------------------------------
# Endpoints controller (control plane)
# ---------------------------------------------------------------------------


def pod_endpoint_ready(p) -> bool:
    """The one Endpoints-membership rule (endpoints_controller.go
    shouldPodBeInEndpoints + the Ready-condition check): bound, not
    terminating, and — when a readiness probe exists — probe-ready. A
    probe-less pod is ready as soon as it is placed (the reference's
    status_manager defaults Ready=true with no probes)."""
    from kubernetes_tpu.api.types import is_pod_terminated

    return (bool(p.node_name) and not p.deletion_timestamp
            and not is_pod_terminated(p)
            and (p.readiness_probe is None or p.ready))


class EndpointsController:
    """Reconciles Endpoints objects from (services, pods) truth —
    endpoints_controller.go syncService, driven from the hub's controller
    pass instead of a workqueue: list pods matching the service selector;
    bound + live ⇒ ready, pending/terminating ⇒ not-ready. Writes go
    through the hub so watchers (the per-node proxies) observe ordered
    ADDED/MODIFIED/DELETED endpoint events."""

    def __init__(self, hub) -> None:
        self.hub = hub

    def reconcile(self) -> int:
        """One full pass; returns the number of Endpoints writes."""
        hub = self.hub
        writes = 0
        live_eps = set()
        for svc in list(hub.services.values()):
            if not svc.selector:
                # selector-less service: endpoints are managed manually
                # (the external-backend pattern) — never reconciled, never
                # GC'd while the service lives (endpoints_controller.go
                # syncService returns early on nil selector)
                live_eps.add(svc.key())
                continue
            ready: List[EndpointAddress] = []
            not_ready: List[EndpointAddress] = []
            for p in hub.truth_pods.values():
                if not svc.selects(p):
                    continue
                addr = EndpointAddress(p.key(), p.node_name)
                if pod_endpoint_ready(p):
                    ready.append(addr)
                else:
                    not_ready.append(addr)
            ready.sort(key=lambda a: a.pod_key)
            not_ready.sort(key=lambda a: a.pod_key)
            ep = Endpoints(svc.name, svc.namespace,
                           tuple(ready), tuple(not_ready))
            live_eps.add(ep.key())
            old = hub.endpoints.get(ep.key())
            if old is None or (old.ready, old.not_ready) != (ep.ready,
                                                            ep.not_ready):
                hub.put_endpoints(ep)
                writes += 1
        for key in [k for k in hub.endpoints if k not in live_eps]:
            hub.delete_endpoints(key)
            writes += 1
        return writes


# ---------------------------------------------------------------------------
# Per-node proxy (dataplane)
# ---------------------------------------------------------------------------


@dataclass
class _Rule:
    """Compiled routing entry for one service port: the analog of that
    port's iptables KUBE-SVC-* chain."""

    service: str  # service key
    port: ServicePort
    backends: Tuple[EndpointAddress, ...]  # ready only, sorted
    session_affinity: str = AFFINITY_NONE
    affinity_seconds: int = DEFAULT_AFFINITY_SECONDS


class ServiceProxy:
    """One node's compiled service table. ``sync`` is the
    syncProxyRules analog: a full deterministic rebuild from the current
    (services, endpoints) snapshot — the reference never patches rules
    incrementally and neither does this. ``resolve`` is the packet path:
    VIP:port (or node port) + client → backend pod."""

    def __init__(self, node_name: str, clock=None) -> None:
        self.node_name = node_name
        self.clock = clock
        #: (cluster_ip, port) -> rule ; rebuilt wholesale by sync()
        self.vip_rules: Dict[Tuple[str, int], _Rule] = {}
        #: node_port -> rule
        self.node_port_rules: Dict[int, _Rule] = {}
        #: ClientIP affinity: (service, port, client) -> (pod_key, stamp)
        self._affinity: Dict[Tuple[str, int, str], Tuple[str, float]] = {}
        self.sync_count = 0

    def _now(self) -> float:
        return self.clock.t if self.clock is not None else 0.0

    def sync(self, services: Dict[str, Service],
             endpoints: Dict[str, Endpoints]) -> None:
        vip: Dict[Tuple[str, int], _Rule] = {}
        nps: Dict[int, _Rule] = {}
        for key, svc in services.items():
            ep = endpoints.get(key)
            backends = ep.ready if ep is not None else ()
            for sp in svc.ports:
                rule = _Rule(key, sp, backends, svc.session_affinity,
                             svc.affinity_seconds)
                if svc.cluster_ip:
                    vip[(svc.cluster_ip, sp.port)] = rule
                if sp.node_port:
                    nps[sp.node_port] = rule
        self.vip_rules = vip
        self.node_port_rules = nps
        # drop affinity entries whose service vanished (iptables flush of
        # the KUBE-SEP recent-match lists)
        live = {r.service for r in vip.values()}
        self._affinity = {k: v for k, v in self._affinity.items()
                          if k[0] in live}
        self.sync_count += 1

    # -- packet path -------------------------------------------------------

    def resolve(self, cluster_ip: str, port: int,
                client: str = "") -> Optional[EndpointAddress]:
        """Route VIP:port from ``client`` to a backend; None ⇒ no ready
        endpoints (the reference REJECTs with ICMP port unreachable)."""
        rule = self.vip_rules.get((cluster_ip, port))
        return self._pick(rule, client)

    def resolve_node_port(self, node_port: int,
                          client: str = "") -> Optional[EndpointAddress]:
        rule = self.node_port_rules.get(node_port)
        return self._pick(rule, client)

    def _pick(self, rule: Optional[_Rule],
              client: str) -> Optional[EndpointAddress]:
        if rule is None or not rule.backends:
            return None
        if rule.session_affinity == AFFINITY_CLIENT_IP and client:
            akey = (rule.service, rule.port.port, client)
            hit = self._affinity.get(akey)
            if hit is not None:
                pod_key, stamp = hit
                if self._now() - stamp <= rule.affinity_seconds:
                    for b in rule.backends:
                        if b.pod_key == pod_key:  # still ready?
                            self._affinity[akey] = (pod_key, self._now())
                            return b
                del self._affinity[akey]
        choice = rule.backends[self._uniform(rule, client)
                               % len(rule.backends)]
        if rule.session_affinity == AFFINITY_CLIENT_IP and client:
            self._affinity[(rule.service, rule.port.port, client)] = (
                choice.pod_key, self._now())
        return choice

    def _uniform(self, rule: _Rule, client: str) -> int:
        """Deterministic stand-in for the iptables statistic-random match:
        uniform over backends, independent across (node, service, port,
        client) — hash, not RNG, so tests can assert exact spread."""
        h = hashlib.blake2b(
            f"{self.node_name}|{rule.service}|{rule.port.port}|{client}"
            .encode(), digest_size=8)
        return int.from_bytes(h.digest(), "big")


# ---------------------------------------------------------------------------
# ClusterIP allocation (apiserver service-ip allocator analog)
# ---------------------------------------------------------------------------


class _RangeAllocator:
    """The one sequential integer-range allocator both service
    allocators ride (the reference's shared
    ``pkg/registry/core/service/allocator`` bitmap): unique values,
    wrap-scan allocate, conflict-checked reservation, release with
    revisit, exhaustion error."""

    def __init__(self, lo: int, hi: int, what: str) -> None:
        self.lo, self.hi = lo, hi
        self.what = what
        self._used: set = set()
        self._next = lo

    def allocate(self) -> int:
        n = self._next if self.lo <= self._next <= self.hi else self.lo
        for _ in range(self.hi - self.lo + 1):
            if n not in self._used:
                self._used.add(n)
                self._next = n + 1
                return n
            n = n + 1 if n < self.hi else self.lo
        raise RuntimeError(f"{self.what} exhausted")

    def reserve(self, n: int) -> None:
        """Claim a caller-chosen value; a DUPLICATE claim raises — the
        apiserver 422s 'provided port is already allocated' instead of
        silently sharing (silent sharing also corrupts release: the
        first delete would free the slot under the survivor)."""
        if not (self.lo <= n <= self.hi):
            return
        if n in self._used:
            raise ValueError(f"provided {self.what} {n} is already "
                             "allocated")
        self._used.add(n)

    def release(self, n: int) -> None:
        self._used.discard(n)
        if self.lo <= n <= self.hi:
            self._next = min(self._next, n)  # released slots revisited


class NodePortAllocator(_RangeAllocator):
    """Service node-port range
    (``pkg/registry/core/service/portallocator``; default 30000-32767)."""

    def __init__(self, lo: int = 30000, hi: int = 32767) -> None:
        super().__init__(lo, hi, "node-port range")


class ClusterIPAllocator:
    """Sequential allocator over a /16 service CIDR — the slice of
    ``pkg/registry/core/service/ipallocator`` the hub needs: unique IPs,
    release on delete, exhaustion error. Rides :class:`_RangeAllocator`
    with the IP-string encoding on top."""

    def __init__(self, prefix: str = "10.96") -> None:
        self.prefix = prefix
        self._core = _RangeAllocator(1, 65534, "service CIDR")

    def _decode(self, ip: str) -> Optional[int]:
        parts = ip.split(".")
        if len(parts) == 4 and f"{parts[0]}.{parts[1]}" == self.prefix:
            return (int(parts[2]) << 8) | int(parts[3])
        return None

    def allocate(self) -> str:
        n = self._core.allocate()
        return f"{self.prefix}.{n >> 8}.{n & 0xFF}"

    def reserve(self, ip: str) -> None:
        """Mark a caller-chosen VIP used (the apiserver honors an
        explicit spec.clusterIP by reserving it in the allocator
        bitmap). Unlike node ports, a repeat reservation of the SAME
        VIP is tolerated here: checkpoint restore and same-IP
        re-creates re-reserve legitimately (the reference repairs the
        bitmap from stored services on startup)."""
        n = self._decode(ip)
        if n is not None:
            self._core._used.add(n)

    def release(self, ip: str) -> None:
        n = self._decode(ip)
        if n is not None:
            self._core.release(n)
