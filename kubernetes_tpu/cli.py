"""Executable entry point — the analog of ``cmd/kube-scheduler``
(``scheduler.go:33`` main → ``app/server.go:65`` NewSchedulerCommand →
``:161`` Run): flags → ComponentConfig file decode → validation → healthz/
metrics server → leader election → the scheduling loop.

    python -m kubernetes_tpu --config scheduler.yaml
    python -m kubernetes_tpu --validate-only --config scheduler.yaml

The config file is the ``KubeSchedulerConfiguration`` in YAML or JSON
(apis/config/types.go:43 field meanings) in one of two formats:
``apiVersion: kubescheduler.config.k8s.io/v1alpha1``-tagged files use
the VERSIONED wire spelling (camelCase keys, duration strings, v1alpha1
defaulting — decoded through the api.scheme pipeline); untagged files
use this implementation's native snake_case spelling. Flags override
file values the way the reference's options layer overlays the decoded
object (app/options/options.go). Invalid configs are rejected with
field-path errors like ``apis/config/validation`` does.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import signal
import sys
import time
from typing import List, Optional

from kubernetes_tpu.config import (
    DEFAULT_FEATURE_GATES,
    FeatureGates,
    IncidentsConfig,
    IncrementalConfig,
    JourneysConfig,
    KubeSchedulerConfiguration,
    LeaderElectionConfig,
    LedgerConfig,
    MemoryLedgerConfig,
    ObservabilityConfig,
    ParallelConfig,
    RecoveryConfig,
    RobustnessConfig,
    ScenarioConfig,
    ServingConfig,
    WarmupConfig,
    load_policy,
)

VALID_SOLVERS = ("batch", "greedy", "exact", "sinkhorn")

#: component-base leader-election jitter factor (leaderelection.go:56) —
#: renewDeadline must exceed retryPeriod * JitterFactor
JITTER_FACTOR = 1.2


class ConfigError(ValueError):
    """Decode/validation failure; ``errors`` lists field-path messages."""

    def __init__(self, errors: List[str]):
        super().__init__("; ".join(errors))
        self.errors = errors


def validate_config(cfg: KubeSchedulerConfiguration) -> List[str]:
    """ValidateKubeSchedulerConfiguration (apis/config/validation/
    validation.go:27) plus checks for this implementation's solver block.
    Returns field-path error strings; empty = valid."""
    errs: List[str] = []
    if not cfg.scheduler_name:
        errs.append("schedulerName: Required value")
    if not 0 <= cfg.hard_pod_affinity_symmetric_weight <= 100:
        errs.append(
            f"hardPodAffinitySymmetricWeight: Invalid value "
            f"{cfg.hard_pod_affinity_symmetric_weight}: not in valid range 0-100"
        )
    if not 0 <= cfg.percentage_of_nodes_to_score <= 100:
        errs.append(
            f"percentageOfNodesToScore: Invalid value "
            f"{cfg.percentage_of_nodes_to_score}: not in valid range 0-100"
        )
    if cfg.bind_timeout_seconds is None or cfg.bind_timeout_seconds < 0:
        errs.append("bindTimeoutSeconds: Required value")
    le = cfg.leader_election
    if le.leader_elect:  # validated only when enabled (validation.go:57-59)
        if le.lease_duration_s <= 0:
            errs.append("leaderElection.leaseDuration: must be greater than zero")
        if le.renew_deadline_s <= 0:
            errs.append("leaderElection.renewDeadline: must be greater than zero")
        if le.retry_period_s <= 0:
            errs.append("leaderElection.retryPeriod: must be greater than zero")
        if le.lease_duration_s <= le.renew_deadline_s:
            errs.append(
                "leaderElection.leaseDuration: must be greater than renewDeadline"
            )
        if le.renew_deadline_s <= JITTER_FACTOR * le.retry_period_s:
            errs.append(
                "leaderElection.renewDeadline: must be greater than "
                f"retryPeriod*JitterFactor ({JITTER_FACTOR})"
            )
        if not le.lock_object_namespace:
            errs.append("leaderElection.lockObjectNamespace: Required value")
        if not le.lock_object_name:
            errs.append("leaderElection.lockObjectName: Required value")
    # solver block (no reference analog; this implementation's tuning)
    if cfg.solver not in VALID_SOLVERS:
        errs.append(
            f"solver: Unsupported value {cfg.solver!r}: "
            f"supported values: {', '.join(VALID_SOLVERS)}"
        )
    if cfg.per_node_cap < 1:
        errs.append("perNodeCap: must be at least 1")
    if cfg.max_rounds < 1:
        errs.append("maxRounds: must be at least 1")
    if cfg.max_batch < 1:
        errs.append("maxBatch: must be at least 1")
    if cfg.pipeline_depth < 1:
        errs.append("pipelineDepth: must be at least 1")
    if cfg.pipeline_chunk < 1:
        errs.append("pipelineChunk: must be at least 1")
    if not 0 <= cfg.snapshot_max_dirty_frac <= 1:
        errs.append(
            f"snapshotMaxDirtyFrac: Invalid value "
            f"{cfg.snapshot_max_dirty_frac}: not in valid range 0-1"
        )
    wu = cfg.warmup
    if wu.min_bucket < 1:
        errs.append("warmup.minBucket: must be at least 1")
    if any(b < 1 for b in wu.pod_buckets):
        errs.append("warmup.podBuckets: buckets must be at least 1")
    inc = cfg.incremental
    if inc.candidate_bucket < 1:
        errs.append("incremental.candidateBucket: must be at least 1")
    if not 0 < inc.max_batch_frac <= 1:
        errs.append(
            f"incremental.maxBatchFrac: Invalid value {inc.max_batch_frac}: "
            "not in valid range (0, 1]"
        )
    if not 0 <= inc.max_dirty_frac <= 1:
        errs.append(
            f"incremental.maxDirtyFrac: Invalid value {inc.max_dirty_frac}: "
            "not in valid range 0-1"
        )
    if inc.warm_tol <= 0:
        errs.append("incremental.warmTol: must be greater than zero")
    if inc.quality_delta < 0:
        errs.append("incremental.qualityDelta: must be non-negative")
    if inc.cold_blocks < 0:
        errs.append("incremental.coldBlocks: must be non-negative "
                    "(0 selects the automatic block count)")
    if not 0 < inc.group_quota_frac <= 1:
        errs.append(
            f"incremental.groupQuotaFrac: Invalid value "
            f"{inc.group_quota_frac}: not in valid range (0, 1]")
    if inc.primary and not inc.enabled:
        errs.append("incremental.primary: requires incremental.enabled "
                    "(the sparsity-first route rides the score cache)")
    rc = cfg.robustness
    if rc.cycle_deadline_s < 0:
        errs.append("robustness.cycleDeadlineSeconds: must be non-negative")
    if rc.solver_retries < 0 or rc.transport_retries < 0:
        errs.append("robustness.retries: must be non-negative")
    if rc.retry_backoff_base_s < 0 or rc.retry_backoff_max_s < 0:
        errs.append("robustness.retryBackoff: must be non-negative")
    if not 0 <= rc.retry_jitter <= 1:
        errs.append(
            f"robustness.retryJitter: Invalid value {rc.retry_jitter}: "
            "not in valid range 0-1"
        )
    if rc.bind_verify_retries < 0:
        errs.append("robustness.bindVerifyRetries: must be non-negative")
    if rc.watch_progress_deadline_s < 0:
        errs.append("robustness.watchProgressDeadline: must be "
                    "non-negative (0 = stall detection off)")
    if rc.breaker_failure_threshold < 1:
        errs.append("robustness.breakerFailureThreshold: must be at least 1")
    if rc.breaker_half_open_probes < 1:
        errs.append("robustness.breakerHalfOpenProbes: must be at least 1")
    bad_tiers = [t for t in rc.fallback_chain
                 if t not in VALID_SOLVERS + ("batch-cpu",)]
    if bad_tiers:
        errs.append(
            f"robustness.fallbackChain: unsupported tier(s) {bad_tiers}: "
            f"supported: {', '.join(VALID_SOLVERS + ('batch-cpu',))}"
        )
    rv = cfg.recovery
    if rv.device_reset_limit < 0:
        errs.append("recovery.deviceResetLimit: must be non-negative")
    if rv.device_cooloff_s < 0:
        errs.append("recovery.deviceCooloff: must be non-negative")
    oc = cfg.observability
    if oc.trace_threshold_s < 0:
        errs.append("observability.traceThreshold: must be non-negative")
    if not 0 <= oc.trace_sampling <= 1:
        errs.append(
            f"observability.traceSampling: Invalid value {oc.trace_sampling}: "
            "not in valid range 0-1"
        )
    if oc.recorder_capacity < 1:
        errs.append("observability.recorderCapacity: must be at least 1")
    if oc.trace_ring_capacity < 1:
        errs.append("observability.traceRingCapacity: must be at least 1")
    if oc.retrace_storm_threshold < 1:
        errs.append("observability.retraceStormThreshold: must be at least 1")
    if oc.retrace_storm_window < 1:
        errs.append("observability.retraceStormWindow: must be at least 1")
    if oc.explain_top_k < 1:
        errs.append("observability.explainTopK: must be at least 1")
    if oc.audit_interval_s < 0:
        errs.append("observability.auditInterval: must be non-negative "
                    "(0 = the serving runtime's auditor off)")
    lg = oc.ledger
    if lg.history < 1:
        errs.append("observability.ledger.history: must be at least 1")
    if lg.dist_window < 1:
        errs.append("observability.ledger.distWindow: must be at least 1")
    if not 0 < lg.baseline_decay <= 1:
        errs.append(
            f"observability.ledger.baselineDecay: Invalid value "
            f"{lg.baseline_decay}: not in valid range (0, 1]")
    if lg.e2e_p99_objective_s < 0:
        errs.append(
            "observability.ledger.e2eP99Objective: must be non-negative "
            "(0 = objective off)")
    if lg.cost_drift_ratio < 0:
        errs.append(
            "observability.ledger.costDriftRatio: must be non-negative "
            "(0 = objective off)")
    if lg.fast_window_s <= 0:
        errs.append(
            "observability.ledger.fastWindow: must be greater than zero")
    if lg.slow_window_s < lg.fast_window_s:
        errs.append(
            "observability.ledger.slowWindow: must be at least fastWindow")
    if lg.burn_threshold <= 0:
        errs.append(
            "observability.ledger.burnThreshold: must be greater than zero")
    mlg = oc.memory_ledger
    if mlg.sample_interval_s < 0:
        errs.append(
            "observability.memoryLedger.sampleInterval: must be "
            "non-negative (0 = sample every cycle boundary)")
    if not 0 < mlg.headroom_frac <= 1:
        errs.append(
            f"observability.memoryLedger.headroomFrac: Invalid value "
            f"{mlg.headroom_frac}: not in valid range (0, 1]")
    if mlg.limit_bytes < 0:
        errs.append(
            "observability.memoryLedger.limitBytes: must be non-negative "
            "(0 = use the device-reported limit)")
    if mlg.history < 1:
        errs.append(
            "observability.memoryLedger.history: must be at least 1")
    if mlg.census_limit < 1:
        errs.append(
            "observability.memoryLedger.censusLimit: must be at least 1")
    jc = oc.journeys
    if jc.slow_k < 1:
        errs.append("observability.journeys.slowK: must be at least 1")
    if jc.sample_every < 0:
        errs.append(
            "observability.journeys.sampleEvery: must be non-negative "
            "(0 = completion sampling off)")
    if jc.window_s <= 0:
        errs.append(
            "observability.journeys.window: must be greater than zero")
    if jc.max_pending < 1:
        errs.append(
            "observability.journeys.maxPending: must be at least 1")
    if jc.max_events < 2:
        errs.append(
            "observability.journeys.maxEvents: must be at least 2")
    ic = oc.incidents
    if ic.capacity < 1:
        errs.append("observability.incidents.capacity: must be at least 1")
    if ic.flight_window < 0:
        errs.append(
            "observability.incidents.flightWindow: must be non-negative")
    if ic.journeys_k < 0:
        errs.append(
            "observability.incidents.journeysK: must be non-negative")
    if ic.cooldown_cycles < 0:
        errs.append(
            "observability.incidents.cooldownCycles: must be non-negative")
    if ic.fallback_burst_threshold < 0:
        errs.append(
            "observability.incidents.fallbackBurstThreshold: must be "
            "non-negative (0 = trigger off)")
    if ic.profile_cycles < 0:
        errs.append(
            "observability.incidents.profileCycles: must be non-negative "
            "(0 = incident-armed profiling off)")
    if ic.max_profiles < 0:
        errs.append(
            "observability.incidents.maxProfiles: must be non-negative")
    ls = oc.lock_sanitizer
    if ls.hold_budget_s < 0:
        errs.append(
            "observability.lockSanitizer.holdBudget: must be non-negative "
            "(0 = hold check off)")
    if ls.max_findings < 1:
        errs.append(
            "observability.lockSanitizer.maxFindings: must be at least 1")
    sc = cfg.serving
    if sc.min_wait_s < 0:
        errs.append("serving.minWait: must be non-negative")
    if sc.max_wait_s < sc.min_wait_s:
        errs.append("serving.maxWait: must be at least minWait")
    if sc.target_bucket < 1:
        errs.append("serving.targetBucket: must be at least 1")
    if sc.idle_wait_s <= 0:
        errs.append("serving.idleWait: must be greater than zero")
    if sc.flow_concurrency < 1:
        errs.append("serving.flowConcurrency: must be at least 1")
    if sc.watch_concurrency < 1:
        errs.append("serving.watchConcurrency: must be at least 1")
    if sc.flow_queue_length < 0:
        errs.append("serving.flowQueueLength: must be non-negative")
    if sc.queue_timeout_s < 0:
        errs.append("serving.queueTimeout: must be non-negative")
    if sc.retry_after_s <= 0:
        errs.append("serving.retryAfter: must be greater than zero")
    if sc.watch_buffer < 1:
        errs.append("serving.watchBuffer: must be at least 1")
    if sc.shed_queue_bound < 0:
        errs.append("serving.shedQueueBound: must be non-negative "
                    "(0 = auto: twice the accumulation target)")
    if sc.degraded_pressure_factor < 1:
        errs.append("serving.degradedPressureFactor: must be at least 1")
    pl = cfg.parallel
    mesh = pl.mesh
    if isinstance(mesh, bool) or not (
            mesh in ("off", "auto")
            or (isinstance(mesh, int) and mesh >= 1)):
        errs.append(
            f"parallel.mesh: Unsupported value {mesh!r}: supported "
            "values: 'off', 'auto', or a positive device count")
    elif isinstance(mesh, int) and mesh & (mesh - 1):
        # the node axis pads to power-of-two buckets and a divisor of a
        # power of two is a power of two — any other count can never
        # divide a bucket and would fail as an opaque XLA shape error
        # mid-solve (make_mesh's runtime fallback covers odd DISCOVERED
        # device sets; a declared count is rejected up front)
        errs.append(
            f"parallel.mesh: Invalid value {mesh}: a device count must "
            "divide the power-of-two node buckets — use a power of two")
    sn = cfg.scenario
    if sn.pack:
        from kubernetes_tpu.scenarios import SCENARIO_REGISTRY

        if sn.pack not in SCENARIO_REGISTRY:
            errs.append(
                f"scenario.pack: Unsupported value {sn.pack!r}: "
                f"supported values: '', "
                f"{', '.join(sorted(SCENARIO_REGISTRY))}")
    if sn.cost_weight < 0:
        errs.append("scenario.costWeight: must be non-negative")
    if sn.fill_block < 1:
        errs.append("scenario.fillBlock: must be at least 1")
    if sn.cascade_max_pods < 1:
        errs.append("scenario.cascadeMaxPods: must be at least 1")
    if sn.superpod < 1:
        errs.append("scenario.superpod: must be at least 1")
    if sn.repack_interval_s < 0:
        errs.append("scenario.repackInterval: must be non-negative")
    if sn.repack_max_pods < 1:
        errs.append("scenario.repackMaxPods: must be at least 1")
    # unknown feature gates are rejected earlier, at FeatureGates
    # construction (featuregate.Set errors on unknown names)
    return errs


_CONFIG_FIELDS = {f.name for f in dataclasses.fields(KubeSchedulerConfiguration)}
_LE_FIELDS = {f.name for f in dataclasses.fields(LeaderElectionConfig)}
_ROB_FIELDS = {f.name for f in dataclasses.fields(RobustnessConfig)}
_REC_FIELDS = {f.name for f in dataclasses.fields(RecoveryConfig)}
_OBS_FIELDS = {f.name for f in dataclasses.fields(ObservabilityConfig)}
_LEDGER_FIELDS = {f.name for f in dataclasses.fields(LedgerConfig)}
_MEMLEDGER_FIELDS = {f.name for f in dataclasses.fields(MemoryLedgerConfig)}
_JOURNEYS_FIELDS = {f.name for f in dataclasses.fields(JourneysConfig)}
_INCIDENTS_FIELDS = {f.name for f in dataclasses.fields(IncidentsConfig)}
_WARMUP_FIELDS = {f.name for f in dataclasses.fields(WarmupConfig)}
_INC_FIELDS = {f.name for f in dataclasses.fields(IncrementalConfig)}
_SERVING_FIELDS = {f.name for f in dataclasses.fields(ServingConfig)}
_PAR_FIELDS = {f.name for f in dataclasses.fields(ParallelConfig)}
_SCN_FIELDS = {f.name for f in dataclasses.fields(ScenarioConfig)}


def decode_config(doc: dict, path: str = "") -> KubeSchedulerConfiguration:
    """Decode a mapping into the typed config, rejecting unknown fields
    (the reference's strict ComponentConfig decode fails on unknowns).

    An ``apiVersion``/``kind`` pair the scheme recognizes routes through
    the VERSIONED pipeline (build strict camelCase v1alpha1 -> default ->
    convert to internal — apis/config/scheme); untagged mappings use this
    implementation's native snake_case decode."""
    if not isinstance(doc, dict):
        raise ConfigError([f"{path or 'config'}: expected a mapping"])
    api_version = doc.get("apiVersion", "")
    if api_version:
        from kubernetes_tpu.api.config_v1alpha1 import SCHEME
        from kubernetes_tpu.api.scheme import SchemeError

        if SCHEME.recognizes(api_version, doc.get("kind", "")):
            try:
                return SCHEME.decode(doc, KubeSchedulerConfiguration)
            except SchemeError as e:
                raise ConfigError(e.errors)
        raise ConfigError([
            f"apiVersion: no kind {doc.get('kind', '')!r} registered for "
            f"{api_version!r}"
        ])
    errs: List[str] = []
    kw: dict = {}
    for key, val in doc.items():
        if key in ("apiVersion", "kind"):
            continue  # accepted for file-shape parity, not interpreted
        if key == "leader_election":
            if not isinstance(val, dict):
                errs.append("leaderElection: expected a mapping")
                continue
            unknown = set(val) - _LE_FIELDS
            if unknown:
                errs.append(
                    f"leaderElection: unknown field(s) {sorted(unknown)}"
                )
                continue
            kw["leader_election"] = LeaderElectionConfig(**val)
        elif key == "feature_gates":
            if not isinstance(val, dict):
                errs.append("featureGates: expected a mapping")
                continue
            try:
                kw["feature_gates"] = FeatureGates(overrides=dict(val))
            except ValueError as e:
                errs.append(f"featureGates: {e}")
        elif key == "robustness":
            if not isinstance(val, dict):
                errs.append("robustness: expected a mapping")
                continue
            unknown = set(val) - _ROB_FIELDS
            if unknown:
                errs.append(
                    f"robustness: unknown field(s) {sorted(unknown)}"
                )
                continue
            rkw = dict(val)
            if "fallback_chain" in rkw:
                rkw["fallback_chain"] = tuple(rkw["fallback_chain"])
            kw["robustness"] = RobustnessConfig(**rkw)
        elif key == "recovery":
            if not isinstance(val, dict):
                errs.append("recovery: expected a mapping")
                continue
            unknown = set(val) - _REC_FIELDS
            if unknown:
                errs.append(f"recovery: unknown field(s) {sorted(unknown)}")
                continue
            kw["recovery"] = RecoveryConfig(**val)
        elif key == "observability":
            if not isinstance(val, dict):
                errs.append("observability: expected a mapping")
                continue
            unknown = set(val) - _OBS_FIELDS
            if unknown:
                errs.append(
                    f"observability: unknown field(s) {sorted(unknown)}"
                )
                continue
            okw = dict(val)
            if "ledger" in okw:
                lval = okw["ledger"]
                if not isinstance(lval, dict):
                    errs.append("observability.ledger: expected a mapping")
                    continue
                lunknown = set(lval) - _LEDGER_FIELDS
                if lunknown:
                    errs.append(
                        f"observability.ledger: unknown field(s) "
                        f"{sorted(lunknown)}")
                    continue
                okw["ledger"] = LedgerConfig(**lval)
            if "memory_ledger" in okw:
                mval = okw["memory_ledger"]
                if not isinstance(mval, dict):
                    errs.append(
                        "observability.memoryLedger: expected a mapping")
                    continue
                munknown = set(mval) - _MEMLEDGER_FIELDS
                if munknown:
                    errs.append(
                        f"observability.memoryLedger: unknown field(s) "
                        f"{sorted(munknown)}")
                    continue
                okw["memory_ledger"] = MemoryLedgerConfig(**mval)
            if "journeys" in okw:
                jval = okw["journeys"]
                if not isinstance(jval, dict):
                    errs.append(
                        "observability.journeys: expected a mapping")
                    continue
                junknown = set(jval) - _JOURNEYS_FIELDS
                if junknown:
                    errs.append(
                        f"observability.journeys: unknown field(s) "
                        f"{sorted(junknown)}")
                    continue
                okw["journeys"] = JourneysConfig(**jval)
            if "incidents" in okw:
                ival = okw["incidents"]
                if not isinstance(ival, dict):
                    errs.append(
                        "observability.incidents: expected a mapping")
                    continue
                iunknown = set(ival) - _INCIDENTS_FIELDS
                if iunknown:
                    errs.append(
                        f"observability.incidents: unknown field(s) "
                        f"{sorted(iunknown)}")
                    continue
                okw["incidents"] = IncidentsConfig(**ival)
            kw["observability"] = ObservabilityConfig(**okw)
        elif key == "warmup":
            if not isinstance(val, dict):
                errs.append("warmup: expected a mapping")
                continue
            unknown = set(val) - _WARMUP_FIELDS
            if unknown:
                errs.append(f"warmup: unknown field(s) {sorted(unknown)}")
                continue
            wkw = dict(val)
            if "pod_buckets" in wkw:
                wkw["pod_buckets"] = tuple(wkw["pod_buckets"])
            kw["warmup"] = WarmupConfig(**wkw)
        elif key == "incremental":
            if not isinstance(val, dict):
                errs.append("incremental: expected a mapping")
                continue
            unknown = set(val) - _INC_FIELDS
            if unknown:
                errs.append(
                    f"incremental: unknown field(s) {sorted(unknown)}"
                )
                continue
            kw["incremental"] = IncrementalConfig(**val)
        elif key == "serving":
            if not isinstance(val, dict):
                errs.append("serving: expected a mapping")
                continue
            unknown = set(val) - _SERVING_FIELDS
            if unknown:
                errs.append(f"serving: unknown field(s) {sorted(unknown)}")
                continue
            kw["serving"] = ServingConfig(**val)
        elif key == "parallel":
            if not isinstance(val, dict):
                errs.append("parallel: expected a mapping")
                continue
            unknown = set(val) - _PAR_FIELDS
            if unknown:
                errs.append(f"parallel: unknown field(s) {sorted(unknown)}")
                continue
            kw["parallel"] = ParallelConfig(**val)
        elif key == "scenario":
            if not isinstance(val, dict):
                errs.append("scenario: expected a mapping")
                continue
            unknown = set(val) - _SCN_FIELDS
            if unknown:
                errs.append(f"scenario: unknown field(s) {sorted(unknown)}")
                continue
            kw["scenario"] = ScenarioConfig(**val)
        elif key == "policy":
            kw["policy"] = load_policy(val)
        elif key in _CONFIG_FIELDS:
            kw[key] = val
        else:
            errs.append(f"{key}: unknown field")
    if errs:
        raise ConfigError(errs)
    try:
        return KubeSchedulerConfiguration(**kw)
    except TypeError as e:
        raise ConfigError([str(e)])


def load_config_file(path: str) -> KubeSchedulerConfiguration:
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        import yaml

        try:
            doc = yaml.safe_load(text)
        except yaml.YAMLError as e:
            raise ConfigError([f"{path}: not valid JSON or YAML: {e}"])
    return decode_config(doc or {}, path)


def parse_feature_gates(spec: str) -> dict:
    """--feature-gates K=true,K2=false (component-base flag syntax)."""
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ConfigError([f"feature-gates: missing '=' in {part!r}"])
        k, v = part.split("=", 1)
        if v.lower() not in ("true", "false"):
            raise ConfigError([f"feature-gates.{k}: must be true|false"])
        out[k.strip()] = v.lower() == "true"
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kubernetes_tpu",
        description="TPU-native scheduler (kube-scheduler capability analog)",
    )
    p.add_argument("--config", help="KubeSchedulerConfiguration file (YAML/JSON)")
    p.add_argument("--policy-config-file",
                   help="legacy Policy file (scheduler.go:178 policy source)")
    p.add_argument("--feature-gates", default="",
                   help="comma-separated K=true|false overrides")
    p.add_argument("--scheduler-name", default=None)
    p.add_argument("--solver", default=None, choices=VALID_SOLVERS)
    p.add_argument("--per-node-cap", type=int, default=None)
    p.add_argument("--pipeline-depth", type=int, default=None,
                   help="pipelined cycle executor depth (1 = monolithic)")
    p.add_argument("--pipeline-chunk", type=int, default=None,
                   help="sub-batch size of the pipelined executor")
    p.add_argument("--warmup", default=None, choices=("true", "false"),
                   help="AOT-compile the bucketed solve shapes at startup")
    p.add_argument("--incremental", default=None,
                   choices=("true", "false"),
                   help="incremental solve: device-resident score cache "
                        "+ restricted candidate-column solves + warm "
                        "Sinkhorn potentials (steady-state cycle cost "
                        "O(churn), cold solve stays the fallback)")
    p.add_argument("--sparse-primary", default=None,
                   choices=("true", "false"),
                   help="sparsity-first solve: restricted candidate "
                        "routing as the PRIMARY path (implies "
                        "--incremental true; full-snapshot cycles "
                        "rebuild the score plane and still solve "
                        "restricted, the cold path runs partitioned, "
                        "the candidate bucket auto-tunes; the dense "
                        "solve stays the correctness oracle)")
    p.add_argument("--mesh", default=None,
                   help="sharded execution backend: off | auto | N "
                        "(1-D device mesh over the node axis)")
    p.add_argument("--scenario", default=None,
                   help="scenario pack: consolidation | gang-topology "
                        "(pluggable solve objective + quality scores; "
                        "empty string turns the pack off)")
    p.add_argument("--percentage-of-nodes-to-score", type=int, default=None)
    p.add_argument("--leader-elect", default=None, choices=("true", "false"))
    p.add_argument("--lock-file", default=None,
                   help="leader-election lock file (FileLock path)")
    p.add_argument("--bind-address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=10251,
                   help="healthz/metrics port (0 = ephemeral)")
    p.add_argument("--v", type=int, default=None,
                   help="log verbosity (klog --v analog; KTPU_V env)")
    p.add_argument("--validate-only", action="store_true",
                   help="decode + validate, print result, exit")
    p.add_argument("--version", action="store_true",
                   help="print version info and exit (pkg/version analog)")
    p.add_argument("--cycle-interval", type=float, default=0.25,
                   help="seconds between scheduling cycles when idle "
                        "(legacy mode; --serving replaces the timer "
                        "with wake-on-event)")
    p.add_argument("--serving", default=None, choices=("true", "false"),
                   help="event-driven micro-batch serving loop "
                        "(doorbell + accumulation window) instead of "
                        "the fixed-interval cycle timer")
    p.add_argument("--serving-max-wait", type=float, default=None,
                   help="micro-batch window latency ceiling, seconds")
    p.add_argument("--journeys", default=None, choices=("true", "false"),
                   help="per-pod journey tracer (phase-attributed "
                        "tail-latency timelines at /debug/journeys)")
    p.add_argument("--profile-dir", default=None,
                   help="artifact directory for triggered jax.profiler "
                        "captures (empty = profiling off); arms "
                        "incident-triggered and /debug/profile captures")
    return p


def resolve_config(args) -> KubeSchedulerConfiguration:
    """File → flag overlay → validation (the options.Complete/Validate
    flow, app/server.go:133-148)."""
    cfg = (load_config_file(args.config) if args.config
           else KubeSchedulerConfiguration())
    if args.policy_config_file:
        with open(args.policy_config_file) as f:
            cfg = dataclasses.replace(cfg, policy=load_policy(json.load(f)))
    overlay = {}
    if args.scheduler_name is not None:
        overlay["scheduler_name"] = args.scheduler_name
    if args.solver is not None:
        overlay["solver"] = args.solver
    if args.per_node_cap is not None:
        overlay["per_node_cap"] = args.per_node_cap
    if args.pipeline_depth is not None:
        overlay["pipeline_depth"] = args.pipeline_depth
    if args.pipeline_chunk is not None:
        overlay["pipeline_chunk"] = args.pipeline_chunk
    if args.warmup is not None:
        overlay["warmup"] = dataclasses.replace(
            cfg.warmup, enabled=args.warmup == "true")
    if getattr(args, "incremental", None) is not None:
        overlay["incremental"] = dataclasses.replace(
            cfg.incremental, enabled=args.incremental == "true")
    if getattr(args, "sparse_primary", None) is not None:
        base = overlay.get("incremental", cfg.incremental)
        on = args.sparse_primary == "true"
        overlay["incremental"] = dataclasses.replace(
            base, enabled=base.enabled or on, primary=on,
            auto_tune=on)
    if getattr(args, "mesh", None) is not None:
        spec = args.mesh
        if spec not in ("off", "auto"):
            try:
                spec = int(spec)
            except ValueError:
                pass  # validate_config rejects with the field path
        overlay["parallel"] = dataclasses.replace(cfg.parallel, mesh=spec)
    if getattr(args, "scenario", None) is not None:
        overlay["scenario"] = dataclasses.replace(
            cfg.scenario, pack=args.scenario)
    serving_overlay = {}
    if getattr(args, "serving", None) is not None:
        serving_overlay["enabled"] = args.serving == "true"
    if getattr(args, "serving_max_wait", None) is not None:
        serving_overlay["max_wait_s"] = args.serving_max_wait
    if serving_overlay:
        overlay["serving"] = dataclasses.replace(
            cfg.serving, **serving_overlay)
    obs_overlay = {}
    if getattr(args, "journeys", None) is not None:
        obs_overlay["journeys"] = dataclasses.replace(
            cfg.observability.journeys, enabled=args.journeys == "true")
    if getattr(args, "profile_dir", None) is not None:
        obs_overlay["incidents"] = dataclasses.replace(
            cfg.observability.incidents, profile_dir=args.profile_dir)
    if obs_overlay:
        overlay["observability"] = dataclasses.replace(
            cfg.observability, **obs_overlay)
    if args.percentage_of_nodes_to_score is not None:
        overlay["percentage_of_nodes_to_score"] = args.percentage_of_nodes_to_score
    if args.leader_elect is not None:
        overlay["leader_election"] = dataclasses.replace(
            cfg.leader_election, leader_elect=args.leader_elect == "true"
        )
    if args.feature_gates:
        # flag gates overlay file gates in place (featuregate.Set on the
        # already-decoded object, options.go ApplyFeatureGates order)
        try:
            cfg.feature_gates.set_from_string(args.feature_gates)
        except ValueError as e:
            raise ConfigError([f"featureGates: {e}"])
    if overlay:
        cfg = dataclasses.replace(cfg, **overlay)
    errors = validate_config(cfg)
    if errors:
        raise ConfigError(errors)
    return cfg


def run(cfg: KubeSchedulerConfiguration, args, stop_event=None) -> None:
    """The serve loop (app/server.go:161 Run): healthz/metrics server up
    first, then leader election gates the scheduling loop — a non-leader
    keeps serving healthz and ticking the elector (active-passive HA)."""
    import os
    import threading

    from kubernetes_tpu.leaderelection import FileLock, InMemoryLock, LeaderElector
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.server import serve_scheduler

    sched = Scheduler.from_config(cfg)
    runtime = None
    fairness = None
    if cfg.serving.enabled:
        # the COMPOSED serving runtime (serving/compose.py): doorbell +
        # micro-batch loop + APF admission with the backend-pressure
        # saturation probe + watch hub, adapted to the scheduler's mesh
        # (serving warmup grid, host-fallback shapes). The APF filter
        # lands on the component's own HTTP surface: extender POSTs
        # classify mutating and shed with 429 + Retry-After under the
        # configured seats/queues, while healthz/metrics/debug stay
        # exempt — and the mutating flow sheds from the scheduler's
        # ACTUAL state (ladder tier + queue depth), not queue length
        # alone.
        from kubernetes_tpu.serving import ServingRuntime

        runtime = ServingRuntime(sched, cfg.serving, warmup=cfg.warmup)
        fairness = runtime.flow
    srv = serve_scheduler(sched, host=args.bind_address, port=args.port,
                          fairness=fairness)
    host, port = srv.server_address[:2]
    print(f"serving healthz/metrics on {host}:{port}", file=sys.stderr)

    stop = stop_event or threading.Event()

    def _sig(_s, _f):
        stop.set()

    try:
        signal.signal(signal.SIGTERM, _sig)
        signal.signal(signal.SIGINT, _sig)
    except ValueError:
        # signal handlers can only be installed on the main thread; an
        # embedded run (tests, a host process driving the loop on a
        # worker thread) relies on stop_event instead
        pass

    elector = None
    if cfg.leader_election.leader_elect:
        lock = (FileLock(args.lock_file) if args.lock_file else InMemoryLock())
        elector = LeaderElector(
            identity=f"{os.uname().nodename}_{os.getpid()}",
            lock=lock,
            config=cfg.leader_election,
        )
        # recovery wiring: the elector fences every bind, gaining the
        # lease runs takeover reconciliation (requeue + resident-
        # snapshot rebuild onto the mesh + re-warm), losing it drains
        # in-flight state; the composed runtime additionally relists
        # its watchers across every leadership change
        if runtime is not None:
            runtime.attach_elector(elector)
        else:
            sched.attach_elector(elector)
    #: AOT warmup is LAZY — it must wait for the first node sync, or
    #: every warmed shape carries an empty-cluster node bucket that no
    #: real cycle will ever match (the compile would land on the first
    #: pod's critical path anyway, the exact latency the flag removes).
    #: The serving runtime owns its own pending flag (warm_if_pending,
    #: run under the ingest lock by its gate); this one is the LEGACY
    #: loop's.
    warmup_pending = cfg.warmup.enabled
    from kubernetes_tpu.serving import Doorbell

    # both modes carry the doorbell: the serving loop blocks on it
    # (runtime.bell), and the legacy loop uses it to tell "idle" from
    # "work arrived while I was solving" (the empty-queue skip below)
    bell = (runtime.bell if runtime is not None
            else sched.attach_doorbell(Doorbell()))

    def gate() -> bool:
        """The LEGACY loop's per-iteration admission: leader election
        (a non-leader keeps serving healthz and ticking the elector)
        and the lazy AOT warmup. Single-threaded, so no ingest guard;
        the serving path uses runtime.gate, which serializes the tick
        and the warmup against the loop's ingest lock."""
        nonlocal warmup_pending
        if elector is not None:
            if not elector.tick():
                stop.wait(cfg.leader_election.retry_period_s)
                return False
        if warmup_pending and sched.cache.node_count():
            pp = getattr(sched.queue, "pending_pods", None)
            sample = pp().get("active", [])[:64] if pp else []
            n = sched.warmup(sample_pods=sample)
            print(f"warmup: compiled {n} bucketed solve shapes",
                  file=sys.stderr)
            warmup_pending = False
        return True

    try:
        if runtime is not None:
            runtime.run(stop, elector=elector,
                        retry_period_s=cfg.leader_election.retry_period_s)
        else:
            while not stop.is_set():
                if not gate():
                    continue
                # idle fast path: an empty activeQ with no doorbell
                # activity since the last look means a solve could only
                # be empty — skip it (no trace, no CycleRecord, no
                # metrics churn) and run queue maintenance instead, so
                # long informer gaps stop minting empty cycle artifacts
                if (sched.queue.pending_counts().get("active", 0) == 0
                        and not bell.consume()):
                    sched.idle_tick()
                    stop.wait(args.cycle_interval)
                    continue
                r = sched.schedule_cycle()
                if r.attempted == 0:
                    stop.wait(args.cycle_interval)
    finally:
        if (elector is not None and cfg.recovery.release_lease_on_shutdown
                and elector.is_leader()):
            # graceful failover: CAS an expired lease record so the
            # standby acquires on its next tick instead of waiting out
            # the full lease duration
            elector.release()
        srv.shutdown()


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.version:
        from kubernetes_tpu import version_info

        print(json.dumps(version_info()))
        return 0
    if args.v is not None:
        from kubernetes_tpu.utils.klog import set_verbosity

        set_verbosity(args.v)
    try:
        cfg = resolve_config(args)
    except ConfigError as e:
        for err in e.errors:
            print(f"invalid configuration: {err}", file=sys.stderr)
        return 1
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if args.validate_only:
        print(f"configuration valid: scheduler={cfg.scheduler_name} "
              f"solver={cfg.solver}")
        return 0
    run(cfg, args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
