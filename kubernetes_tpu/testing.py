"""Test object builders — the analog of the reference's
``pkg/scheduler/testing/wrappers.go`` pod/node wrappers used throughout its
unit suites."""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import (
    Affinity,
    LabelSelector,
    Node,
    NodeCondition,
    NodeSelectorTerm,
    Pod,
    PreferredSchedulingTerm,
    Requirement,
    Resources,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)


def make_node(
    name: str,
    cpu_milli: float = 32000,
    memory: float = 64 * 2**30,
    pods: int = 110,
    labels: Optional[Dict[str, str]] = None,
    taints: Sequence[Taint] = (),
    zone: Optional[str] = None,
    **kw,
) -> Node:
    labels = dict(labels or {})
    labels.setdefault("kubernetes.io/hostname", name)
    if zone is not None:
        labels["failure-domain.beta.kubernetes.io/zone"] = zone
    return Node(
        name=name,
        labels=labels,
        allocatable=Resources(cpu_milli=cpu_milli, memory=memory, pods=pods),
        taints=tuple(taints),
        **kw,
    )


def make_pod(
    name: str,
    cpu_milli: float = 0,
    memory: float = 0,
    namespace: str = "default",
    node_name: str = "",
    labels: Optional[Dict[str, str]] = None,
    node_selector: Optional[Dict[str, str]] = None,
    affinity: Optional[Affinity] = None,
    tolerations: Sequence[Toleration] = (),
    priority: int = 0,
    host_ports: Sequence[Tuple[str, str, int]] = (),
    scalars: Optional[Dict[str, float]] = None,
    **kw,
) -> Pod:
    return Pod(
        name=name,
        namespace=namespace,
        node_name=node_name,
        labels=dict(labels or {}),
        node_selector=dict(node_selector or {}),
        affinity=affinity or Affinity(),
        tolerations=tuple(tolerations),
        priority=priority,
        requests=Resources(cpu_milli=cpu_milli, memory=memory, scalars=dict(scalars or {})),
        host_ports=tuple(host_ports),
        **kw,
    )


def req(key: str, op: str, *values: str) -> Requirement:
    return Requirement(key=key, operator=op, values=tuple(values))


def node_affinity_required(*terms: Sequence[Requirement]) -> Affinity:
    return Affinity(
        node_required=tuple(NodeSelectorTerm(tuple(t)) for t in terms)
    )


def node_affinity_preferred(*weighted: Tuple[int, Sequence[Requirement]]) -> Affinity:
    return Affinity(
        node_preferred=tuple(
            PreferredSchedulingTerm(weight=w, preference=NodeSelectorTerm(tuple(t)))
            for w, t in weighted
        )
    )
