"""Test object builders — the analog of the reference's
``pkg/scheduler/testing/wrappers.go`` pod/node wrappers used throughout its
unit suites — plus :func:`lint_clean`, the graftlint assertion future ops
kernels use to pin their own tracer-safety."""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import (
    Affinity,
    LabelSelector,
    Node,
    NodeCondition,
    NodeSelectorTerm,
    Pod,
    PreferredSchedulingTerm,
    Requirement,
    Resources,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)


def make_node(
    name: str,
    cpu_milli: float = 32000,
    memory: float = 64 * 2**30,
    pods: int = 110,
    labels: Optional[Dict[str, str]] = None,
    taints: Sequence[Taint] = (),
    zone: Optional[str] = None,
    **kw,
) -> Node:
    labels = dict(labels or {})
    labels.setdefault("kubernetes.io/hostname", name)
    if zone is not None:
        labels["failure-domain.beta.kubernetes.io/zone"] = zone
    return Node(
        name=name,
        labels=labels,
        allocatable=Resources(cpu_milli=cpu_milli, memory=memory, pods=pods),
        taints=tuple(taints),
        **kw,
    )


def make_pod(
    name: str,
    cpu_milli: float = 0,
    memory: float = 0,
    namespace: str = "default",
    node_name: str = "",
    labels: Optional[Dict[str, str]] = None,
    node_selector: Optional[Dict[str, str]] = None,
    affinity: Optional[Affinity] = None,
    tolerations: Sequence[Toleration] = (),
    priority: int = 0,
    host_ports: Sequence[Tuple[str, str, int]] = (),
    scalars: Optional[Dict[str, float]] = None,
    **kw,
) -> Pod:
    return Pod(
        name=name,
        namespace=namespace,
        node_name=node_name,
        labels=dict(labels or {}),
        node_selector=dict(node_selector or {}),
        affinity=affinity or Affinity(),
        tolerations=tuple(tolerations),
        priority=priority,
        requests=Resources(cpu_milli=cpu_milli, memory=memory, scalars=dict(scalars or {})),
        host_ports=tuple(host_ports),
        **kw,
    )


def req(key: str, op: str, *values: str) -> Requirement:
    return Requirement(key=key, operator=op, values=tuple(values))


def node_affinity_required(*terms: Sequence[Requirement]) -> Affinity:
    return Affinity(
        node_required=tuple(NodeSelectorTerm(tuple(t)) for t in terms)
    )


def node_affinity_preferred(*weighted: Tuple[int, Sequence[Requirement]]) -> Affinity:
    return Affinity(
        node_preferred=tuple(
            PreferredSchedulingTerm(weight=w, preference=NodeSelectorTerm(tuple(t)))
            for w, t in weighted
        )
    )


def lint_clean(
    source,
    rules: Sequence[str] = ("R1", "R2", "R3", "R5", "R6"),
    filename: str = "<kernel>",
    jit_all: bool = True,
) -> None:
    """Assert a kernel's source passes graftlint — the tracer-safety
    analog of the wrappers above: a new ops kernel pins its own
    discipline with one line in its unit test::

        from kubernetes_tpu.testing import lint_clean
        import kubernetes_tpu.ops.mykernel as mk
        def test_mykernel_tracer_safe():
            lint_clean(mk)

    ``source`` is a source string, a module, or any object
    ``inspect.getsource`` accepts (function, class). ``jit_all=True``
    treats every *uncalled* top-level function as a jit entry point, so
    the check covers kernels whose ``jax.jit`` wrapper lives in the
    caller; helpers the source itself calls are judged by their real
    call-site taint (``_block_shapes(*x.shape)`` stays host). Pass
    ``jit_all=False`` for modules that mix kernels with deliberate
    host-side functions (``ops/assign.py``'s trust-but-verify
    ``validate_solution``) to lint via the module's real jit roots. The
    default rule set is the device-side discipline (tracer safety,
    host syncs, retrace, dtype); pass ``rules=None`` for everything.

    Raises AssertionError listing every finding; returns None when clean.
    """
    import inspect
    import os

    from kubernetes_tpu.lint import lint_source
    from kubernetes_tpu.lint.report import render_text

    if not isinstance(source, str):
        filename = getattr(source, "__file__", None) or filename
        source = inspect.getsource(source)
    # R5 scopes by path: make bare snippet names look like ops/ files so
    # the dtype rule engages for kernel sources passed as strings
    if "/" not in filename.replace(os.sep, "/"):
        filename = f"ops/{filename.lstrip('<').rstrip('>') or 'kernel'}.py"
    # R6 is always on: every OTHER rule is vacuous on source that does
    # not parse, so without the syntax gate a broken kernel would pass
    select = tuple(dict.fromkeys(tuple(rules) + ("R6",))) \
        if rules is not None else None
    findings = lint_source(
        source, filename=filename, select=select, jit_all=jit_all,
    )
    if findings:
        raise AssertionError(
            "graftlint found tracer-safety problems:\n"
            + render_text(findings)
        )
