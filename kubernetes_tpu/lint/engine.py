"""graftlint core — file loading, comment directives, the cross-file jit
call graph, and the rule registry plumbing.

The engine is deliberately runtime-free: everything works from source
text + ``ast`` so the linter can run on files that would crash on import
(that is the whole point of the R6/parse gate) and inside tier-1 without
touching a device.
"""

from __future__ import annotations

import ast
import hashlib
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: every rule class the engine knows; report/CLI validate --select and
#: suppression comments against this
RULE_IDS = ("R0", "R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8", "R9", "R10")


@dataclass(frozen=True)
class Finding:
    """One lint finding. ``fingerprint`` is line-number-free (rule + file
    + normalized source text + occurrence index) so committed baselines
    survive unrelated edits above the finding."""

    path: str  # root-relative, '/'-separated
    line: int
    col: int
    rule: str
    message: str
    snippet: str = ""
    occurrence: int = 0  # index among identical (rule, path, snippet)

    def fingerprint(self) -> str:
        key = "|".join(
            (self.rule, self.path, " ".join(self.snippet.split()),
             str(self.occurrence))
        )
        return hashlib.sha1(key.encode("utf-8")).hexdigest()[:16]

    def as_dict(self) -> Dict[str, object]:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "rule": self.rule,
            "message": self.message,
            "snippet": self.snippet,
            "fingerprint": self.fingerprint(),
        }


# --------------------------------------------------------------------------
# suppression directives
# --------------------------------------------------------------------------

_DIRECTIVE_RE = re.compile(
    r"graftlint:\s*(?P<form>disable(?:-scope|-file)?)\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\s]+?)\s*(?:--\s*(?P<why>.+?)\s*)?$"
)


@dataclass
class _Directive:
    line: int
    standalone: bool
    form: str  # disable | disable-scope | disable-file
    rules: Tuple[str, ...]
    why: str


class Suppressions:
    """Inline ``# graftlint:`` directives for one file.

    - ``disable=``: trailing comment suppresses its own line; a
      standalone comment line suppresses the next line.
    - ``disable-scope=``: standalone comment immediately above a
      ``def``/``class`` (or trailing on its header line) suppresses the
      whole body.
    - ``disable-file=``: suppresses the rule everywhere in the file.

    A justification after `` -- `` is mandatory; directives without one
    (or naming unknown rules) become R0 findings instead of working.
    """

    def __init__(self) -> None:
        self.line_rules: Dict[int, Set[str]] = {}
        self.span_rules: List[Tuple[int, int, Set[str]]] = []
        self.file_rules: Set[str] = set()
        self.hygiene: List[_Directive] = []
        self._directives: List[_Directive] = []

    def allows(self, line: int, rule: str) -> bool:
        if rule in self.file_rules:
            return True
        if rule in self.line_rules.get(line, ()):
            return True
        return any(a <= line <= b and rule in rules
                   for a, b, rules in self.span_rules)


def _parse_directives(source: str) -> List[_Directive]:
    out: List[_Directive] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        # unparseable file: fall back to a per-line scan so a broken file
        # can still carry directives (and R0 still checks them)
        tokens = []
        for i, text in enumerate(source.splitlines(), 1):
            pos = text.find("#")
            if pos >= 0 and "graftlint:" in text[pos:]:
                tok = tokenize.TokenInfo(
                    tokenize.COMMENT, text[pos:], (i, pos), (i, len(text)), text
                )
                tokens.append(tok)
    for tok in tokens:
        if tok.type != tokenize.COMMENT or "graftlint:" not in tok.string:
            continue
        m = _DIRECTIVE_RE.search(tok.string)
        line = tok.start[0]
        before = tok.line[: tok.start[1]]
        standalone = not before.strip()
        if m is None:
            out.append(_Directive(line, standalone, "malformed", (), ""))
            continue
        rules = tuple(
            r.strip().upper() for r in m.group("rules").split(",") if r.strip()
        )
        out.append(_Directive(
            line, standalone, m.group("form"), rules, m.group("why") or ""
        ))
    return out


#: statement types a line-level ``disable`` may widen to: simple (non-
#: block) statements only, so a trailing directive on a compound header
#: can never blanket the whole body
_SIMPLE_STMTS = (
    ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Expr, ast.Return,
    ast.Assert, ast.Raise, ast.Delete, ast.Import, ast.ImportFrom,
)


def _simple_stmt_span(tree: Optional[ast.Module], line: int) -> Tuple[int, int]:
    """(lineno, end_lineno) of the innermost simple statement containing
    ``line``, or (line, line). Lets a ``disable`` directive govern a call
    that wraps over several lines — whether the comment trails the first
    line, a continuation line, or stands above the statement — since
    findings anchor to the offending node's own line."""
    if tree is not None:
        for node in ast.walk(tree):
            if isinstance(node, _SIMPLE_STMTS):
                end = node.end_lineno or node.lineno
                if node.lineno <= line <= end:
                    return (node.lineno, end)
    return (line, line)


def _def_spans(tree: ast.Module) -> List[Tuple[int, int, int]]:
    """(first_line_incl_decorators, header_line, end_line) per def/class."""
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            first = min([node.lineno] + [d.lineno for d in node.decorator_list])
            spans.append((first, node.lineno, node.end_lineno or node.lineno))
    return spans


def build_suppressions(source: str, tree: Optional[ast.Module]) -> Suppressions:
    sup = Suppressions()
    spans = _def_spans(tree) if tree is not None else []
    lines = source.splitlines()

    def next_code_line(after: int) -> int:
        """First non-blank, non-comment line after ``after`` (1-based) —
        standalone directives may wrap their justification over several
        comment lines before the code they govern."""
        for i in range(after, len(lines)):
            text = lines[i].strip()
            if text and not text.startswith("#"):
                return i + 1
        return after + 1

    for d in _parse_directives(source):
        sup._directives.append(d)
        bad = (
            d.form == "malformed"
            or not d.rules
            or not d.why.strip()
            or any(r not in RULE_IDS for r in d.rules)
        )
        if bad:
            sup.hygiene.append(d)
            continue
        rules = set(d.rules)
        if d.form == "disable-file":
            sup.file_rules |= rules
        elif d.form == "disable-scope":
            code_line = d.line if not d.standalone else next_code_line(d.line)
            target = None
            for first, header, end in spans:
                if first <= code_line <= header:
                    # directive sits on/above the header (decorators count)
                    target = (min(first, d.line), end)
                    break
            if target is None:
                sup.hygiene.append(d)
            else:
                sup.span_rules.append((target[0], target[1], rules))
        else:  # disable
            target_line = next_code_line(d.line) if d.standalone else d.line
            first, last = _simple_stmt_span(tree, target_line)
            for ln in range(first, last + 1):
                sup.line_rules.setdefault(ln, set()).update(rules)
    return sup


# --------------------------------------------------------------------------
# AST helpers shared by the rules
# --------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.numpy.asarray' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_dotted(name: Optional[str], imports: Dict[str, str]) -> Optional[str]:
    """Rewrite the first segment of a dotted name through the file's
    import table: ``jnp.asarray`` → ``jax.numpy.asarray``."""
    if not name:
        return None
    head, _, rest = name.partition(".")
    base = imports.get(head)
    if base is None:
        return name
    return base + ("." + rest if rest else "")


def is_jit_callable(node: ast.AST, imports: Dict[str, str]) -> Tuple[bool, Set[str]]:
    """Is this expression a jit transform (``jax.jit``, ``jit``,
    ``partial(jax.jit, ...)``)? Returns (yes, static_argnames)."""
    full = resolve_dotted(dotted_name(node), imports)
    if full in ("jax.jit", "jax.api.jit"):
        return True, set()
    if isinstance(node, ast.Call):
        fn = resolve_dotted(dotted_name(node.func), imports)
        if fn in ("functools.partial", "partial") and node.args:
            inner, static = is_jit_callable(node.args[0], imports)
            if inner:
                return True, static | _static_argnames_of(node)
    return False, set()


def _static_argnames_of(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                return {
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
    return set()


# --------------------------------------------------------------------------
# files, functions, project
# --------------------------------------------------------------------------

@dataclass
class FuncRecord:
    qual: str  # "<relpath>::Outer.name"
    name: str
    node: ast.AST  # FunctionDef / AsyncFunctionDef
    file: "FileInfo"
    params: List[str]
    jit_root: bool = False
    static_params: Set[str] = field(default_factory=set)

    @property
    def line(self) -> int:
        return self.node.lineno


@dataclass
class FileInfo:
    path: str  # absolute
    relpath: str  # root-relative, '/'-separated — Finding.path
    source: str
    lines: List[str]
    tree: Optional[ast.Module]
    parse_error: Optional[BaseException]
    suppressions: Suppressions
    imports: Dict[str, str] = field(default_factory=dict)
    functions: Dict[str, FuncRecord] = field(default_factory=dict)  # local name -> rec
    module: Optional[str] = None  # dotted module when under a package

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, node_or_line, rule: str, message: str) -> Finding:
        if isinstance(node_or_line, int):
            line, col = node_or_line, 0
        else:
            line, col = node_or_line.lineno, node_or_line.col_offset
        return Finding(self.relpath, line, col, rule, message,
                       self.line_text(line))


def _collect_imports(tree: ast.Module) -> Dict[str, str]:
    imports: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                imports[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                imports[a.asname or a.name] = f"{node.module}.{a.name}"
    return imports


def _collect_functions(fi: FileInfo) -> None:
    """Top-level (and class-level) function records + jit-root marking.
    Nested defs are analyzed inside their parent, not indexed."""

    def visit(body: Sequence[ast.stmt], prefix: str) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = node.args
                params = [p.arg for p in
                          (a.posonlyargs + a.args + a.kwonlyargs)]
                rec = FuncRecord(
                    qual=f"{fi.relpath}::{prefix}{node.name}",
                    name=prefix + node.name, node=node, file=fi, params=params,
                )
                for dec in node.decorator_list:
                    jit, static = is_jit_callable(dec, fi.imports)
                    if jit:
                        rec.jit_root = True
                        rec.static_params |= static
                fi.functions[prefix + node.name] = rec
            elif isinstance(node, ast.ClassDef):
                visit(node.body, prefix + node.name + ".")

    if fi.tree is not None:
        visit(fi.tree.body, "")
        _mark_value_jits(fi)


def _mark_value_jits(fi: FileInfo) -> None:
    """``f = jax.jit(g)`` / ``jax.jit(partial(g, ...))(...)`` forms: mark
    ``g`` as a jit root when it is a module-local function."""
    for node in ast.walk(fi.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = resolve_dotted(dotted_name(node.func), fi.imports)
        if fn not in ("jax.jit", "jax.api.jit") or not node.args:
            continue
        static = _static_argnames_of(node)
        target = node.args[0]
        bound: Set[str] = set()
        n_pos_bound = 0
        if isinstance(target, ast.Call):
            inner = resolve_dotted(dotted_name(target.func), fi.imports)
            if inner in ("functools.partial", "partial") and target.args:
                bound = {kw.arg for kw in target.keywords if kw.arg}
                # partial(g, a, b) binds g's first two parameters: those
                # values are closed over — concrete at trace time, never
                # traced parameters of the wrapper
                n_pos_bound = len(target.args) - 1
                target = target.args[0]
        name = dotted_name(target)
        if name and name in fi.functions:
            rec = fi.functions[name]
            rec.jit_root = True
            rec.static_params |= static | bound | set(rec.params[:n_pos_bound])


def _module_name(path: str) -> Optional[str]:
    """Dotted module for a file under package dirs (walks up while
    __init__.py exists)."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    if len(parts) == 1 and parts[0] != "__init__":
        return None
    if parts[0] == "__init__":
        parts = parts[1:]
    return ".".join(reversed(parts)) or None


def load_file(path: str, root: str) -> FileInfo:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        source = f.read()
    return make_fileinfo(source, path, root)


def make_fileinfo(source: str, path: str, root: str) -> FileInfo:
    rel = os.path.relpath(path, root).replace(os.sep, "/") \
        if os.path.isabs(path) else path.replace(os.sep, "/")
    tree: Optional[ast.Module] = None
    err: Optional[BaseException] = None
    try:
        tree = ast.parse(source, filename=path)
    except (SyntaxError, ValueError, RecursionError) as e:
        err = e
    fi = FileInfo(
        path=path, relpath=rel, source=source,
        lines=source.splitlines(), tree=tree, parse_error=err,
        suppressions=build_suppressions(source, tree),
    )
    if tree is not None:
        fi.imports = _collect_imports(tree)
        _collect_functions(fi)
    fi.module = _module_name(path) if os.path.isabs(path) else None
    return fi


def iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__" and not d.startswith(".")]
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames) if f.endswith(".py"))
    return sorted(set(out))


class Project:
    """All files under analysis + the cross-file function index the
    interprocedural rules (R1/R2) need."""

    def __init__(self, files: Sequence[FileInfo]) -> None:
        self.files = list(files)
        #: dotted module -> FileInfo (only files that live under packages)
        self.modules: Dict[str, FileInfo] = {
            fi.module: fi for fi in self.files if fi.module
        }

    @classmethod
    def from_paths(cls, paths: Sequence[str], root: str) -> "Project":
        return cls([load_file(p, root) for p in iter_py_files(paths)])

    def resolve_call(self, call: ast.Call, fi: FileInfo,
                     local_prefix: str = "") -> Optional[FuncRecord]:
        """Map a call expression to a first-party FuncRecord, through the
        caller file's imports, or None for stdlib/third-party/dynamic."""
        return self.resolve_name(dotted_name(call.func), fi, local_prefix)

    def resolve_name(self, name: Optional[str], fi: FileInfo,
                     local_prefix: str = "") -> Optional[FuncRecord]:
        """Resolve a (dotted) function reference to a FuncRecord."""
        if name is None:
            return None
        if name in fi.functions:
            return fi.functions[name]
        if local_prefix and (local_prefix + name) in fi.functions:
            return fi.functions[local_prefix + name]
        full = resolve_dotted(name, fi.imports)
        if full is None or "." not in full:
            return None
        mod, _, func = full.rpartition(".")
        target = self.modules.get(mod)
        if target is not None and func in target.functions:
            return target.functions[func]
        return None

    def jit_roots(self) -> List[FuncRecord]:
        return [rec for fi in self.files
                for rec in fi.functions.values() if rec.jit_root]


# --------------------------------------------------------------------------
# rule registry + entry points
# --------------------------------------------------------------------------

#: rule id -> callable(project) -> List[Finding]; populated by rules.py
_PROJECT_RULES: Dict[str, Callable[[Project], List[Finding]]] = {}


def register_rule(rule_id: str):
    def deco(fn):
        _PROJECT_RULES[rule_id] = fn
        return fn
    return deco


def run_lint(
    paths: Sequence[str],
    root: Optional[str] = None,
    select: Optional[Iterable[str]] = None,
    respect_suppressions: bool = True,
) -> List[Finding]:
    """Lint ``paths`` (files/dirs). Returns surviving findings sorted by
    (path, line, rule); suppressed findings are dropped, and suppression
    hygiene problems surface as R0."""
    root = os.path.abspath(root or os.getcwd())
    project = Project.from_paths(paths, root)
    return lint_project(project, select=select,
                        respect_suppressions=respect_suppressions)


def lint_project(
    project: Project,
    select: Optional[Iterable[str]] = None,
    respect_suppressions: bool = True,
) -> List[Finding]:
    from kubernetes_tpu.lint import rules as _rules  # registers on import

    _rules.ensure_registered()
    wanted = set(select) if select else set(RULE_IDS)
    unknown = wanted - set(RULE_IDS)
    if unknown:
        raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
    findings: List[Finding] = []
    for rule_id, fn in sorted(_PROJECT_RULES.items()):
        if rule_id in wanted:
            findings.extend(fn(project))
    by_file = {fi.relpath: fi for fi in project.files}
    kept: List[Finding] = []
    for f in findings:
        fi = by_file.get(f.path)
        if (respect_suppressions and fi is not None
                and f.rule != "R0"
                and fi.suppressions.allows(f.line, f.rule)):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.col))
    # stable occurrence indices for identical (rule, path, snippet) triples
    seen: Dict[Tuple[str, str, str], int] = {}
    out: List[Finding] = []
    for f in kept:
        key = (f.rule, f.path, " ".join(f.snippet.split()))
        n = seen.get(key, 0)
        seen[key] = n + 1
        out.append(Finding(f.path, f.line, f.col, f.rule, f.message,
                           f.snippet, occurrence=n))
    return out


def lint_source(
    source: str,
    filename: str = "<snippet>",
    select: Optional[Iterable[str]] = None,
    jit_all: bool = False,
    respect_suppressions: bool = True,
) -> List[Finding]:
    """Lint one source string. ``jit_all=True`` treats every *uncalled*
    top-level function as a jit entry point — what :func:`kubernetes_tpu.
    testing.lint_clean` uses so an ops kernel's body is checked even
    though its ``jax.jit`` wrapper lives in the caller. Functions the
    snippet itself calls are left to the interprocedural propagation, so
    a host helper invoked with static values (``_block_shapes(*x.shape)``)
    is judged by its real call-site taint, not worst-case entry taint —
    the same verdict the whole-project run reaches."""
    fi = make_fileinfo(source, filename, root=os.getcwd())
    if jit_all:
        called: Set[str] = set()
        for rec in fi.functions.values():
            for sub in ast.walk(rec.node):
                if isinstance(sub, ast.Call):
                    name = dotted_name(sub.func)
                    if name and name != rec.name and name in fi.functions:
                        called.add(name)
        for name, rec in fi.functions.items():
            if name not in called:
                rec.jit_root = True
    return lint_project(Project([fi]), select=select,
                        respect_suppressions=respect_suppressions)
