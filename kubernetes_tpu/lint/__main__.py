"""graftlint CLI — ``python -m kubernetes_tpu.lint [paths...]``.

Exit codes: 0 clean (after suppressions + baseline), 1 findings, 2 usage
error. Tier-1 runs this (via tests/test_static_analysis.py) with the
committed baseline, so `exit 0` here is a merge gate.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from kubernetes_tpu.lint.engine import RULE_IDS, run_lint
from kubernetes_tpu.lint.report import (
    load_baseline,
    render_json,
    render_text,
    subtract_baseline,
    write_baseline,
)

DEFAULT_PATHS = ("kubernetes_tpu/", "scripts/", "tests/")
DEFAULT_BASELINE = ".graftlint-baseline.json"


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.lint",
        description="AST-based tracer-safety / determinism / host-sync / "
                    "concurrency linter for the jax_graft scheduler "
                    "(rules R0-R10; see docs/lint.md).",
    )
    parser.add_argument("paths", nargs="*", default=None,
                        help=f"files/dirs to lint (default: {' '.join(DEFAULT_PATHS)})")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--select", default=None, metavar="R1,R2",
                        help="comma-separated rule ids to run (default: all)")
    parser.add_argument("--baseline", default=None, metavar="FILE",
                        help="baseline JSON of grandfathered findings "
                             f"(default: {DEFAULT_BASELINE} if present)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline file")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from current findings "
                             "and exit 0")
    parser.add_argument("--root", default=None,
                        help="path findings are reported relative to "
                             "(default: cwd)")
    args = parser.parse_args(argv)

    root = os.path.abspath(args.root or os.getcwd())
    if args.paths:
        # an explicitly named path that doesn't exist is a usage error,
        # not a clean run — a typo'd path in CI must fail the gate loudly
        missing = [p for p in args.paths if not os.path.exists(p)]
        if missing:
            print(f"graftlint: path(s) do not exist: {' '.join(missing)}",
                  file=sys.stderr)
            return 2
        paths = args.paths
    else:
        paths = [p for p in
                 (os.path.join(root, d) for d in DEFAULT_PATHS)
                 if os.path.exists(p)]
    if not paths:
        print("graftlint: no existing paths to lint", file=sys.stderr)
        return 2

    select = None
    if args.select:
        select = [s.strip().upper() for s in args.select.split(",") if s.strip()]
        bad = [s for s in select if s not in RULE_IDS]
        if bad:
            print(f"graftlint: unknown rule id(s) {bad}; known: "
                  f"{', '.join(RULE_IDS)}", file=sys.stderr)
            return 2

    findings = run_lint(paths, root=root, select=select)

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    if args.write_baseline:
        write_baseline(findings, baseline_path)
        print(f"graftlint: wrote {len(findings)} finding(s) to "
              f"{baseline_path}")
        return 0

    baselined = 0
    if not args.no_baseline and os.path.exists(baseline_path):
        try:
            baseline = load_baseline(baseline_path)
        except ValueError as e:
            print(f"graftlint: {e}", file=sys.stderr)
            return 2
        findings, baselined = subtract_baseline(findings, baseline)

    if args.format == "json":
        sys.stdout.write(render_json(findings, baselined))
    else:
        print(render_text(findings, baselined))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
