"""graftlint rule implementations.

R1 is the deep one: a cross-file, interprocedural taint pass that starts
from every jit root's non-static parameters and follows values through
assignments, pytree field access and first-party call edges, flagging
the Python constructs whose *truthiness/host conversion* a tracer cannot
survive. The other rules are syntactic scans scoped by the same jit call
graph (R2) or by file class (R4/R5) — cheap by design so tier-1 can
afford to run the whole thing on every change.

Taint lattice: ``None < "pytree" < "maybe" < "array"``.

- ``"array"`` — definitely a traced array (jnp/jax result, field access
  on a traced bundle). Everything flags: truthiness, conversion,
  iteration, membership.
- ``"maybe"`` — unknown (unannotated parameter, element of a mixed
  container, opaque call result). Truthiness and conversions flag;
  iteration does not — iterating a NamedTuple of tracers
  (``DevicePods(*[f(x) for x in pods])``) is legal and common.
- ``"pytree"`` — definitely a container of traced leaves (dict/tuple
  literal, ``dict()``-family ctor). Containers have host truthiness, so
  only element access re-taints.

Parameter type annotations refine the entry kind: ``x: jnp.ndarray`` →
array, ``hoisted: Dict[...] | None`` → pytree, ``reverse: bool`` /
``name: str`` → host (annotated bools/strs are trace-time constants in
this codebase — jit would have to be told they're static anyway).
Comparisons against string constants are host metadata checks
(``kind == "full"``) and never taint.
"""

from __future__ import annotations

import ast
import re
import sys
from collections import deque
from typing import Dict, List, Optional, Sequence, Set, Tuple

from kubernetes_tpu.lint.engine import (
    RULE_IDS,
    FileInfo,
    Finding,
    FuncRecord,
    Project,
    dotted_name,
    register_rule,
    resolve_dotted,
)

# --- taint lattice ---------------------------------------------------------

_ORDER = {None: 0, "pytree": 1, "maybe": 2, "array": 3}

#: kinds whose truthiness / host conversion a tracer cannot survive
_HAZARD_KINDS = ("maybe", "array")


def _join(a: Optional[str], b: Optional[str]) -> Optional[str]:
    return a if _ORDER[a] >= _ORDER[b] else b


#: annotation leaf name -> entry taint kind. None means host value
#: (trusted untraced); absent leaves mean "maybe".
_ANNOTATION_KINDS = {
    "ndarray": "array", "array": "array", "jaxarray": "array",
    "arraylike": "array",
    "dict": "pytree", "mapping": "pytree", "defaultdict": "pytree",
    "list": "pytree", "tuple": "pytree", "sequence": "pytree",
    "set": "pytree", "frozenset": "pytree", "iterable": "pytree",
    "bool": None, "str": None, "bytes": None, "callable": None,
    "none": None, "nonetype": None,
}


def _annotation_kind(ann: Optional[ast.expr]) -> Tuple[Optional[str], bool]:
    """(entry kind, recognized?) for a parameter annotation. Optional[X]
    and ``X | None`` unwrap to X; unions join their parts."""
    if ann is None:
        return "maybe", False
    if isinstance(ann, ast.Constant):
        if ann.value is None:  # the `| None` / Optional member
            return None, True
        if isinstance(ann.value, str):  # string annotation
            leaf = ann.value.split("[")[0].split(".")[-1].strip().lower()
            if leaf in _ANNOTATION_KINDS:
                return _ANNOTATION_KINDS[leaf], True
        return "maybe", False
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        k1, r1 = _annotation_kind(ann.left)
        k2, r2 = _annotation_kind(ann.right)
        if r1 and r2:
            return _join(k1, k2), True
        # `DevicePods | None`: an unrecognized union member means the
        # value can be anything — do not let the recognized side pin it
        return "maybe", False
    if isinstance(ann, ast.Subscript):
        base = dotted_name(ann.value)
        leaf = (base or "").split(".")[-1].lower()
        if leaf in ("optional", "union"):
            parts = (ann.slice.elts if isinstance(ann.slice, ast.Tuple)
                     else [ann.slice])
            kind: Optional[str] = None
            recognized = True
            for p in parts:
                k, r = _annotation_kind(p)
                recognized &= r
                kind = _join(kind, k)
            return (kind, True) if recognized else ("maybe", False)
        return _annotation_kind(ann.value)
    name = dotted_name(ann)
    if name is not None:
        leaf = name.split(".")[-1].lower()
        if leaf in _ANNOTATION_KINDS:
            return _ANNOTATION_KINDS[leaf], True
    return "maybe", False


def _param_pins(rec: FuncRecord) -> Dict[str, Tuple[Optional[str], bool]]:
    """Per-parameter (annotation kind, recognized) for a function."""
    a = rec.node.args
    return {p.arg: _annotation_kind(p.annotation)
            for p in a.posonlyargs + a.args + a.kwonlyargs}


#: attribute projections of a tracer that are plain host values (safe to
#: branch on): the static trace-time metadata
STATIC_ATTRS = {
    "shape", "dtype", "ndim", "size", "itemsize", "nbytes", "weak_type",
    "aval", "sharding", "dims",
}

#: builtins whose result is a host value even on traced input
_HOST_RESULT_CALLS = {
    "len", "range", "isinstance", "issubclass", "type", "id", "repr",
    "str", "callable", "print", "format", "hasattr",
}

#: conversions that force a concrete value out of a tracer (R1)
_CONVERSIONS = {"bool", "int", "float", "complex"}

#: method names that pull device values to host (R1 in jit, R2 in hot host)
_SYNC_METHODS = {"item", "tolist"}

#: call targets that read a whole device buffer back (R2)
_SYNC_CALLS = {
    "numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
    "jax.device_get",
}

#: host functions that form the per-cycle solve loop (R2 hot scope) in
#: addition to every jit-context function. schedule_cycle itself is the
#: documented host boundary (results must come back to bind) and is
#: deliberately NOT in this set — see docs/lint.md.
HOT_FUNC_NAMES = {
    "Scheduler._run_tier", "Scheduler._solve_ladder", "Scheduler._exact_solve",
    "validate_solution", "greedy_assign", "batch_assign",
}

#: one-line rule summaries (lint_report / docs surface these)
RULE_SUMMARIES = {
    "R0": "suppression hygiene: every disable needs a justification",
    "R1": "tracer-unsafe Python in jit-compiled code",
    "R2": "host-device sync inside the per-cycle solve loop",
    "R3": "retrace hazards (jit-per-call, bogus static_argnames)",
    "R4": "non-determinism (global RNG, wall clock, argless now())",
    "R5": "dtype drift: float64 in device-math modules",
    "R6": "syntax gate: Py3.10 f-string backslash / parse errors",
    "R7": "d2h readback outside a declared obs.jax.readback boundary",
    "R8": "sharded-value gather in a mesh-aware (parallel-importing) module",
    "R9": "lock discipline: guarded state accessed off-lock",
    "R10": "blocking call (RPC/sleep/readback/event emit) under a held lock",
}

#: modules whose arrays must stay float32 (R5): the device-math layer
#: plus the host oracles that feed it
_DTYPE_SCOPE_MARKERS = ("/ops/", "/parallel/")
_DTYPE_SCOPE_FILES = ("native.py",)

_F64_ATTRS = {
    "numpy.float64", "numpy.double", "numpy.float128", "numpy.longdouble",
    "numpy.complex128", "jax.numpy.float64", "jax.numpy.complex128",
}


# ==========================================================================
# R1 — tracer safety (interprocedural taint)
# ==========================================================================

class _FnAnalysis:
    """Analyze one function under a parameter-taint assignment.

    Flow-sensitive single-environment walk. Loop bodies are walked
    twice so loop-carried taint settles (`a = x` at the bottom of the
    body reaches an `if a:` at the top on the second walk); the hazard
    dict is keyed by (line, col, message), so re-walks never duplicate
    findings. Nested defs/lambdas are walked inline with their
    parameters tainted "maybe" (annotation-refined) — inside a jit trace
    they are almost always scan/while/cond callbacks receiving tracers.
    """

    def __init__(self, rec: FuncRecord, param_taint: Dict[str, Optional[str]],
                 project: Project) -> None:
        self.rec = rec
        self.fi = rec.file
        self.project = project
        self.param_taint = {k: v for k, v in param_taint.items() if v}
        self.env: Dict[str, Optional[str]] = {}
        self.calls: Dict[str, Dict[str, str]] = {}  # callee qual -> param taint
        self.callee_recs: Dict[str, FuncRecord] = {}
        self.hazards: Dict[Tuple[int, int, str], Finding] = {}
        self.collect = False

    # -- driver --

    def run(self, collect: bool) -> None:
        self.collect = collect
        self.env = dict(self.param_taint)
        for stmt in self.rec.node.body:
            self.stmt(stmt)

    def findings(self) -> List[Finding]:
        return [self.hazards[k] for k in sorted(self.hazards)]

    def _flag(self, node: ast.AST, message: str) -> None:
        if not self.collect:
            return
        key = (node.lineno, node.col_offset, message)
        self.hazards[key] = self.fi.finding(
            node, "R1", f"{message} in jit-compiled `{self.rec.name}`"
        )

    # -- statements --

    def stmt(self, node: ast.stmt) -> None:
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = node.value
            kind = self.eval(value) if value is not None else None
            if isinstance(node, ast.AugAssign):
                kind = _join(kind, self.eval_target_as_expr(node.target))
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                self.bind(t, kind)
        elif isinstance(node, (ast.Expr, ast.Return)):
            if node.value is not None:
                self.eval(node.value)
        elif isinstance(node, ast.If):
            self.truthiness(node.test, "`if` branch on traced value")
            for s in node.body + node.orelse:
                self.stmt(s)
        elif isinstance(node, ast.While):
            self.truthiness(node.test, "`while` condition on traced value")
            for _ in range(2):
                for s in node.body:
                    self.stmt(s)
                # the condition re-runs on loop-carried taint
                self.truthiness(node.test,
                                "`while` condition on traced value")
            for s in node.orelse:
                self.stmt(s)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            k = self.eval(node.iter)
            if k == "array":
                self._flag(node.iter, "iteration over a traced array "
                                      "(use lax.scan / lax.fori_loop)")
            for _ in range(2):
                self.bind(node.target,
                          "array" if k == "array" else ("maybe" if k else None))
                for s in node.body:
                    self.stmt(s)
            for s in node.orelse:
                self.stmt(s)
        elif isinstance(node, ast.Match):
            k = self.eval(node.subject)
            if k in _HAZARD_KINDS:
                self._flag(node.subject,
                           "`match` on a traced value (pattern matching "
                           "concretizes the tracer — use lax.switch)")
            for case in node.cases:
                self._bind_pattern(case.pattern,
                                   "maybe" if k in _HAZARD_KINDS else None)
                if case.guard is not None:
                    self.truthiness(case.guard, "`case` guard on traced value")
                for s in case.body:
                    self.stmt(s)
        elif isinstance(node, ast.Assert):
            self.truthiness(node.test, "`assert` on traced value")
            if node.msg is not None:
                self.eval(node.msg)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                k = self.eval(item.context_expr)
                if item.optional_vars is not None:
                    self.bind(item.optional_vars, k)
            for s in node.body:
                self.stmt(s)
        elif isinstance(node, ast.Try):
            for s in node.body + node.orelse + node.finalbody:
                self.stmt(s)
            for h in node.handlers:
                for s in h.body:
                    self.stmt(s)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            for p in a.posonlyargs + a.args + a.kwonlyargs:
                kind, known = _annotation_kind(p.annotation)
                self.env[p.arg] = kind if known else "maybe"
            for s in node.body:
                self.stmt(s)
            self.env[node.name] = None
        elif isinstance(node, ast.ClassDef):
            for s in node.body:
                self.stmt(s)
        elif isinstance(node, (ast.Delete,)):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.env[t.id] = None
        # Pass/Break/Continue/Import/Global/Nonlocal/Raise: nothing to do
        elif isinstance(node, ast.Raise):
            if node.exc is not None:
                self.eval(node.exc)

    def _bind_pattern(self, pat: ast.pattern, kind: Optional[str]) -> None:
        """Bind capture names of a match-case pattern; destructuring a
        traced subject yields traced pieces."""
        if isinstance(pat, ast.MatchAs):
            if pat.pattern is not None:
                self._bind_pattern(pat.pattern, kind)
            if pat.name:
                self.env[pat.name] = kind
        elif isinstance(pat, ast.MatchStar):
            if pat.name:
                self.env[pat.name] = kind
        elif isinstance(pat, ast.MatchMapping):
            for p in pat.patterns:
                self._bind_pattern(p, kind)
            if pat.rest:
                self.env[pat.rest] = kind
        elif isinstance(pat, (ast.MatchSequence, ast.MatchOr)):
            for p in pat.patterns:
                self._bind_pattern(p, kind)
        elif isinstance(pat, ast.MatchClass):
            for p in list(pat.patterns) + list(pat.kwd_patterns):
                self._bind_pattern(p, kind)
        elif isinstance(pat, ast.MatchValue):
            self.eval(pat.value)

    def bind(self, target: ast.AST, kind: Optional[str]) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = kind
        elif isinstance(target, (ast.Tuple, ast.List)):
            elt_kind = kind if kind is None else (
                "array" if kind == "array" else "maybe"
            )
            for e in target.elts:
                self.bind(e, elt_kind)
        elif isinstance(target, ast.Starred):
            self.bind(target.value, "maybe" if kind else None)
        # Attribute/Subscript targets mutate containers: no new name taint

    def eval_target_as_expr(self, target: ast.AST) -> Optional[str]:
        if isinstance(target, ast.Name):
            return self.env.get(target.id)
        return None

    # -- truthiness contexts --

    def truthiness(self, test: ast.expr, message: str) -> None:
        if isinstance(test, ast.Compare) and all(
            isinstance(o, (ast.Is, ast.IsNot)) for o in test.ops
        ):
            # `x is None` never calls __bool__ on a tracer — the blessed
            # Optional-arg branch form
            for v in [test.left] + test.comparators:
                self.eval(v)
            return
        if isinstance(test, ast.BoolOp):
            for v in test.values:
                self.truthiness(v, message)
            return
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            self.truthiness(test.operand, message)
            return
        k = self.eval(test)
        if k in _HAZARD_KINDS:
            self._flag(test, message + " (use jnp.where / lax.cond)")

    # -- expressions --

    def eval(self, node: ast.expr) -> Optional[str]:  # noqa: C901
        if isinstance(node, ast.Name):
            return self.env.get(node.id)
        if isinstance(node, ast.Constant):
            return None
        if isinstance(node, ast.Attribute):
            base = self.eval(node.value)
            if node.attr in STATIC_ATTRS:
                return None
            if base in _HAZARD_KINDS:
                return "array"
            return "maybe" if base else None
        if isinstance(node, ast.Subscript):
            base = self.eval(node.value)
            self.eval(node.slice)
            if base == "array":
                return "array"
            return "maybe" if base else None
        if isinstance(node, ast.Call):
            return self.eval_call(node)
        if isinstance(node, ast.BinOp):
            return _join(self.eval(node.left), self.eval(node.right))
        if isinstance(node, ast.UnaryOp):
            k = self.eval(node.operand)
            if isinstance(node.op, ast.Not):
                if k in _HAZARD_KINDS:
                    self._flag(node, "`not` on traced value")
                return None
            return k
        if isinstance(node, ast.BoolOp):
            # `a and b` outside an `if` still calls bool(a)
            out: Optional[str] = None
            for i, v in enumerate(node.values):
                k = self.eval(v)
                if k in _HAZARD_KINDS and i < len(node.values) - 1:
                    self._flag(v, "`and`/`or` short-circuit on traced value")
                out = _join(out, k)
            return out
        if isinstance(node, ast.Compare):
            operands = [node.left] + list(node.comparators)
            kinds = [self.eval(v) for v in operands]
            if all(isinstance(o, (ast.Is, ast.IsNot)) for o in node.ops):
                return None
            if any(isinstance(v, ast.Constant) and isinstance(v.value, str)
                   for v in operands):
                # comparing against a string constant is a host metadata
                # check (`kind == "full"`) — arrays don't compare to str
                return None
            if any(kinds) and len(node.ops) > 1:
                self._flag(node, "chained comparison on traced values "
                                 "(implicit `and` calls bool())")
            if "array" in kinds and any(isinstance(o, (ast.In, ast.NotIn))
                                        for o in node.ops):
                self._flag(node, "membership test on traced value")
            if "array" in kinds:
                return "array"
            return "maybe" if "maybe" in kinds else None
        if isinstance(node, ast.IfExp):
            self.truthiness(node.test, "conditional expression on traced value")
            return _join(self.eval(node.body), self.eval(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            kinds = [self.eval(e) for e in node.elts]
            return "pytree" if any(kinds) else None
        if isinstance(node, ast.Dict):
            kinds = [self.eval(v) for v in node.values if v is not None]
            kinds += [self.eval(k) for k in node.keys if k is not None]
            return "pytree" if any(kinds) else None
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            self._comp_generators(node.generators)
            k = self.eval(node.elt)
            return "pytree" if k else None
        if isinstance(node, ast.DictComp):
            self._comp_generators(node.generators)
            k = _join(self.eval(node.key), self.eval(node.value))
            return "pytree" if k else None
        if isinstance(node, ast.Lambda):
            a = node.args
            for p in a.posonlyargs + a.args + a.kwonlyargs:
                self.env[p.arg] = "maybe"
            self.eval(node.body)
            return None
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        if isinstance(node, ast.NamedExpr):
            k = self.eval(node.value)
            self.bind(node.target, k)
            return k
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                self.eval(v)
            return None
        if isinstance(node, ast.FormattedValue):
            self.eval(node.value)
            return None
        if isinstance(node, (ast.Await, ast.YieldFrom)):
            return self.eval(node.value)
        if isinstance(node, ast.Yield):
            return self.eval(node.value) if node.value else None
        if isinstance(node, ast.Slice):
            for part in (node.lower, node.upper, node.step):
                if part is not None:
                    self.eval(part)
            return None
        return None

    def _comp_generators(self, gens) -> None:
        for g in gens:
            k = self.eval(g.iter)
            if k == "array":
                self._flag(g.iter, "iteration over a traced array "
                                   "(use lax.scan / jnp ops)")
            self.bind(g.target,
                      "array" if k == "array" else ("maybe" if k else None))
            for cond in g.ifs:
                self.truthiness(cond, "comprehension filter on traced value")

    def eval_call(self, node: ast.Call) -> Optional[str]:
        arg_kinds = [self.eval(a) for a in node.args]
        kw_kinds = {kw.arg: self.eval(kw.value) for kw in node.keywords}
        all_kinds = arg_kinds + list(kw_kinds.values())
        any_taint = any(all_kinds)
        hazard_arg = any(k in _HAZARD_KINDS for k in all_kinds)
        name = dotted_name(node.func)
        full = resolve_dotted(name, self.fi.imports)

        # self.meth(...) / cls.meth(...): resolve within the enclosing
        # class and thread argument taint through like any first-party
        # call — without this, interprocedural R1/R2 stops dead at every
        # method boundary of class-structured jit code
        if (isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in ("self", "cls")
                and "." in self.rec.name):
            cls_prefix = self.rec.name.rsplit(".", 1)[0] + "."
            meth = self.fi.functions.get(cls_prefix + node.func.attr)
            if meth is not None:
                recv = self.env.get(node.func.value.id)
                taints: Dict[str, str] = {}
                if recv and meth.params:
                    taints[meth.params[0]] = recv  # receiver slot
                for i, k in enumerate(arg_kinds):
                    if (k and i + 1 < len(meth.params)
                            and not any(isinstance(a, ast.Starred)
                                        for a in node.args[: i + 1])):
                        taints[meth.params[i + 1]] = k
                for kwname, k in kw_kinds.items():
                    if k and kwname and kwname in meth.params:
                        taints[kwname] = k
                if taints:
                    merged = self.calls.setdefault(meth.qual, {})
                    for p, k in taints.items():
                        merged[p] = _join(merged.get(p), k) or k
                    self.callee_recs[meth.qual] = meth
                return "maybe" if (any_taint or recv) else None

        # method-style: base.method(...)
        if isinstance(node.func, ast.Attribute):
            base = self.eval(node.func.value)
            if node.func.attr in _SYNC_METHODS and base in _HAZARD_KINDS:
                self._flag(node, f"`.{node.func.attr}()` forces a traced "
                                 "value to host")
                return None
            if base in _HAZARD_KINDS:
                return "array"
            if base == "pytree":
                # dict/tuple methods (.items(), .get(), .keys()) return
                # host iterables whose elements may be traced
                return "maybe"

        if full in _HOST_RESULT_CALLS:
            return None
        if full in _CONVERSIONS:
            if hazard_arg:
                self._flag(node, f"`{full}()` on a traced value")
            return None
        if full in ("dict", "list", "tuple", "set", "frozenset", "sorted",
                    "reversed", "zip", "enumerate"):
            return "pytree" if any_taint else None
        if full and (full.startswith("jax.") or full.startswith("numpy.")
                     or full == "jax"):
            # higher-order transforms (lax.scan/while_loop/cond, vmap, …)
            # trace their callbacks: a first-party function passed by name
            # into ANY jax call runs with traced parameters
            for a in node.args:
                if isinstance(a, (ast.Name, ast.Attribute)):
                    cb = self.project.resolve_name(dotted_name(a), self.fi)
                    if cb is not None and cb.params:
                        merged = self.calls.setdefault(cb.qual, {})
                        for p in cb.params:
                            merged.setdefault(p, "maybe")
                        self.callee_recs[cb.qual] = cb
            # numpy on tracers raises/constant-folds; R2 reports the sync
            # aspect, taint-wise the result is device-shaped either way
            return "array" if any_taint else None

        callee = self.project.resolve_call(node, self.fi)
        if callee is not None:
            taints: Dict[str, str] = {}
            for i, k in enumerate(arg_kinds):
                if k and not any(isinstance(a, ast.Starred)
                                 for a in node.args[: i + 1]):
                    if i < len(callee.params):
                        taints[callee.params[i]] = k
            for kwname, k in kw_kinds.items():
                if k and kwname and kwname in callee.params:
                    taints[kwname] = k
            if taints:
                merged = self.calls.setdefault(callee.qual, {})
                for p, k in taints.items():
                    merged[p] = _join(merged.get(p), k) or k
                self.callee_recs[callee.qual] = callee
        return "maybe" if any_taint else None


#: test hook: when set, overrides the computed fixpoint iteration budget
_FIXPOINT_LIMIT: Optional[int] = None


def _jit_taint_state(project: Project) -> Dict[str, Tuple[FuncRecord, Dict[str, str]]]:
    """Fixed-point interprocedural propagation from jit roots. Returns
    qual -> (record, param taints) for every function that runs in jit
    context. Cached on the project (R1 and R2 share it)."""
    cached = getattr(project, "_graftlint_jit_state", None)
    if cached is not None:
        return cached
    state: Dict[str, Tuple[FuncRecord, Dict[str, str]]] = {}
    work: deque = deque()
    for rec in project.jit_roots():
        pins = _param_pins(rec)
        taint = {}
        for p in rec.params:
            if p in rec.static_params:
                continue
            kind, known = pins.get(p, ("maybe", False))
            kind = kind if known else "maybe"
            if kind:
                taint[p] = kind
        state[rec.qual] = (rec, taint)
        work.append(rec.qual)
    # monotone 4-level lattice: each function re-enters the worklist at
    # most a few times, so pops are bounded by ~levels × call edges. The
    # guard only exists to catch an analysis bug — tripping it must be
    # LOUD, never a silent truncation of R1/R2 coverage that lets the
    # tier-1 gate pass with unanalyzed functions
    guard = 0
    guard_limit = _FIXPOINT_LIMIT or max(
        2000, 8 * sum(len(fi.functions) for fi in project.files)
    )
    while work:
        guard += 1
        if guard > guard_limit:
            raise RuntimeError(
                f"graftlint: interprocedural taint fixpoint exceeded "
                f"{guard_limit} iterations (still {len(work)} pending) — "
                "analysis bug or pathological call graph; refusing to "
                "report partial R1/R2 coverage as clean"
            )
        qual = work.popleft()
        rec, taint = state[qual]
        an = _FnAnalysis(rec, dict(taint), project)
        an.run(collect=False)
        for callee_qual, ptaints in an.calls.items():
            callee = an.callee_recs[callee_qual]
            pins = _param_pins(callee)
            if callee.jit_root:
                # statics of a root stay static even when inline-traced
                ptaints = {p: k for p, k in ptaints.items()
                           if p not in callee.static_params}
            prev = state.get(callee_qual)
            cur = dict(prev[1]) if prev else {}
            changed = prev is None
            for p, k in ptaints.items():
                kind, known = pins.get(p, ("maybe", False))
                if known:
                    # a recognized annotation pins the entry kind: the
                    # author's declared contract beats call-site guessing
                    k = kind
                    if not k:
                        continue
                nk = _join(cur.get(p), k)
                if nk != cur.get(p):
                    cur[p] = nk or k
                    changed = True
            if changed:
                state[callee_qual] = (callee, cur)
                work.append(callee_qual)
    project._graftlint_jit_state = state
    return state


@register_rule("R1")
def rule_r1_tracer_safety(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for qual, (rec, taint) in sorted(_jit_taint_state(project).items()):
        an = _FnAnalysis(rec, dict(taint), project)
        an.run(collect=True)
        findings.extend(an.findings())
    return findings


# ==========================================================================
# R2 — host↔device sync in hot paths
# ==========================================================================

@register_rule("R2")
def rule_r2_host_sync(project: Project) -> List[Finding]:
    jit_funcs = _jit_taint_state(project)
    findings: List[Finding] = []
    for fi in project.files:
        if fi.tree is None:
            continue
        for rec in fi.functions.values():
            hot = rec.qual in jit_funcs or rec.name in HOT_FUNC_NAMES \
                or rec.name.split(".")[-1] in HOT_FUNC_NAMES
            if not hot:
                continue
            where = ("jit-compiled" if rec.qual in jit_funcs
                     else "hot-path") + f" `{rec.name}`"
            for node in ast.walk(rec.node):
                if not isinstance(node, ast.Call):
                    continue
                full = resolve_dotted(dotted_name(node.func), fi.imports)
                if full in _SYNC_CALLS:
                    findings.append(fi.finding(
                        node, "R2",
                        f"`{full}` forces a host↔device sync inside {where} "
                        "(keep device values on device; move readback to "
                        "the cycle boundary)",
                    ))
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in _SYNC_METHODS
                      and rec.qual not in jit_funcs):
                    # in jit context R1 already reports tainted .item()
                    findings.append(fi.finding(
                        node, "R2",
                        f"`.{node.func.attr}()` is a per-element device "
                        f"sync inside {where}",
                    ))
    return findings


# ==========================================================================
# R3 — retrace hazards
# ==========================================================================

@register_rule("R3")
def rule_r3_retrace(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for fi in project.files:
        if fi.tree is None:
            continue
        findings.extend(_r3_jit_in_body(fi))
        for rec in fi.functions.values():
            if not rec.jit_root or not rec.static_params:
                continue
            a = rec.node.args
            has_kwargs = a.kwarg is not None
            missing = sorted(rec.static_params - set(rec.params))
            if missing and not has_kwargs:
                findings.append(fi.finding(
                    rec.node, "R3",
                    f"static_argnames {missing} name no parameter of "
                    f"`{rec.name}` — silent retrace/TypeError hazard",
                ))
    return findings


def _r3_jit_in_body(fi: FileInfo) -> List[Finding]:
    """``jax.jit(...)`` constructed inside a function or loop builds a
    fresh wrapper (empty compile cache) per call — the classic retrace
    storm. Decorators and module-scope wrappers are the blessed forms."""
    findings: List[Finding] = []

    def walk(node: ast.AST, in_def: bool, in_loop: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                walk(dec, in_def, in_loop)
            for s in node.body:
                walk(s, True, False)
            return
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            in_loop = True
        if isinstance(node, ast.Call):
            full = resolve_dotted(dotted_name(node.func), fi.imports)
            if full in ("jax.jit", "jax.api.jit") and (in_def or in_loop):
                site = "a loop" if in_loop else "a function body"
                findings.append(fi.finding(
                    node, "R3",
                    f"jax.jit constructed inside {site}: every call "
                    "builds a fresh wrapper with an empty compile "
                    "cache — hoist to module scope or memoize",
                ))
        for child in ast.iter_child_nodes(node):
            walk(child, in_def, in_loop)

    walk(fi.tree, False, False)
    return findings


# ==========================================================================
# R4 — determinism
# ==========================================================================

_RANDOM_OK = {"Random", "SystemRandom", "getstate", "setstate"}
_NP_RANDOM_OK = {
    "default_rng", "Generator", "RandomState", "SeedSequence", "PCG64",
    "Philox", "bit_generator",
}
_DATETIME_NOW = {
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today", "datetime.datetime.today",
}


@register_rule("R4")
def rule_r4_determinism(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for fi in project.files:
        if fi.tree is None:
            continue
        for node in ast.walk(fi.tree):
            if not isinstance(node, ast.Call):
                continue
            full = resolve_dotted(dotted_name(node.func), fi.imports)
            if not full:
                continue
            if full.startswith("random.") and full.count(".") == 1:
                leaf = full.split(".")[1]
                if leaf not in _RANDOM_OK:
                    findings.append(fi.finding(
                        node, "R4",
                        f"`{full}()` uses the global random state — seed a "
                        "`random.Random(seed)` instance and thread it "
                        "through (the sim/faults idiom)",
                    ))
            elif full.startswith("numpy.random."):
                leaf = full.split(".")[2]
                if leaf not in _NP_RANDOM_OK:
                    findings.append(fi.finding(
                        node, "R4",
                        f"`{full}()` uses numpy's global RNG — use "
                        "`np.random.default_rng(seed)`",
                    ))
            elif full == "time.time":
                findings.append(fi.finding(
                    node, "R4",
                    "`time.time()` is wall-clock — inject a clock "
                    "(`clock: Callable[[], float] = time.monotonic`) so "
                    "sim/chaos runs stay deterministic",
                ))
            elif full in _DATETIME_NOW and not node.args and not node.keywords:
                findings.append(fi.finding(
                    node, "R4",
                    f"argless `{full}()` — inject a clock or pass an "
                    "explicit timezone/timestamp",
                ))
    return findings


# ==========================================================================
# R5 — dtype drift in device-math modules
# ==========================================================================

def _in_dtype_scope(fi: FileInfo) -> bool:
    rel = "/" + fi.relpath
    return (any(m in rel for m in _DTYPE_SCOPE_MARKERS)
            or any(rel.endswith("/" + f) for f in _DTYPE_SCOPE_FILES))


@register_rule("R5")
def rule_r5_dtype(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for fi in project.files:
        if fi.tree is None or not _in_dtype_scope(fi):
            continue
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.Attribute):
                full = resolve_dotted(dotted_name(node), fi.imports)
                if full in _F64_ATTRS:
                    findings.append(fi.finding(
                        node, "R5",
                        f"`{full}` in a device-math module — the solver "
                        "rides float32 end to end; widening silently "
                        "doubles memory traffic and splits jit caches",
                    ))
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg == "dtype":
                        v = kw.value
                        if isinstance(v, ast.Name) and v.id == "float":
                            findings.append(fi.finding(
                                v, "R5",
                                "`dtype=float` is float64 — spell the "
                                "narrow dtype (np.float32) explicitly",
                            ))
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if (isinstance(arg, ast.Constant)
                            and arg.value in ("float64", "complex128")):
                        findings.append(fi.finding(
                            arg, "R5",
                            f"dtype string '{arg.value}' in a device-math "
                            "module — use float32",
                        ))
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "astype" and node.args):
                    a0 = node.args[0]
                    if isinstance(a0, ast.Name) and a0.id == "float":
                        findings.append(fi.finding(
                            a0, "R5",
                            "`.astype(float)` is float64 — use np.float32",
                        ))
    return findings


# ==========================================================================
# R6 — syntax gate: Py3.10 f-string backslash (the seed breaker)
# ==========================================================================

@register_rule("R6")
def rule_r6_fstring_backslash(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for fi in project.files:
        if fi.parse_error is not None:
            line = getattr(fi.parse_error, "lineno", None) or 1
            if _looks_like_fstring_backslash(fi, line):
                findings.append(Finding(
                    fi.relpath, line, 0, "R6",
                    "f-string expression contains a backslash — a "
                    "SyntaxError on Python 3.10 (the class that broke the "
                    "seed's metrics.py); pull the escape into a variable",
                    fi.line_text(line),
                ))
            else:
                findings.append(Finding(
                    fi.relpath, line, 0, "R6",
                    f"file does not parse: {fi.parse_error}",
                    fi.line_text(line),
                ))
            continue
        # forward-compat: on interpreters where the construct parses
        # (3.12+, PEP 701), catch it from the AST so the repo stays
        # 3.10-loadable. Before 3.12 every FormattedValue in a joined
        # string shares the whole string's span (adjacent `\n` literals
        # would false-positive) — and the construct cannot parse there
        # anyway, so the parse_error path above is the real check.
        if sys.version_info < (3, 12):
            continue
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.FormattedValue):
                seg = ast.get_source_segment(fi.source, node)
                if seg and "\\" in seg:
                    findings.append(fi.finding(
                        node, "R6",
                        "backslash inside an f-string expression — "
                        "SyntaxError on Python 3.10; pull the escape into "
                        "a variable",
                    ))
    return findings


def _looks_like_fstring_backslash(fi: FileInfo, around_line: int) -> bool:
    import re

    pat = re.compile(r"""[fF][rRbB]?(['"]).*{[^{}]*\\[^}]*}.*\1""")
    lo = max(0, around_line - 3)
    hi = min(len(fi.lines), around_line + 2)
    return any(pat.search(text) for text in fi.lines[lo:hi])


# ==========================================================================
# R7 — undeclared d2h readback sites
# ==========================================================================

#: modules implementing the declared boundary itself — their internal
#: numpy materialization IS the accounting path
_R7_BOUNDARY_MODULES = ("obs/jaxtel.py",)

#: argument AST nodes that cannot be device buffers (host literals and
#: comprehensions) — np.asarray over them is host-on-host bookkeeping
_R7_HOST_LITERALS = (ast.List, ast.Tuple, ast.Dict, ast.Set, ast.Constant,
                     ast.ListComp, ast.SetComp, ast.DictComp,
                     ast.GeneratorExp, ast.JoinedStr)


@register_rule("R7")
def rule_r7_undeclared_readback(project: Project) -> List[Finding]:
    """``np.asarray``/``jax.device_get`` on a potential device value
    outside the declared ``obs.jax.readback`` boundary. The PR-7 fused
    solve+validate work shrank the steady-state cycle's d2h traffic to
    one small accounted transfer; this rule is the ratchet that keeps
    new unaccounted readback sites from sneaking in silently. Scope:
    first-party modules that import jax (pure-numpy host modules can't
    hold device buffers); obvious host literals are exempt; remaining
    legitimate sites carry scope suppressions with justifications or
    live in the committed baseline — baseline-aware like R0-R6."""
    findings: List[Finding] = []
    for fi in project.files:
        if fi.tree is None:
            continue
        rel = fi.relpath.replace("\\", "/")
        if any(rel.endswith(m) for m in _R7_BOUNDARY_MODULES):
            continue
        if rel.split("/", 1)[0] in ("tests", "tests_tpu", "scripts"):
            # offline harnesses and parity oracles read device values by
            # design; the ratchet guards the serving/production modules
            continue
        if not any(v == "jax" or v.startswith("jax.")
                   for v in fi.imports.values()):
            continue
        for node in ast.walk(fi.tree):
            if not isinstance(node, ast.Call):
                continue
            full = resolve_dotted(dotted_name(node.func), fi.imports)
            if full not in _SYNC_CALLS:
                continue
            if node.args and isinstance(node.args[0], _R7_HOST_LITERALS):
                continue
            findings.append(fi.finding(
                node, "R7",
                f"`{full}` reads a (potential) device value back outside "
                "a declared boundary — route d2h syncs through "
                "obs.jax.readback so transfer accounting (and the "
                "readback-budget gate) sees them",
            ))
    return findings


# ==========================================================================
# R8 — sharded-value gather in mesh-aware modules
# ==========================================================================

#: gather-ish method calls on a (potentially sharded) device value: the
#: per-element syncs plus the per-shard buffer access that implies the
#: caller is about to assemble the full array on host
_R8_GATHER_METHODS = _SYNC_METHODS | {"addressable_data"}

#: argument forms np.asarray may legitimately take in mesh-aware modules
#: without touching a device buffer (host literals + comprehensions)
_R8_HOST_ONLY = _R7_HOST_LITERALS

_R8_SCOPE_PREFIX = "kubernetes_tpu.parallel"


def _imports_parallel(fi: FileInfo) -> bool:
    """Does this module import the mesh layer (any form, any level)?
    ``fi.imports`` alone is not enough: the engine maps a bare
    ``import a.b.c`` to its top-level name only, so the scope check
    walks the AST for Import/ImportFrom nodes too."""
    if any(v == _R8_SCOPE_PREFIX or v.startswith(_R8_SCOPE_PREFIX + ".")
           for v in fi.imports.values()):
        return True
    for node in ast.walk(fi.tree):
        if isinstance(node, ast.Import):
            if any(a.name == _R8_SCOPE_PREFIX
                   or a.name.startswith(_R8_SCOPE_PREFIX + ".")
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom) and node.module:
            if (node.module == _R8_SCOPE_PREFIX
                    or node.module.startswith(_R8_SCOPE_PREFIX + ".")):
                return True
    return False


@register_rule("R8")
def rule_r8_mesh_gather(project: Project) -> List[Finding]:
    """``jax.device_get``/``np.asarray``/per-element sync on a potential
    device value inside a PRODUCTION module that imports
    ``kubernetes_tpu.parallel`` — i.e. a module whose values may be
    node-axis-sharded or (P, N)-shaped across the mesh. There, an
    undeclared materialization is not just an unaccounted d2h transfer
    (R7's concern): GSPMD inserts an ALL-GATHER to assemble the full
    array first, so one stray ``np.asarray`` silently moves a
    (P, N)-sized matrix across ICI and then over PCIe — the exact
    transfer the collective cost model (parallel/costmodel.py) claims
    never happens. This rule turns that falsifiable claim into a
    parse-time gate: every d2h in a mesh-aware module must ride the
    declared ``obs.jax.readback`` boundary (which gathers ONCE, with
    byte accounting) or carry a justified suppression. Scope mirrors
    R7 (tests/scripts/boundary modules exempt; host literals exempt);
    baseline-aware and tier-1-enforced like R0-R7."""
    findings: List[Finding] = []
    for fi in project.files:
        if fi.tree is None:
            continue
        rel = fi.relpath.replace("\\", "/")
        if any(rel.endswith(m) for m in _R7_BOUNDARY_MODULES):
            continue
        if rel.split("/", 1)[0] in ("tests", "tests_tpu", "scripts"):
            # parity oracles and offline harnesses gather by design;
            # the gate guards the production cycle
            continue
        if "/parallel/" in "/" + rel:
            # the placement layer itself (device_put, never a gather)
            continue
        if not _imports_parallel(fi):
            continue
        for node in ast.walk(fi.tree):
            if not isinstance(node, ast.Call):
                continue
            full = resolve_dotted(dotted_name(node.func), fi.imports)
            if full in _SYNC_CALLS:
                if node.args and isinstance(node.args[0], _R8_HOST_ONLY):
                    continue
                findings.append(fi.finding(
                    node, "R8",
                    f"`{full}` materializes a (potentially node-axis-"
                    "sharded) value on host in a mesh-aware module — "
                    "GSPMD all-gathers the full array first; route the "
                    "readback through obs.jax.readback so the gather is "
                    "deliberate, single, and byte-accounted",
                ))
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in _R8_GATHER_METHODS):
                findings.append(fi.finding(
                    node, "R8",
                    f"`.{node.func.attr}()` on a (potentially sharded) "
                    "device value in a mesh-aware module — a per-shard/"
                    "per-element gather outside the declared "
                    "obs.jax.readback boundary",
                ))
    return findings


# ==========================================================================
# R9 / R10 — lock discipline + blocking-under-lock
# ==========================================================================

#: lock constructors recognized on ``self.X = threading.Lock()`` — plus
#: any injectable factory whose name mentions "lock" (the sanitize.py
#: seam: ``self._lock = lock_factory("cache.snap")``)
_LOCK_CTOR_LEAVES = {
    "lock", "rlock", "condition", "semaphore", "boundedsemaphore",
}

#: ``# guarded-by: self._lock`` — the explicit declaration form; the
#: lock name normalizes through a leading ``self.``
_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*(?P<lock>[A-Za-z_][\w.]*)")

#: container mutations that count as WRITES for guard inference — in
#: this codebase shared state is mostly dicts/deques mutated in place,
#: not rebound
_MUTATOR_METHODS = {
    "append", "appendleft", "add", "insert", "extend", "update", "pop",
    "popleft", "popitem", "remove", "discard", "clear", "setdefault",
}

#: files R9/R10 never look at: test fakes and offline harnesses are
#: single-threaded by design, same scoping as R7/R8
_LOCK_EXEMPT_TOPDIRS = ("tests", "tests_tpu", "scripts")

#: directly-blocking operations for R10 — exactly the shapes that have
#: bitten this repo: hub RPC verbs, the declared d2h boundary, sleeps,
#: event-sink emission, and device syncs
_R10_BLOCKING_DOTTED = {"time.sleep"}
_R10_BLOCKING_METHODS = {"result", "block_until_ready", "readback"}
_R10_HUB_VERBS = {
    "bind", "bind_pod", "create_pod", "update_pod", "delete_pod",
    "patch_pod", "list_pods", "get_pod",
}
_R10_SINK_NAMES = {"event_sink"}
_R10_SINK_DESC = "event-sink emission"


def _r10_blocking_desc(node: ast.Call, imports: Dict[str, str]) -> Optional[str]:
    """Human description when this call is a known-blocking op, else
    None. Callers exclude intraclass ``self.meth()`` calls first —
    a class invoking its OWN ``delete_pod`` is in-process bookkeeping,
    not a stub RPC."""
    func = node.func
    name = dotted_name(func)
    full = resolve_dotted(name, imports)
    leaf = (name or "").split(".")[-1]
    if full in _R10_BLOCKING_DOTTED:
        return f"`{full}()`"
    if leaf == "block_until_ready" or (
            full and full.endswith(".block_until_ready")):
        return "`block_until_ready` (device sync)"
    if isinstance(func, ast.Attribute) and func.attr in _R10_BLOCKING_METHODS:
        return (f"`.{func.attr}()` "
                + ("(declared d2h readback)" if func.attr == "readback"
                   else "(device/future sync)"))
    if isinstance(func, ast.Attribute) and func.attr in _R10_HUB_VERBS:
        return f"hub RPC `.{func.attr}()`"
    if leaf in _R10_SINK_NAMES:
        return _R10_SINK_DESC
    return None


#: name tokens that mean "this is a lock" — token-wise so ``clock`` /
#: ``blocked`` never match
_LOCKISH_TOKENS = {"lock", "rlock", "mutex", "cond", "condition"}


def _lockish_name(leaf: str) -> bool:
    tokens = leaf.lower().strip("_").split("_")
    return any(t in _LOCKISH_TOKENS for t in tokens)


def _is_lock_ctor(call: ast.Call, imports: Dict[str, str]) -> bool:
    name = dotted_name(call.func)
    full = resolve_dotted(name, imports) or ""
    leaf = full.split(".")[-1].lower()
    if full.startswith("threading.") and leaf in _LOCK_CTOR_LEAVES:
        return True
    # injectable lock factories (kubernetes_tpu/sanitize.py seam)
    return _lockish_name((name or "").split(".")[-1])


def _lockish_expr(expr: ast.expr, locks: Set[str]) -> Optional[str]:
    """Dotted name of a with-item that acquires a lock, else None.
    ``self.X`` for a known class lock always counts; otherwise the last
    segment must look lock-like (lock / cond / mutex)."""
    name = dotted_name(expr)
    if name is None:
        return None
    parts = name.split(".")
    if len(parts) == 2 and parts[0] == "self" and parts[1] in locks:
        return name
    if _lockish_name(parts[-1]):
        return name
    return None


class _MethodLockScan:
    """One method's lock-relevant events: attribute accesses (with the
    self-locks held at that point), intraclass ``self.meth()`` call
    sites, and R10-relevant blocking calls (with every held lock expr,
    including non-self ones like ``loop.lock``)."""

    def __init__(self, cls: "_ClassLockInfo", meth_name: str,
                 node: ast.AST) -> None:
        self.cls = cls
        self.name = meth_name
        self.node = node
        #: (attr, is_write, frozenset(held self-locks), node)
        self.accesses: List[Tuple[str, bool, frozenset, ast.AST]] = []
        #: (callee method leaf name, frozenset(held self-locks),
        #:  tuple(held lock exprs), node)
        self.self_calls: List[Tuple[str, frozenset, Tuple[str, ...], ast.AST]] = []
        #: (description, tuple(held lock exprs), node) — direct blocking
        self.blocking: List[Tuple[str, Tuple[str, ...], ast.AST]] = []
        #: does this method directly call the event sink?
        self.emits = False

    # -- walk ---------------------------------------------------------------

    def run(self) -> None:
        for stmt in self.node.body:
            self._stmt(stmt, frozenset(), ())

    def _stmt(self, node: ast.stmt, held: frozenset,
              held_exprs: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # a nested def runs LATER, on whatever thread calls it —
            # never under the locks held at definition time
            for s in getattr(node, "body", ()):
                self._stmt(s, frozenset(), ())
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = set(held)
            new_exprs = list(held_exprs)
            for item in node.items:
                self._expr(item.context_expr, held, held_exprs)
                lk = _lockish_expr(item.context_expr, self.cls.locks)
                if lk is not None:
                    new_exprs.append(lk)
                    parts = lk.split(".")
                    if (len(parts) == 2 and parts[0] == "self"
                            and parts[1] in self.cls.locks):
                        new_held = new_held | {parts[1]}
            for s in node.body:
                self._stmt(s, frozenset(new_held), tuple(new_exprs))
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if node.value is not None:
                self._expr(node.value, held, held_exprs)
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                self._target(t, held, held_exprs,
                             aug=isinstance(node, ast.AugAssign))
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                self._target(t, held, held_exprs)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._stmt(child, held, held_exprs)
            elif isinstance(child, ast.expr):
                self._expr(child, held, held_exprs)
            elif isinstance(child, ast.excepthandler):
                for s in child.body:
                    self._stmt(s, held, held_exprs)

    def _self_attr(self, node: ast.AST) -> Optional[str]:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    def _target(self, node: ast.AST, held: frozenset,
                held_exprs: Tuple[str, ...], aug: bool = False) -> None:
        attr = self._self_attr(node)
        if attr is not None:
            self._record(attr, True, held, node)
            return
        if isinstance(node, ast.Subscript):
            base = self._self_attr(node.value)
            if base is not None:
                # self.A[k] = v mutates A in place
                self._record(base, True, held, node.value)
            else:
                self._expr(node.value, held, held_exprs)
            self._expr(node.slice, held, held_exprs)
            return
        if isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                self._target(e, held, held_exprs)
            return
        if isinstance(node, ast.Starred):
            self._target(node.value, held, held_exprs)
            return
        if isinstance(node, ast.Attribute):
            self._expr(node.value, held, held_exprs)

    def _record(self, attr: str, is_write: bool, held: frozenset,
                node: ast.AST) -> None:
        if attr in self.cls.locks or attr in self.cls.method_names:
            return
        self.accesses.append((attr, is_write, held, node))

    def _expr(self, node: ast.expr, held: frozenset,
              held_exprs: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.Lambda,)):
            # runs later, lock-free (same as nested defs)
            self._expr(node.body, frozenset(), ())
            return
        if isinstance(node, ast.Call):
            self._call(node, held, held_exprs)
            return
        attr = self._self_attr(node)
        if attr is not None:
            self._record(attr, False, held, node)
            self._expr(node.value, held, held_exprs)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, held, held_exprs)

    def _call(self, node: ast.Call, held: frozenset,
              held_exprs: Tuple[str, ...]) -> None:
        func = node.func
        meth = self._self_attr(func)
        intraclass = meth is not None and meth in self.cls.method_names
        desc = (None if intraclass
                else _r10_blocking_desc(node, self.cls.fi.imports))
        if desc is not None and desc == _R10_SINK_DESC:
            self.emits = True
        if desc is not None and held_exprs:
            self.blocking.append((desc, held_exprs, node))
        # intraclass call edge: self.meth(...) — an in-process call, not
        # a stub RPC, even when the method name is a hub verb; whatever
        # blocking IT does is reached through the entry/emitter closures
        if intraclass:
            self.self_calls.append((meth, held, held_exprs, node))
        # `self.A.append(x)` mutates A in place: a WRITE for guard
        # inference — the dominant shape for this codebase's shared
        # deques/dicts, which are mutated, not rebound
        if isinstance(func, ast.Attribute) and not intraclass:
            base = self._self_attr(func.value)
            if base is not None:
                self._record(base, func.attr in _MUTATOR_METHODS,
                             held, func.value)
            else:
                self._expr(func.value, held, held_exprs)
        for a in node.args:
            self._expr(a, held, held_exprs)
        for kw in node.keywords:
            self._expr(kw.value, held, held_exprs)


class _ClassLockInfo:
    """Per-class lock model: which attributes are locks, which state
    they guard (declared or inferred), and which methods are only ever
    entered with a lock already held."""

    def __init__(self, fi: FileInfo, node: ast.ClassDef) -> None:
        self.fi = fi
        self.node = node
        self.locks: Set[str] = set()
        self.declared: Dict[str, str] = {}  # attr -> lock attr
        self.method_names: Set[str] = set()
        self.scans: Dict[str, _MethodLockScan] = {}
        #: attr -> (lock, "declared"|"inferred", locked_writes, writes)
        self.guarded: Dict[str, Tuple[str, str, int, int]] = {}
        #: method leaf name -> self-locks guaranteed held on entry
        self.entry: Dict[str, frozenset] = {}
        #: methods that (transitively, intraclass) emit events
        self.emitters: Set[str] = set()

    # -- construction -------------------------------------------------------

    def build(self) -> None:
        methods = [n for n in self.node.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        self.method_names = {m.name for m in methods}
        for m in methods:
            self._find_locks_and_declarations(m)
        # class-level ``# guarded-by:`` annotations on assignments
        for n in self.node.body:
            if isinstance(n, (ast.Assign, ast.AnnAssign)):
                self._declare_from_line(n)
        if not self.locks:
            return
        for m in methods:
            if m.name in ("__init__", "__post_init__"):
                continue
            scan = _MethodLockScan(self, m.name, m)
            scan.run()
            self.scans[m.name] = scan
        self._infer_guards()
        self._entry_closure()
        self._emitter_closure()

    def _find_locks_and_declarations(self, meth: ast.AST) -> None:
        for node in ast.walk(meth):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and _is_lock_ctor(node.value, self.fi.imports)):
                        self.locks.add(t.attr)
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                self._declare_from_line(node)

    def _declare_from_line(self, node: ast.stmt) -> None:
        for ln in range(node.lineno, (node.end_lineno or node.lineno) + 1):
            if not 1 <= ln <= len(self.fi.lines):
                continue
            m = _GUARDED_BY_RE.search(self.fi.lines[ln - 1])
            if m is None:
                continue
            lock = m.group("lock")
            if lock.startswith("self."):
                lock = lock[len("self."):]
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    self.declared[t.attr] = lock
                elif isinstance(t, ast.Name):
                    self.declared[t.id] = lock
            return

    # -- guard inference ----------------------------------------------------

    def _infer_guards(self) -> None:
        for attr, lock in self.declared.items():
            if lock in self.locks:
                self.guarded[attr] = (lock, "declared", 0, 0)
        writes: Dict[str, List[frozenset]] = {}
        for scan in self.scans.values():
            for attr, is_write, held, _node in scan.accesses:
                if is_write:
                    writes.setdefault(attr, []).append(held)
        for attr, helds in writes.items():
            if attr in self.guarded:
                continue
            total = len(helds)
            best_lock, best_k = None, 0
            for lock in self.locks:
                k = sum(1 for h in helds if lock in h)
                if k > best_k:
                    best_lock, best_k = lock, k
            if best_lock is not None and total and best_k / total >= 0.8:
                self.guarded[attr] = (best_lock, "inferred", best_k, total)

    # -- interprocedural closures (intraclass call graph) -------------------

    def _entry_closure(self) -> None:
        # *_locked is the codebase's declared caller-holds-the-lock
        # convention (cache._refresh_host_locked); everything else starts
        # lock-free and is promoted only when EVERY intraclass call site
        # provably holds the lock
        for name in self.scans:
            self.entry[name] = (frozenset(self.locks)
                                if name.endswith("_locked") else frozenset())
        sites: Dict[str, List[Tuple[str, frozenset]]] = {}
        for scan in self.scans.values():
            for callee, held, _exprs, _node in scan.self_calls:
                sites.setdefault(callee, []).append((scan.name, held))
        for _ in range(len(self.scans) + 2):
            changed = False
            for name, scan in self.scans.items():
                if name.endswith("_locked"):
                    continue
                calls = sites.get(name)
                if not calls:
                    continue
                new = frozenset.intersection(*[
                    held | self.entry.get(caller, frozenset())
                    for caller, held in calls
                ])
                if new != self.entry[name]:
                    self.entry[name] = new
                    changed = True
            if not changed:
                break

    def _emitter_closure(self) -> None:
        self.emitters = {n for n, s in self.scans.items() if s.emits}
        for _ in range(len(self.scans) + 2):
            grown = False
            for name, scan in self.scans.items():
                if name in self.emitters:
                    continue
                if any(callee in self.emitters
                       for callee, _h, _e, _n in scan.self_calls):
                    self.emitters.add(name)
                    grown = True
            if not grown:
                break


def _lock_state(project: Project) -> List[_ClassLockInfo]:
    """Per-class lock models for every production file; cached on the
    project (R9 and R10 share it, like the R1/R2 jit-taint cache)."""
    cached = getattr(project, "_graftlint_lock_state", None)
    if cached is not None:
        return cached
    out: List[_ClassLockInfo] = []
    for fi in project.files:
        if fi.tree is None:
            continue
        rel = fi.relpath.replace("\\", "/")
        if rel.split("/", 1)[0] in _LOCK_EXEMPT_TOPDIRS:
            continue
        for node in ast.walk(fi.tree):
            if isinstance(node, ast.ClassDef):
                info = _ClassLockInfo(fi, node)
                info.build()
                if info.locks:
                    out.append(info)
    project._graftlint_lock_state = out
    return out


@register_rule("R9")
def rule_r9_lock_discipline(project: Project) -> List[Finding]:
    """Guarded state accessed off-lock. An attribute is guarded by a
    lock when a ``# guarded-by: self._lock`` comment says so, or when
    >= 80% of its writes (rebinds AND in-place container mutations,
    ``__init__`` excluded — construction precedes sharing) happen under
    ``with self._lock``. Every other access — reads included, because
    unlocked snapshot reads were exactly the PR-8/PR-14 bug class —
    must hold that lock, either lexically or by being a method whose
    every intraclass call site holds it (``self._helper()`` under the
    lock, the ``*_locked`` naming convention)."""
    findings: List[Finding] = []
    for info in _lock_state(project):
        for scan in info.scans.values():
            entry = info.entry.get(scan.name, frozenset())
            for attr, is_write, held, node in scan.accesses:
                g = info.guarded.get(attr)
                if g is None:
                    continue
                lock, how, k, n = g
                if lock in held or lock in entry:
                    continue
                basis = ("declared guarded-by" if how == "declared"
                         else f"inferred from {k}/{n} locked writes")
                verb = "written" if is_write else "read"
                findings.append(info.fi.finding(
                    node, "R9",
                    f"`self.{attr}` is guarded by `self.{lock}` ({basis}) "
                    f"but {verb} here without holding it — a data race "
                    f"with the locked writers (torn reads / lost updates)",
                ))
    return findings


@register_rule("R10")
def rule_r10_blocking_under_lock(project: Project) -> List[Finding]:
    """Known-blocking operations while a lock is statically held — the
    exact shape of the PR-14 watchdog-events bug (events emitted inside
    the watchdog mutex, deadlocking any sink that calls back into the
    ledger). Blocking set: hub RPC verbs, ``obs.jax.readback``,
    ``time.sleep``, event-sink emission, ``.result()`` /
    ``block_until_ready``. Held means: inside ``with <lock>`` (any
    lock-named context manager, self or not), or in a method whose
    every intraclass call site holds one (incl. ``*_locked``).
    Collect what you need under the lock, drop it, THEN block."""
    findings: List[Finding] = []
    for info in _lock_state(project):
        for scan in info.scans.values():
            # blocking ops under a lexically held with-lock
            for desc, held_exprs, node in scan.blocking:
                locks = ", ".join(f"`{e}`" for e in held_exprs)
                findings.append(info.fi.finding(
                    node, "R10",
                    f"{desc} while holding {locks} — blocking under a "
                    "lock stalls every thread contending for it (and an "
                    "emission sink calling back in deadlocks); collect "
                    "under the lock, release, then block",
                ))
            # blocking ops in methods whose every intraclass call site
            # holds a lock (incl. *_locked), and emitter methods invoked
            # under a lexically held lock
            entry = info.entry.get(scan.name, frozenset())
            if entry:
                locks = ", ".join(f"`self.{l}`" for l in sorted(entry))
                for node in _r10_unlocked_blocking_nodes(scan):
                    findings.append(info.fi.finding(
                        node[1], "R10",
                        f"{node[0]} in `{scan.name}`, which is only ever "
                        f"called with {locks} held — blocking under a "
                        "caller-held lock; hoist the blocking work out "
                        "of the locked region",
                    ))
            for callee, held, held_exprs, node in scan.self_calls:
                if held_exprs and callee in info.emitters:
                    locks = ", ".join(f"`{e}`" for e in held_exprs)
                    findings.append(info.fi.finding(
                        node, "R10",
                        f"`self.{callee}()` emits events and is called "
                        f"while holding {locks} — the watchdog-events "
                        "bug shape; emit after the lock drops",
                    ))
    return findings


def _r10_unlocked_blocking_nodes(scan: _MethodLockScan):
    """Blocking calls in a scan that are NOT under a lexical with-lock
    (those already reported) — used for caller-held-lock methods."""
    out = []
    seen_lex = {id(n) for _d, _e, n in scan.blocking}

    class _V(ast.NodeVisitor):
        def visit_Call(self, node: ast.Call) -> None:
            if id(node) not in seen_lex:
                func = node.func
                intraclass = (isinstance(func, ast.Attribute)
                              and isinstance(func.value, ast.Name)
                              and func.value.id == "self"
                              and func.attr in scan.cls.method_names)
                if not intraclass:
                    desc = _r10_blocking_desc(node, scan.cls.fi.imports)
                    if desc is not None:
                        out.append((desc, node))
            self.generic_visit(node)

    _V().visit(scan.node)
    return out


# ==========================================================================
# R0 — suppression hygiene
# ==========================================================================

@register_rule("R0")
def rule_r0_suppression_hygiene(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for fi in project.files:
        for d in fi.suppressions.hygiene:
            if d.form == "malformed":
                msg = ("malformed graftlint directive — expected "
                       "`# graftlint: disable=R2 -- justification`")
            elif not d.why.strip():
                msg = (f"suppression of {','.join(d.rules) or '?'} has no "
                       "justification — add ` -- <why this is safe>`")
            elif any(r not in RULE_IDS for r in d.rules):
                msg = f"unknown rule id in suppression: {d.rules}"
            else:
                msg = ("disable-scope directive is not attached to a "
                       "def/class header")
            findings.append(Finding(fi.relpath, d.line, 0, "R0", msg,
                                    fi.line_text(d.line)))
    return findings


def ensure_registered() -> None:
    """Importing this module registers every rule; hook for the engine."""
