"""graftlint — AST-based tracer-safety / determinism / host-sync linter.

The jit-compiled ops layer only surfaces tracer leaks, host↔device syncs
and retrace storms at runtime, on the shapes a test happened to exercise.
graftlint moves those checks to parse time: a cross-file jit call graph
decides which functions run under tracing, an interprocedural taint pass
decides which values are traced there, and the rule classes (R1–R10,
plus R0 suppression hygiene) turn the hazards into findings a tier-1
test can enforce.

Rule classes
------------

==== =================================================================
R0   suppression hygiene — every inline disable needs a justification
R1   tracer-unsafe Python in jit-compiled code (``if``/``while``/
     ``bool()``/``int()``/``float()``/``.item()``/iteration on traced)
R2   host↔device sync in hot paths (``np.asarray``/``np.array``/
     ``device_get``/``.item()`` inside the per-cycle solve loop)
R3   retrace hazards (``jax.jit`` constructed per call; bogus
     ``static_argnames``)
R4   non-determinism (bare ``random.*``/``np.random.*`` global state,
     ``time.time()``, argless ``datetime.now()``)
R5   dtype drift (float64 in device-math modules)
R6   Py3.10 f-string backslash (the seed-breaking SyntaxError class)
R7   d2h readback outside the declared ``obs.jax.readback`` boundary
R8   sharded-value gather in a mesh-aware module
R9   lock discipline — ``# guarded-by:`` (declared or inferred) state
     accessed without its lock
R10  blocking under a lock (hub RPC verbs, ``time.sleep``, readback,
     ``.result()``/``block_until_ready``, event-sink emission)
==== =================================================================

Suppression forms (justification after ``--`` is mandatory, R0-checked)::

    x = np.asarray(dev)  # graftlint: disable=R2 -- deliberate readback
    # graftlint: disable=R4 -- wall time is the payload here
    stamp = time.time()
    # graftlint: disable-scope=R2 -- host oracle: CPU math by design
    def _exact_solve(...): ...

Programmatic entry points: :func:`run_lint` (paths → findings) and
:func:`lint_source` (one source string → findings, used by
``kubernetes_tpu.testing.lint_clean``).
"""

from kubernetes_tpu.lint.engine import (
    Finding,
    Project,
    lint_source,
    run_lint,
)
from kubernetes_tpu.lint.report import (
    load_baseline,
    render_json,
    render_text,
    subtract_baseline,
    write_baseline,
)

__all__ = [
    "Finding",
    "Project",
    "lint_source",
    "run_lint",
    "load_baseline",
    "render_json",
    "render_text",
    "subtract_baseline",
    "write_baseline",
]
