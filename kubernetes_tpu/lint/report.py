"""graftlint output + baseline handling.

The baseline file grandfathers findings the team has decided not to fix
yet: a committed JSON map of line-number-free fingerprints (rule + file
+ normalized snippet + occurrence index), so edits elsewhere in a file
never invalidate it. New findings — anything not in the baseline — fail
the run; fixed findings simply age out the next time the baseline is
rewritten (``--write-baseline``).
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Dict, Iterable, List, Sequence, Tuple

from kubernetes_tpu.lint.engine import Finding

BASELINE_VERSION = 1


def render_text(findings: Sequence[Finding], baselined: int = 0) -> str:
    out: List[str] = []
    for f in findings:
        out.append(f"{f.path}:{f.line}:{f.col + 1}: {f.rule} {f.message}")
        if f.snippet:
            out.append(f"    {f.snippet}")
    counts = Counter(f.rule for f in findings)
    if findings:
        per_rule = ", ".join(f"{r}={n}" for r, n in sorted(counts.items()))
        out.append("")
        out.append(f"graftlint: {len(findings)} finding(s) ({per_rule})"
                   + (f", {baselined} baselined" if baselined else ""))
    else:
        out.append("graftlint: clean"
                   + (f" ({baselined} baselined)" if baselined else ""))
    return "\n".join(out)


def render_json(findings: Sequence[Finding], baselined: int = 0) -> str:
    payload = {
        "version": BASELINE_VERSION,
        "findings": [f.as_dict() for f in findings],
        "counts": dict(sorted(Counter(f.rule for f in findings).items())),
        "baselined": baselined,
    }
    return json.dumps(payload, indent=2, sort_keys=True) + "\n"


def write_baseline(findings: Sequence[Finding], path: str) -> None:
    entries: Dict[str, Dict[str, object]] = {}
    for f in findings:
        entries[f.fingerprint()] = {
            "rule": f.rule,
            "path": f.path,
            "snippet": " ".join(f.snippet.split()),
            "occurrence": f.occurrence,
        }
    payload = {"version": BASELINE_VERSION, "findings": entries}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


def load_baseline(path: str) -> Dict[str, Dict[str, object]]:
    with open(path, "r", encoding="utf-8") as fh:
        payload = json.load(fh)
    if payload.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {payload.get('version')!r}, "
            f"expected {BASELINE_VERSION}"
        )
    findings = payload.get("findings", {})
    if not isinstance(findings, dict):
        raise ValueError(f"baseline {path}: 'findings' must be a mapping")
    return findings


def subtract_baseline(
    findings: Sequence[Finding], baseline: Dict[str, Dict[str, object]]
) -> Tuple[List[Finding], int]:
    """Split findings into (new, n_baselined) by fingerprint.

    Fingerprints are line-free, so identical snippets in one file are
    told apart only by occurrence index — when a fresh copy of an
    already-baselined snippet appears, WHICH copy gets blamed is
    positional, not causal. Such findings carry an explicit warning so
    nobody "fixes" a pre-existing site and leaves the new one
    grandfathered."""
    fresh: List[Finding] = []
    matched = 0
    sibling_keys = Counter(
        (e.get("rule"), e.get("path"), e.get("snippet"))
        for e in baseline.values() if isinstance(e, dict)
    )
    for f in findings:
        if f.fingerprint() in baseline:
            matched += 1
            continue
        n = sibling_keys.get((f.rule, f.path, " ".join(f.snippet.split())), 0)
        if n:
            f = Finding(
                f.path, f.line, f.col, f.rule,
                f.message + f" [{n} identical baselined occurrence(s) in "
                "this file — the NEW copy may be at a different line than "
                "the one reported here]",
                f.snippet, occurrence=f.occurrence,
            )
        fresh.append(f)
    return fresh, matched


def per_rule_counts(findings: Iterable[Finding]) -> Dict[str, int]:
    return dict(sorted(Counter(f.rule for f in findings).items()))
