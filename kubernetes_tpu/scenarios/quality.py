"""Host side of the scenario placement-quality surface: decode of the
device :func:`~kubernetes_tpu.ops.scenario_cost.quality_reduce` vector,
the gang all-or-nothing bookkeeping (computed from the already-read-back
assignment — zero extra readback bytes), and the ONE source of truth for
the ``mean_score`` / ``balanced`` solution-score numbers the bench and
``scripts/sinkhorn_quality.py`` report (``node_resources_score`` lived
in bench.py as a private host recomputation before this module; both
callers now fold onto it here)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from kubernetes_tpu.ops.scenario_cost import QUALITY_FIELDS


def decode_quality(vec) -> Dict[str, float]:
    """Read-back (len(QUALITY_FIELDS),) f32 vector -> named score dict.
    Counting fields decode as ints; fractions round to 4 places."""
    out: Dict[str, float] = {}
    arr = np.asarray(vec, np.float64).reshape(-1)
    for i, name in enumerate(QUALITY_FIELDS):
        v = float(arr[i])
        if name in ("nodes_used", "nodes_used_batch", "placed"):
            out[name] = int(round(v))
        else:
            out[name] = round(v, 4)
    return out


def slice_distance_host(za, zb, superpod: int = 4):
    """Numpy twin of :func:`kubernetes_tpu.ops.scenario_cost.
    slice_distance` — the ONE host-side spelling of the hierarchical
    ICI metric (0 = same slice, 1 = same superpod, 2 = fabric; -1 =
    unlabeled is always fabric), so the reported locality score cannot
    drift from the solve objective (parity pinned in
    tests/test_scenarios.py). Broadcasts like the operands."""
    za = np.asarray(za)
    zb = np.asarray(zb)
    sp = max(int(superpod), 1)
    labeled = (za >= 0) & (zb >= 0)
    return np.where(labeled & (za == zb), 0,
                    np.where(labeled & ((za // sp) == (zb // sp)), 1, 2))


def gang_stats(batch, assigned, zone_of_node: Optional[Sequence[int]] = None,
               superpod: int = 4) -> Dict[str, float]:
    """Gang all-or-nothing bookkeeping over the cycle's FINAL host
    assignment (post gang-rollback): group success rate, partial binds
    (the atomicity invariant — MUST be 0; the scheduler's rollback
    enforces it and this number is how a bench/gate observes it), and —
    when ``zone_of_node`` (host zone index per node row) is given —
    mean intra-gang slice locality: the average pairwise-hop SAVINGS of
    each placed gang vs cross-fabric (2.0 = whole gang on one slice,
    0.0 = fully scattered)."""
    groups: Dict[str, List[int]] = {}
    for i, p in enumerate(batch):
        if p.pod_group:
            groups.setdefault(p.pod_group, []).append(i)
    total = len(groups)
    placed_groups = 0
    partial = 0
    locality: List[float] = []
    for idxs in groups.values():
        n_placed = sum(1 for i in idxs if int(assigned[i]) >= 0)
        if n_placed == len(idxs):
            placed_groups += 1
            if zone_of_node is not None and len(idxs) > 1:
                zs = np.asarray(
                    [int(zone_of_node[int(assigned[i])]) for i in idxs])
                d = slice_distance_host(zs[:, None], zs[None, :],
                                        superpod)
                iu = np.triu_indices(len(idxs), k=1)
                locality.append(float(np.mean(2.0 - d[iu])))
        elif n_placed:
            partial += 1
    return {
        "gang_groups": total,
        "gangs_placed": placed_groups,
        "gang_success_rate": (round(placed_groups / total, 4)
                              if total else 1.0),
        "gang_partial_binds": partial,
        **({"gang_locality": round(float(np.mean(locality)), 4)}
           if locality else {}),
    }


def node_resources_score(alloc, requested, assigned) -> Dict[str, float]:
    """Aggregate NodeResources score of a solution: mean over PLACED
    pods of their node's LeastRequested + BalancedResourceAllocation
    score at the FINAL usage state (same rule for every solver, so
    solutions are comparable). Mirrors resource_allocation.go:39
    arithmetic: LeastRequested = ((cap-req)*10/cap averaged over
    cpu,mem); Balanced = 10 - |cpuFrac - memFrac|*10.

    THE single source of the ``mean_score``/``balanced`` figures:
    ``bench.node_resources_score`` and ``scripts/sinkhorn_quality.py``
    both delegate here (they used to carry private copies of this
    arithmetic that could drift)."""
    from kubernetes_tpu.snapshot import RES_CPU, RES_MEM

    alloc = np.asarray(alloc, np.float64)
    req = np.asarray(requested, np.float64)
    assigned = np.asarray(assigned)
    placed = assigned[assigned >= 0]
    if placed.size == 0:
        return {"mean_score": 0.0, "least_requested": 0.0, "balanced": 0.0}
    cap_cpu = np.maximum(alloc[:, RES_CPU], 1e-9)
    cap_mem = np.maximum(alloc[:, RES_MEM], 1e-9)
    fr_cpu = np.clip(req[:, RES_CPU] / cap_cpu, 0.0, 1.0)
    fr_mem = np.clip(req[:, RES_MEM] / cap_mem, 0.0, 1.0)
    lr = ((1.0 - fr_cpu) * 10.0 + (1.0 - fr_mem) * 10.0) / 2.0
    ba = 10.0 - np.abs(fr_cpu - fr_mem) * 10.0
    per_node = lr + ba
    return {
        "mean_score": round(float(per_node[placed].mean()), 4),
        "least_requested": round(float(lr[placed].mean()), 4),
        "balanced": round(float(ba[placed].mean()), 4),
    }
