"""Scenario packs: pluggable solve objectives + quality-gated
placement scores over the dense (P, N) formulation (docs/scenarios.md).

Device cost kernels and the quality reduction live in
:mod:`kubernetes_tpu.ops.scenario_cost` (graftlint R2/R3/R7
discipline); this package is the host orchestration: pack definitions
(packs.py), the in-batch preemption cascade (cascade.py), and the
quality decode / gang bookkeeping / shared solution scores
(quality.py)."""

from kubernetes_tpu.scenarios.cascade import CascadeSelection, select_cascade
from kubernetes_tpu.scenarios.packs import (
    SCENARIO_REGISTRY,
    ConsolidationPack,
    GangTopologyPack,
    ScenarioPack,
    resolve_pack,
)
from kubernetes_tpu.scenarios.quality import (
    decode_quality,
    gang_stats,
    node_resources_score,
)

__all__ = [
    "SCENARIO_REGISTRY",
    "CascadeSelection",
    "ConsolidationPack",
    "GangTopologyPack",
    "ScenarioPack",
    "decode_quality",
    "gang_stats",
    "node_resources_score",
    "resolve_pack",
    "select_cascade",
]
