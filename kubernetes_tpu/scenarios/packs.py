"""Scenario packs — pluggable solve objectives over the dense (P, N)
formulation (docs/scenarios.md; ROADMAP item 4, "schedule what the
papers schedule").

A :class:`ScenarioPack` owns three seams the scheduler threads through
its EXISTING machinery (no solver forks):

- **weights** — a priority-weight override; the re-weighted kernels are
  recomputed per round by every tier of the degradation ladder, so the
  objective survives batch -> batch-single -> batch-cpu -> greedy
  unchanged;
- **cost** — an optional (P, N) device term folded into ``extra_score``
  (the same seam extenders and score plugins use), built by the jitted
  kernels in :mod:`kubernetes_tpu.ops.scenario_cost`;
- **quality** — the per-cycle placement-quality readback
  (ops/scenario_cost.quality_reduce -> scenarios/quality.decode) plus
  host-side gang bookkeeping, landing on CycleResult / the flight
  record / ``scheduler_scenario_quality``.

Two packs ship:

- ``consolidation`` — "Priority Matters"-style bin packing: minimize
  nodes used / maximize priority-weighted headroom. MostRequested
  replaces the stock spreading objective, a flat occupied-node bias
  covers the open-a-new-node step, and priority tiers ride the queue
  order the solvers already honor. Preemption runs as an IN-BATCH
  cascade (scenarios/cascade.py): victims and displaced pods re-enter
  one dense solve in the same cycle instead of looping per-pod through
  the nominate-and-wait path.
- ``gang-topology`` — Tesserae-style DL placement: multi-slice TPU
  gangs score nodes by hierarchical slice distance to a per-gang home
  slice (biggest gang -> freest slice, host-side greedy over the host
  mirror — no readback), with the scheduler's existing all-or-nothing
  group semantics enforcing atomicity at scale.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class ScenarioPack:
    """Base pack: no cost term, no weight override, quality on."""

    name = ""
    #: route preemption through the in-batch cascade when the scenario
    #: config asks for it (consolidation turns this on)
    wants_cascade = False
    #: the pack's cost term survives restriction to a candidate-column
    #: frame — i.e. ``cost`` depends on the node table rows alone (a
    #: gathered (P, C) sub-table sees the same per-column values), not
    #: on global cross-column structure. Packs that opt in ride the
    #: sparsity-first restricted/pipelined paths; the default keeps
    #: unknown packs on the dense oracle.
    restricted_ok = False

    def __init__(self, config) -> None:
        self.config = config

    def weights(self, base: Optional[Dict[str, float]]
                ) -> Optional[Dict[str, float]]:
        """Priority-weight override (None = keep the configured set)."""
        return base

    def cost(self, batch, nt, node_order, dp, dn):
        """Optional (P, N) device score term for THIS cycle's solve.
        ``batch``/``nt``/``node_order`` are host-side (the pack may
        derive small per-pod arrays from them — uploads only, never a
        readback); ``dp``/``dn`` are the cycle's device tables (mesh
        placement included, so the term inherits the node-axis
        sharding). None = no term (the lean fast path stays open)."""
        return None

    def quality_host(self, batch, assigned, nt) -> Dict[str, float]:
        """Pack-specific host-side scores over the final assignment
        (already read back — zero extra readback bytes)."""
        return {}

    def candidate_hint(self, batch, nt, node_order) -> Optional[np.ndarray]:
        """(N,) bool mask of columns the restricted path should keep in
        the candidate frame for this batch (HINT_BOOST seam), or None.
        Host-side only — the mask is uploaded, never read back. Packs
        whose cost term concentrates on specific columns (e.g. a gang's
        home slice) use this so top-C restriction cannot starve them."""
        return None


class ConsolidationPack(ScenarioPack):
    """Minimize-nodes-used / maximize-headroom under priority tiers."""

    name = "consolidation"
    # consolidation_bias is a per-column function of dn (occupancy +
    # headroom) — restricting to candidate columns preserves it exactly
    restricted_ok = True

    @property
    def wants_cascade(self) -> bool:
        return self.config.preempt_in_batch

    def weights(self, base):
        # the packing objective REPLACES the spreading one: fill the
        # fullest feasible node (MostRequested), keep node-local
        # balance so cpu/mem exhaust together, drop every spreading
        # kernel. The bias term below covers the open-a-new-node step.
        return {
            "MostRequestedPriority": 3,
            "BalancedResourceAllocation": 1,
        }

    def cost(self, batch, nt, node_order, dp, dn):
        import jax.numpy as jnp

        from kubernetes_tpu.ops.scenario_cost import consolidation_bias

        return consolidation_bias(
            dp.valid, dn, jnp.float32(self.config.cost_weight),
            fill_block=self.config.fill_block)


class GangTopologyPack(ScenarioPack):
    """Topology-aware DL gangs: slice-distance cost to per-gang home
    slices, all-or-nothing groups (the scheduler's gang rollback)."""

    name = "gang-topology"
    # gang_topology_score is per-column (slice distance of each node's
    # zone to the pod's home zone); candidate_hint below keeps the home
    # slices' columns in the frame so restriction can't strand a gang
    restricted_ok = True

    # graftlint: disable-scope=R7 -- nt is the HOST-mirror NodeTable
    # (numpy arrays the packer built on host); no device value ever
    # crosses here — the home-zone greedy is upload-only by design
    def _home_zones(self, batch, nt) -> np.ndarray:
        """(P,) int32 home slice per pod (-1 = gangless). Host-side
        greedy over the HOST mirror: gangs by total CPU demand
        descending pick the slice with the most remaining free CPU;
        each pick debits the slice so later gangs see the cascade.
        Cheap (G x Z) work on arrays the packer already built."""
        zone = np.asarray(nt.zone_id)[: nt.n]
        from kubernetes_tpu.snapshot import RES_CPU

        free = np.maximum(
            np.asarray(nt.allocatable)[: nt.n, RES_CPU]
            - np.asarray(nt.requested)[: nt.n, RES_CPU], 0.0)
        n_zones = int(zone.max()) + 1 if zone.size and zone.max() >= 0 else 0
        zfree = np.zeros((max(n_zones, 1),), np.float64)
        for z in range(n_zones):
            zfree[z] = free[zone == z].sum()
        gangs: Dict[str, List[int]] = {}
        demand: Dict[str, float] = {}
        for i, p in enumerate(batch):
            if p.pod_group:
                gangs.setdefault(p.pod_group, []).append(i)
                demand[p.pod_group] = (demand.get(p.pod_group, 0.0)
                                       + p.requests.cpu_milli)
        home = np.full((len(batch),), -1, np.int32)
        if not gangs or n_zones == 0:
            return home
        for g in sorted(gangs, key=lambda g: (-demand[g], g)):
            z = int(np.argmax(zfree))
            zfree[z] -= demand[g]
            for i in gangs[g]:
                home[i] = z
        return home

    def cost(self, batch, nt, node_order, dp, dn):
        import jax.numpy as jnp

        from kubernetes_tpu.ops.scenario_cost import gang_topology_score

        home = self._home_zones(batch, nt)
        P = dp.valid.shape[0]
        if P > home.shape[0]:  # padding rows are gangless
            home = np.concatenate(
                [home, np.full((P - home.shape[0],), -1, np.int32)])
        return gang_topology_score(
            jnp.asarray(home), dn, jnp.float32(self.config.cost_weight),
            superpod=self.config.superpod)

    # graftlint: disable-scope=R7 -- nt is the HOST-mirror NodeTable
    # (numpy); the hint mask is derived host-side and uploaded only
    def candidate_hint(self, batch, nt, node_order) -> Optional[np.ndarray]:
        """Keep every column inside a gang's home slice: the top-C
        rank order knows nothing about slice distance, so without the
        hint a hot-but-remote candidate set could leave a gang zero
        feasible home-slice columns and force the dense fallback."""
        home = self._home_zones(batch, nt)
        zones = np.unique(home[home >= 0])
        if zones.size == 0:
            return None
        zone = np.asarray(nt.zone_id)[: nt.n]
        return np.isin(zone, zones)

    # graftlint: disable-scope=R7 -- nt is the HOST-mirror NodeTable
    # (numpy); gang bookkeeping reads host arrays only
    def quality_host(self, batch, assigned, nt) -> Dict[str, float]:
        from kubernetes_tpu.scenarios.quality import gang_stats

        return gang_stats(batch, assigned,
                          zone_of_node=np.asarray(nt.zone_id)[: nt.n],
                          superpod=self.config.superpod)


#: pack name -> class; "" stays unregistered (scenario mode off)
SCENARIO_REGISTRY = {
    ConsolidationPack.name: ConsolidationPack,
    GangTopologyPack.name: GangTopologyPack,
}


def resolve_pack(config) -> Optional[ScenarioPack]:
    """ScenarioConfig -> pack instance (None when ``pack`` is empty).
    Unknown names fail loudly — ``cli.validate_config`` rejects them
    up front; this guard covers direct constructor callers."""
    if config is None or not getattr(config, "pack", ""):
        return None
    cls = SCENARIO_REGISTRY.get(config.pack)
    if cls is None:
        raise ValueError(
            f"scenario.pack: unknown pack {config.pack!r} "
            f"(known: {sorted(SCENARIO_REGISTRY)})")
    return cls(config)
