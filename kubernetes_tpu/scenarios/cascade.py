"""In-batch preemption cascade — the scenario-pack replacement for the
per-pod nominate-and-wait preemption loop.

Division of labor (the parity contract tests/test_scenarios.py pins):

- **victim SELECTION stays exact and shared**: each preemptor runs the
  reference-faithful machinery from :mod:`kubernetes_tpu.preemption`
  (candidate pruning by resolvable reason bits, selectVictimsOnNode's
  reprieve loop, PDB splits, the 6-tier node pick) — one source of
  truth, so a single-pod batch selects BIT-IDENTICAL victim sets to the
  stock path by construction. The cascade part: preemptors process in
  priority order against ONE shared hypothetical state, so an earlier
  preemptor's evictions are visible to later ones (no double-claiming a
  victim, no phantom capacity).
- **re-entry is the dense solve**: instead of nominating each preemptor
  and parking it for a future cycle while victims terminate one-by-one,
  the driver evacuates every selected victim (grace 0 — the scenario
  pack's batch-consolidation semantics), then runs preemptors AND
  displaced victims through ONE additional dense solve in the SAME
  cycle (the full ladder: validation, fallback tiers, fused readback).
  Displaced pods that re-place migrate; those that cannot requeue
  through the standard error path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.preemption import preempt


@dataclass
class CascadeSelection:
    """What the shared-state selection pass decided."""

    #: preemptor pod key -> node chosen for it (the evacuated node)
    chosen: Dict[str, str] = field(default_factory=dict)
    #: every victim selected across the cascade, in eviction order
    victims: List[Pod] = field(default_factory=list)
    #: victim key -> the preemptor key that claimed it
    victim_of: Dict[str, str] = field(default_factory=dict)
    #: pods whose lower-priority nominations must clear (stock semantics)
    clear_nominations: List[Pod] = field(default_factory=list)
    num_pdb_violations: int = 0


def select_cascade(
    preemptors: List[Tuple[Pod, Dict[str, int]]],
    nodes,
    node_pods_of: Dict[str, List[Pod]],
    pdbs=(),
    nominated_pods_of: Optional[Dict[str, List[Pod]]] = None,
    vol_state=None,
    extenders=(),
    enable_non_preempting: bool = False,
    max_preemptions: int = 16,
    on_attempt=None,
) -> CascadeSelection:
    """Run victim selection for every preemptor against one shared
    state. ``preemptors`` is [(pod, reason_bits_by_node)] already in
    priority-descending order (the caller sorts — same order the stock
    loop uses). Selected victims leave the shared ``node_pods_of`` view
    before the next preemptor runs, which IS the cascade.
    ``on_attempt`` fires once per pod PROCESSED (after the cap check) —
    the same accounting the stock per-pod loop gives
    ``scheduler_preemption_attempts_total``."""
    sel = CascadeSelection()
    state = {k: list(v) for k, v in node_pods_of.items()}
    # the nominated view EVOLVES like the stock loop's (which re-reads
    # queue.nominated every iteration): each successful preemptor joins
    # as a phantom occupant of its chosen node, and its cleared
    # lower-priority nominations leave — otherwise a later preemptor
    # would see the evacuated capacity as free and over-evict victims
    # an earlier preemptor is about to occupy
    nom = {k: list(v) for k, v in (nominated_pods_of or {}).items()}
    done = 0
    for pod, reason_bits in preemptors:
        if done >= max_preemptions:
            break
        if on_attempt is not None:
            on_attempt()
        result = preempt(
            pod, nodes, state, reason_bits, pdbs,
            nominated_pods_of=nom,
            vol_state=vol_state,
            extenders=extenders,
            enable_non_preempting=enable_non_preempting,
        )
        if result is None:
            continue
        sel.chosen[pod.key()] = result.node_name
        sel.num_pdb_violations += result.num_pdb_violations
        sel.clear_nominations.extend(result.clear_nominations)
        for v in result.victims:
            sel.victims.append(v)
            sel.victim_of[v.key()] = pod.key()
            state[result.node_name] = [
                p for p in state[result.node_name] if p.key() != v.key()
            ]
        cleared = {p.key() for p in result.clear_nominations}
        nom[result.node_name] = [
            p for p in nom.get(result.node_name, [])
            if p.key() not in cleared
        ] + [pod]
        done += 1
    return sel
