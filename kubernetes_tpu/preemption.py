"""Preemption — exact host-side victim selection over the cache, mirroring
``genericScheduler.Preempt`` (``pkg/scheduler/core/generic_scheduler.go:316``)
and its helpers:

- eligibility (``:1190`` podEligibleToPreemptOthers)
- candidate pruning (``:1167`` nodesWherePreemptionMightHelp — only nodes
  whose filter failures are *resolvable by removing pods* qualify)
- victim selection with the reprieve loop (``:1079`` selectVictimsOnNode:
  remove all lower-priority pods, verify the preemptor fits, then try to
  re-add each candidate victim highest-priority-first — PDB-violating pods
  reprieved first — keeping those whose return doesn't break the fit)
- the 6-tier lexicographic node pick (``:862`` pickOneNodeForPreemption)

Division of labor with the device: the *filter* pass that discovered the
failures ran batched on TPU and produced per-(pod, node) failure-reason
bitmasks; this module consumes those bits to prune candidates, then runs the
exact what-if semantics host-side via the sequential reference predicates
(``kubernetes_tpu.seqref``) — preemption is rare and victim counts are
small, so the ragged reprieve loop is not worth tensorizing (the reference
itself re-runs full predicates per what-if). A batched coarse pre-filter
remains possible later via the reasons matrix alone.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu import seqref
from kubernetes_tpu.api.types import Node, Pod, PodDisruptionBudget
from kubernetes_tpu.ops.predicates import BIT

#: Failure bits that deleting pods can possibly clear. Complement of the
#: reference's unresolvable list (generic_scheduler.go:65-84): node
#: conditions, unschedulable flag, taints, selector/hostname mismatches
#: cannot be fixed by preemption.
RESOLVABLE_BITS = (
    (1 << BIT["PodFitsResources"])
    | (1 << BIT["PodFitsHostPorts"])
    | (1 << BIT["MatchInterPodAffinity"])
    | (1 << BIT["EvenPodsSpread"])
    # disk conflicts and attach-count limits clear when mounting pods are
    # evicted; zone/node-affinity/bind conflicts do not (the reference lists
    # ErrVolume{Zone,Node,Bind}Conflict as unresolvable)
    | (1 << BIT["NoDiskConflict"])
    | (1 << BIT["MaxVolumeCount"])
)


@dataclass
class PreemptionResult:
    node_name: str
    victims: List[Pod] = field(default_factory=list)
    num_pdb_violations: int = 0
    #: lower-priority pods nominated on the chosen node whose nomination
    #: must be cleared (scheduler.go:330 getLowerPriorityNominatedPods)
    clear_nominations: List[Pod] = field(default_factory=list)


def pod_eligible_to_preempt_others(
    pod: Pod, node_pods_of: Dict[str, List[Pod]],
    enable_non_preempting: bool = False,
) -> bool:
    """generic_scheduler.go:1190 — a pod that already triggered a preemption
    (has a nominated node) waits while any lower-priority pod there is still
    terminating; with the NonPreemptingPriority gate on, a PreemptNever
    policy disqualifies outright (:1191-1194)."""
    if enable_non_preempting and pod.preemption_policy == "Never":
        return False
    nom = pod.nominated_node_name
    if nom and nom in node_pods_of:
        for p in node_pods_of[nom]:
            if p.deletion_timestamp and p.priority < pod.priority:
                return False
    return True


def nodes_where_preemption_might_help(
    reason_bits_by_node: Dict[str, int]
) -> List[str]:
    """generic_scheduler.go:1167 — keep nodes whose every failure bit is
    resolvable by removing pods. Nodes with no failure bits (feasible or
    padding) are not candidates."""
    return [
        n
        for n, bits in reason_bits_by_node.items()
        if bits and (bits & ~RESOLVABLE_BITS) == 0
    ]


def _fits_with(
    pod: Pod,
    node: Node,
    nodes: Sequence[Node],
    node_pods_of: Dict[str, List[Pod]],
    vol_state=None,
) -> bool:
    """Full predicate check of ``pod`` on ``node`` against the given
    hypothetical cluster state (podFitsOnNode's predicate set as evaluated
    during preemption what-ifs)."""
    return (
        seqref.feasible(pod, node, node_pods_of.get(node.name, []))
        and seqref.inter_pod_affinity_feasible(pod, node, nodes, node_pods_of)
        and seqref.even_pods_spread_feasible(pod, node, nodes, node_pods_of)
        and (
            vol_state is None
            or seqref.volumes_feasible(
                pod, node, node_pods_of.get(node.name, []), vol_state
            )
        )
    )


def select_victims_on_node(
    pod: Pod,
    node: Node,
    nodes: Sequence[Node],
    node_pods_of: Dict[str, List[Pod]],
    pdbs: Sequence[PodDisruptionBudget] = (),
    nominated_pods_of: Optional[Dict[str, List[Pod]]] = None,
    vol_state=None,
) -> Optional[Tuple[List[Pod], int]]:
    """selectVictimsOnNode (generic_scheduler.go:1079). Returns
    (victims, num_pdb_violations) or None when preemption can't help.

    ``nominated_pods_of`` — pods nominated onto nodes by earlier
    preemptions. The reference's what-if fit check passes the scheduling
    queue into podFitsOnNode, so higher/equal-priority nominated pods count
    as phantom occupants (they are never selectable as victims): without
    this, a second preemptor would claim capacity already promised to the
    first."""
    pods_here = list(node_pods_of.get(node.name, []))
    potential = [p for p in pods_here if p.priority < pod.priority]
    keep = [p for p in pods_here if p.priority >= pod.priority]
    phantoms = [
        p
        for p in (nominated_pods_of or {}).get(node.name, [])
        if p.priority >= pod.priority and p.key() != pod.key()
    ]

    # hypothetical state: all lower-priority pods gone, phantoms present
    state = dict(node_pods_of)
    state[node.name] = keep + phantoms
    if not _fits_with(pod, node, nodes, state, vol_state):
        return None

    violating, non_violating = filter_pods_with_pdb_violation(potential, pdbs)
    victims: List[Pod] = []
    num_violations = 0

    def reprieve(p: Pod) -> bool:
        state[node.name] = state[node.name] + [p]
        if _fits_with(pod, node, nodes, state, vol_state):
            return True  # keep it — not a victim
        state[node.name] = state[node.name][:-1]
        return False

    # highest-priority first within each group; PDB-violating group first so
    # it gets the best chance of reprieve (generic_scheduler.go:1110-1125)
    for p in sorted(violating, key=lambda q: -q.priority):
        if not reprieve(p):
            victims.append(p)
            num_violations += 1
    for p in sorted(non_violating, key=lambda q: -q.priority):
        if not reprieve(p):
            victims.append(p)
    return victims, num_violations


def filter_pods_with_pdb_violation(
    pods: Sequence[Pod], pdbs: Sequence[PodDisruptionBudget]
) -> Tuple[List[Pod], List[Pod]]:
    """generic_scheduler.go:1129 — split pods into (would violate a PDB,
    would not): a pod violates when any matching PDB has no disruptions
    left."""
    violating, ok = [], []
    for p in pods:
        if any(pdb.matches(p) and pdb.disruptions_allowed <= 0 for pdb in pdbs):
            violating.append(p)
        else:
            ok.append(p)
    return violating, ok


def pick_one_node(
    candidates: Dict[str, Tuple[List[Pod], int]]
) -> Optional[str]:
    """pickOneNodeForPreemption (generic_scheduler.go:862): lexicographic
    tie-break —
      1. fewest PDB violations
      2. lowest highest-victim priority
      3. smallest sum of victim priorities
      4. fewest victims
      5. latest start time of the highest-priority victim
      6. first remaining (stable iteration order).
    A node with NO victims wins immediately (the reference returns it)."""
    if not candidates:
        return None
    names = list(candidates)
    for n in names:
        if not candidates[n][0]:
            return n

    def metrics(n: str):
        victims, pdb = candidates[n]
        high = max(v.priority for v in victims)
        return (
            pdb,
            high,
            # each victim contributes priority + (MaxInt32+1) so the count
            # of victims dominates negative priorities — a node with few
            # negative-priority victims must not lose to one with fewer
            # total-priority but more pods (generic_scheduler.go:921-928)
            sum(v.priority + 2**31 for v in victims),
            len(victims),
            -max(v.start_time for v in victims if v.priority == high),
        )

    m = {n: metrics(n) for n in names}
    for tier in range(5):
        best = min(v[tier] for v in (m[n] for n in names))
        names = [n for n in names if m[n][tier] == best]
        if len(names) == 1:
            return names[0]
    return names[0]


def preempt(
    pod: Pod,
    nodes: Sequence[Node],
    node_pods_of: Dict[str, List[Pod]],
    reason_bits_by_node: Dict[str, int],
    pdbs: Sequence[PodDisruptionBudget] = (),
    nominated_pods_of: Optional[Dict[str, List[Pod]]] = None,
    vol_state=None,
    extenders: Sequence = (),
    enable_non_preempting: bool = False,
) -> Optional[PreemptionResult]:
    """The full Preempt flow for one unschedulable pod. ``node_pods_of``
    maps node name -> pods (from the cache); ``reason_bits_by_node`` is the
    pod's row of the device filter pass; ``nominated_pods_of`` maps node
    name -> pods currently nominated there (phantom occupants for the
    what-if checks, and the source for nomination clearing)."""
    if not pod_eligible_to_preempt_others(pod, node_pods_of,
                                          enable_non_preempting):
        return None
    by_name = {nd.name: nd for nd in nodes}
    candidates: Dict[str, Tuple[List[Pod], int]] = {}
    for name in nodes_where_preemption_might_help(reason_bits_by_node):
        nd = by_name.get(name)
        if nd is None:
            continue
        r = select_victims_on_node(
            pod, nd, nodes, node_pods_of, pdbs,
            nominated_pods_of=nominated_pods_of,
            vol_state=vol_state,
        )
        if r is not None:
            candidates[name] = r
    # extender.ProcessPreemption (generic_scheduler.go:350): preemption-
    # capable extenders may drop candidate nodes or shrink victim lists;
    # ignorable extenders drop out on error
    for ext in extenders:
        if not candidates:
            break
        try:
            candidates = ext.process_preemption(pod, candidates)
        except Exception:
            if getattr(ext, "is_ignorable", lambda: False)():
                continue
            return None
    chosen = pick_one_node(candidates)
    if chosen is None:
        return None
    victims, pdb_violations = candidates[chosen]
    clear = [
        p
        for p in (nominated_pods_of or {}).get(chosen, [])
        if p.priority < pod.priority
    ]
    return PreemptionResult(
        node_name=chosen,
        victims=victims,
        num_pdb_violations=pdb_violations,
        clear_nominations=clear,
    )
