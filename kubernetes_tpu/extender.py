"""Scheduler extender — the out-of-process HTTP+JSON webhook protocol
(``pkg/scheduler/core/extender.go`` HTTPExtender; wire types
``pkg/scheduler/api/types.go:240-345``).

This is the integration seam for a Go control plane: the wire shapes
(ExtenderArgs / ExtenderFilterResult / ExtenderBindingArgs /
ExtenderPreemptionArgs) keep the reference's JSON field names, so an
existing extender webhook works against this scheduler unchanged, and —
symmetrically — a Go kube-scheduler pointed at this framework running
behind :class:`ExtenderServer` offloads its filter/prioritize work to the
TPU batch kernels (BASELINE's "scheduler-extender protocol" target).

``nodeCacheCapable`` mode exchanges node *names* only (the extender keeps
its own cache), which is also how the TPU service keeps the columnar
snapshot resident device-side instead of shipping node objects per pod.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.config import ExtenderConfig

# ---------------------------------------------------------------------------
# v1-shaped JSON serialization (the minimal slice extenders read)
# ---------------------------------------------------------------------------


def _rfc3339(epoch_s: float) -> str:
    """Seconds-epoch -> RFC3339 with microseconds (Go's time.Time JSON
    unmarshal accepts fractional RFC3339, so a metav1.Time-shaped
    consumer parses this; wire precision is 1 µs — the hub floors its
    terminating epsilon there)."""
    import datetime

    return datetime.datetime.fromtimestamp(
        epoch_s, datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


def rfc3339_to_epoch(v) -> float:
    """Inverse of :func:`_rfc3339` (fractional seconds optional); also
    accepts a bare number (the hub's internal clock is a float epoch)."""
    import datetime

    if isinstance(v, (int, float)):
        return float(v)
    s = str(v)
    fmt = "%Y-%m-%dT%H:%M:%S.%fZ" if "." in s else "%Y-%m-%dT%H:%M:%SZ"
    return datetime.datetime.strptime(s, fmt).replace(
        tzinfo=datetime.timezone.utc).timestamp()


def pod_to_json(pod: Pod) -> dict:
    """A v1.Pod-shaped document carrying the fields the scheduler consumes
    (metadata + the scheduling-relevant spec/status slice)."""
    return {
        "metadata": {
            "name": pod.name,
            "namespace": pod.namespace,
            "uid": pod.uid or pod.key(),
            "labels": dict(pod.labels),
            **({"ownerReferences": [
                {"kind": r.kind, "name": r.name,
                 **({"uid": r.uid} if r.uid else {})}
                for r in pod.owner_refs
            ]} if pod.owner_refs else {}),
            # metadata.deletionTimestamp as RFC3339 (metav1.Time
            # unmarshals only from that shape — a float here would break
            # any Go-side consumer of the extender wire). A terminating
            # pod must cross the wire as terminating or the remote
            # side's skipPodSchedule/preemption checks go blind.
            **({"deletionTimestamp": _rfc3339(pod.deletion_timestamp)}
               if pod.deletion_timestamp else {}),
        },
        "spec": {
            "nodeName": pod.node_name,
            "nodeSelector": dict(pod.node_selector),
            "priority": pod.priority,
            "schedulerName": pod.scheduler_name,
            "preemptionPolicy": pod.preemption_policy,
            "containers": [
                {
                    "name": "main",
                    "resources": {
                        "requests": {
                            "cpu": f"{int(pod.requests.cpu_milli)}m",
                            "memory": str(int(pod.requests.memory)),
                            **{k: str(v) for k, v in pod.requests.scalars.items()},
                        }
                    },
                    **({"readinessProbe": {
                        "initialDelaySeconds":
                            pod.readiness_probe.initial_delay_s}}
                       if pod.readiness_probe is not None else {}),
                }
            ],
        },
        "status": {
            "nominatedNodeName": pod.nominated_node_name,
            "phase": pod.phase,
            **({"conditions": [{"type": "Ready",
                                "status": "True" if pod.ready else "False"}]}
               if pod.readiness_probe is not None else {}),
        },
    }


def node_to_json(node) -> dict:
    c = node.conditions
    conditions = [
        {"type": "Ready", "status": "True" if c.ready else "False"},
        {"type": "MemoryPressure",
         "status": "True" if c.memory_pressure else "False"},
        {"type": "DiskPressure",
         "status": "True" if c.disk_pressure else "False"},
        {"type": "PIDPressure",
         "status": "True" if c.pid_pressure else "False"},
        {"type": "NetworkUnavailable",
         "status": "True" if c.network_unavailable else "False"},
    ]
    meta = {"name": node.name, "labels": dict(node.labels)}
    if node.annotations:
        meta["annotations"] = dict(node.annotations)
    if node.prefer_avoid_owner_uids:
        # the reference carries this via the preferAvoidPods annotation
        # (scheduler.alpha.kubernetes.io/preferAvoidPods, priorities/
        # node_prefer_avoid_pods.go) — keep the wire shape
        meta.setdefault("annotations", {})[
            "scheduler.alpha.kubernetes.io/preferAvoidPods"] = json.dumps({
                "preferAvoidPods": [
                    {"podSignature": {"podController": {"uid": uid}}}
                    for uid in node.prefer_avoid_owner_uids
                ]
            })
    status = {
        "allocatable": {
            "cpu": f"{int(node.allocatable.cpu_milli)}m",
            "memory": str(int(node.allocatable.memory)),
            "pods": str(int(node.allocatable.pods)),
            "ephemeral-storage": str(int(node.allocatable.ephemeral_storage)),
            **{k: str(v) for k, v in node.allocatable.scalars.items()},
        },
        "conditions": conditions,
    }
    if node.images:
        status["images"] = [
            {"names": [name], "sizeBytes": int(size)}
            for name, size in node.images.items()
        ]
    return {
        "metadata": meta,
        "spec": {
            "unschedulable": node.unschedulable,
            **({"podCIDR": node.pod_cidr} if node.pod_cidr else {}),
            "taints": [
                {"key": t.key, "value": t.value, "effect": t.effect}
                for t in node.taints
            ],
        },
        "status": status,
    }


# ---------------------------------------------------------------------------
# HTTP extender client
# ---------------------------------------------------------------------------


class ExtenderError(Exception):
    pass


class HTTPExtender:
    """core/extender.go:42 — POSTs JSON to urlPrefix/verb. ``transport``
    is injectable for tests (callable(url, payload_dict, timeout) ->
    response dict); the default uses urllib.

    Robustness seams (kubernetes_tpu/faults.py): ``retry`` — a
    RetryPolicy applying bounded exponential backoff + jitter around the
    transport call (the scheduler wires its shared policy in when left
    None); ``fault_injector`` — the chaos harness hook, consulted before
    each send (may raise timeouts/connection errors) and after (may
    corrupt the decoded response); :meth:`set_call_budget` — the
    scheduler's per-cycle deadline propagation, clamping the next calls'
    transport timeout to the remaining cycle budget."""

    def __init__(
        self,
        config: ExtenderConfig,
        transport: Optional[Callable[[str, dict, float], dict]] = None,
        retry=None,
        fault_injector=None,
        clock: Callable[[], float] = None,
        obs=None,
    ) -> None:
        import time

        self.config = config
        self._transport = transport or _urllib_transport
        self.retry = retry
        self.fault_injector = fault_injector
        self._clock = clock or time.monotonic
        #: True when no clock was injected — the scheduler then adopts
        #: this extender onto its own clock (fake-clock tests stay
        #: deterministic across the budget-deadline math)
        self._clock_defaulted = clock is None
        #: observability facade (kubernetes_tpu/obs): per-verb transport
        #: spans on the in-flight cycle trace; the scheduler wires it in
        #: like retry/fault_injector (None stays silent)
        self.obs = obs
        self._call_budget_s: Optional[float] = None
        #: absolute deadline on self._clock derived from the last
        #: set_call_budget — what bounds the RETRY loop and refreshes the
        #: per-attempt timeout clamp (a fixed budget snapshot would let
        #: attempt 3 run with attempt 1's generous clamp)
        self._budget_deadline: Optional[float] = None

    def name(self) -> str:
        return self.config.url_prefix

    def set_call_budget(self, seconds: Optional[float]) -> None:
        """Clamp subsequent transport timeouts to the caller's remaining
        cycle budget; re-armed per verb by the scheduler. ``None``
        clears the clamp (unbounded cycle) — required so a clamp from a
        deadline-bearing cycle can't leak into later verbs/cycles."""
        if seconds is None:
            self._call_budget_s = None
            self._budget_deadline = None
            return
        self._call_budget_s = max(float(seconds), 1e-3)
        self._budget_deadline = self._clock() + self._call_budget_s

    def is_ignorable(self) -> bool:
        return self.config.ignorable

    def is_binder(self) -> bool:
        return bool(self.config.bind_verb)

    def supports_preemption(self) -> bool:
        return bool(self.config.preempt_verb)

    def is_interested(self, pod: Pod) -> bool:
        """extender.go:417 IsInterested: no managed resources = interested
        in everything; otherwise only pods requesting one of them."""
        if not self.config.managed_resources:
            return True
        managed = set(self.config.managed_resources)
        return any(name in managed for name in pod.requests.scalars)

    def _send(self, verb: str, args: dict) -> dict:
        from contextlib import nullcontext

        url = self.config.url_prefix.rstrip("/") + "/" + verb

        def once() -> dict:
            # per-ATTEMPT timeout clamp, refreshed from the remaining
            # budget at each retry — the static snapshot it replaces let
            # later attempts run on a stale (too-generous) clamp and
            # blow the cycle deadline (ROADMAP bug (b))
            timeout = self.config.http_timeout_s
            if self._budget_deadline is not None:
                timeout = min(
                    timeout, max(self._budget_deadline - self._clock(), 1e-3))
            kind = None
            if self.fault_injector is not None:
                # may raise (timeout/connection/truncated) or return a
                # corruption to apply to the decoded response
                kind = self.fault_injector.transport_fault(
                    f"extender:{verb}")
            resp = self._transport(url, args, timeout)
            if kind is not None:
                resp = self.fault_injector.corrupt_response(kind, resp)
            return resp

        span = (self.obs.span(f"extender:{verb}", url=url)
                if self.obs is not None else nullcontext())
        with span:
            if self.retry is not None:
                # retries bounded by the same budget deadline: a backoff
                # that would land past it propagates the error instead
                # of burning cycle time the caller no longer has
                return self.retry.call(once,
                                       deadline_s=self._budget_deadline,
                                       clock=self._clock)
            return once()

    # -- verbs -------------------------------------------------------------

    def filter(
        self, pod: Pod, node_names: Sequence[str], nodes_by_name: Dict[str, object]
    ) -> Tuple[List[str], Dict[str, str]]:
        """Returns (feasible node names, failed nodes map). Raises
        ExtenderError on transport/remote error (caller applies the
        Ignorable policy, generic_scheduler.go:539-566)."""
        if not self.config.filter_verb:
            return list(node_names), {}
        args: dict = {"pod": pod_to_json(pod)}
        if self.config.node_cache_capable:
            args["nodenames"] = list(node_names)
        else:
            args["nodes"] = {
                "items": [node_to_json(nodes_by_name[n]) for n in node_names]
            }
        try:
            result = self._send(self.config.filter_verb, args)
        except Exception as e:
            raise ExtenderError(str(e))
        # parse hardening: a corrupt/mistyped response is a remote error
        # (ExtenderError, so the Ignorable policy applies) — it must
        # never escape as a TypeError that aborts the whole cycle
        try:
            if result.get("error"):
                raise ExtenderError(result["error"])
            if (self.config.node_cache_capable
                    and result.get("nodenames") is not None):
                names = [str(n) for n in result["nodenames"]]
            elif result.get("nodes") is not None:
                names = [
                    item["metadata"]["name"]
                    for item in result["nodes"].get("items", [])
                ]
            else:
                names = list(node_names)
            return names, dict(result.get("failedNodes") or {})
        except ExtenderError:
            raise
        except Exception as e:
            raise ExtenderError(f"malformed filter response: {e}")

    def prioritize(
        self, pod: Pod, node_names: Sequence[str], nodes_by_name: Dict[str, object]
    ) -> Tuple[Dict[str, float], int]:
        """Returns ({node: score}, weight) — the caller adds
        score*weight into the total (extender.go:318)."""
        if not self.config.prioritize_verb:
            return {n: 0.0 for n in node_names}, 1
        args: dict = {"pod": pod_to_json(pod)}
        if self.config.node_cache_capable:
            args["nodenames"] = list(node_names)
        else:
            args["nodes"] = {
                "items": [node_to_json(nodes_by_name[n]) for n in node_names]
            }
        try:
            result = self._send(self.config.prioritize_verb, args)
        except Exception as e:
            raise ExtenderError(str(e))
        try:
            scores = {hp["host"]: float(hp["score"]) for hp in (result or [])}
        except Exception as e:
            raise ExtenderError(f"malformed prioritize response: {e}")
        return scores, self.config.weight

    def bind(self, pod: Pod, node_name: str) -> None:
        """extender.go:360 — delegate the binding to the extender."""
        args = {
            "podName": pod.name,
            "podNamespace": pod.namespace,
            "podUID": pod.uid or pod.key(),
            "node": node_name,
        }
        result = self._send(self.config.bind_verb, args)
        if result and result.get("error"):
            raise ExtenderError(result["error"])

    def process_preemption(
        self, pod: Pod, victims_by_node: Dict[str, Tuple[List[Pod], int]]
    ) -> Dict[str, Tuple[List[Pod], int]]:
        """extender.go:135 ProcessPreemption: the extender may drop
        candidate nodes or shrink victim lists. Node-cache-capable wire
        form (metaVictims, pod UIDs only)."""
        if not self.config.preempt_verb:
            return victims_by_node
        pods_by_uid = {
            v.uid or v.key(): v
            for victims, _ in victims_by_node.values()
            for v in victims
        }
        args = {
            "pod": pod_to_json(pod),
            "nodeNameToMetaVictims": {
                node: {
                    "pods": [{"uid": v.uid or v.key()} for v in victims],
                    "numPDBViolations": npdb,
                }
                for node, (victims, npdb) in victims_by_node.items()
            },
        }
        try:
            result = self._send(self.config.preempt_verb, args)
        except Exception as e:
            raise ExtenderError(str(e))
        out: Dict[str, Tuple[List[Pod], int]] = {}
        for node, mv in (result.get("nodeNameToMetaVictims") or {}).items():
            victims = [
                pods_by_uid[p["uid"]]
                for p in mv.get("pods", [])
                if p.get("uid") in pods_by_uid
            ]
            out[node] = (victims, int(mv.get("numPDBViolations", 0)))
        return out


def _urllib_transport(url: str, payload: dict, timeout: float) -> dict:
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode() or "{}")


def build_extenders(
    configs: Sequence[ExtenderConfig],
    transport: Optional[Callable] = None,
    retry=None,
    fault_injector=None,
    clock=None,
    obs=None,
) -> List[HTTPExtender]:
    return [HTTPExtender(c, transport, retry=retry,
                         fault_injector=fault_injector, clock=clock,
                         obs=obs) for c in configs]
