"""Scheduler cache — in-memory truth about nodes and (assumed) pods, with
generation-tracked incremental snapshot packing.

Reference: ``pkg/scheduler/internal/cache/cache.go``. Two ideas carry over
directly:

1. **Assumed-pod state machine** (``cache/interface.go:36-47``): the driver
   optimistically AssumePod()s a pod onto its chosen node the moment the
   algorithm picks it, so the next cycle sees the capacity as used while the
   binding RPC is still in flight. FinishBinding starts a TTL; if the bound
   pod add never arrives from the watch before the TTL, the assumption
   expires and capacity frees (``cache.go:674`` cleanupAssumedPods).
   ForgetPod undoes an assumption on bind failure (``scheduler.go:447``).

2. **Generation-ordered incremental snapshots** (``cache.go:211``
   UpdateNodeInfoSnapshot, ``cache.go:135`` moveNodeInfoToHead): every
   mutation bumps a per-node generation; snapshotting recomputes only rows
   whose generation passed the last snapshot. Here the columnar NodeTable is
   cached and only dirty node rows are repacked (a full repack happens only
   when universe widths or the node set shape change — rare by design,
   since widths are power-of-two bucketed).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.snapshot import NodeTable, SnapshotPacker

#: cache.go — factory.NewConfigFactory wires a 30 s assumed-pod TTL.
DEFAULT_ASSUME_TTL_S = 30.0

# assumed-pod states
_ASSUMED = "assumed"  # Assume() called, bind in flight
_EXPIRING = "expiring"  # FinishBinding() called, TTL armed
_ADDED = "added"  # confirmed via watch AddPod


class CacheError(Exception):
    pass


class SchedulerCache:
    """Host-side cluster cache. Thread-free by design (the driver is a
    single loop around device dispatch); the watch pump calls the mutators
    between cycles."""

    def __init__(
        self,
        packer: Optional[SnapshotPacker] = None,
        ttl_s: float = DEFAULT_ASSUME_TTL_S,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.packer = packer or SnapshotPacker()
        self.ttl_s = ttl_s
        self.clock = clock
        self._nodes: Dict[str, Node] = {}
        self._pods_by_node: Dict[str, Dict[str, Pod]] = {}
        self._pod_state: Dict[str, str] = {}  # key -> assumed state
        self._pod_node: Dict[str, str] = {}  # key -> node name
        self._pod_deadline: Dict[str, float] = {}  # key -> expiry (EXPIRING only)
        self._dirty: Set[str] = set()  # node names needing row repack
        self._shape_dirty = True  # node set / widths changed => full repack
        # cached snapshot state
        self._table: Optional[NodeTable] = None
        self._row_of: Dict[str, int] = {}
        self._widths_key: Optional[Tuple] = None

    # -- introspection -----------------------------------------------------

    def node(self, name: str) -> Optional[Node]:
        return self._nodes.get(name)

    def nodes(self) -> List[Node]:
        return list(self._nodes.values())

    def pods_on(self, node_name: str) -> List[Pod]:
        return list(self._pods_by_node.get(node_name, {}).values())

    def is_assumed(self, pod_key: str) -> bool:
        return self._pod_state.get(pod_key) in (_ASSUMED, _EXPIRING)

    def pod_count(self) -> int:
        return sum(len(m) for m in self._pods_by_node.values())

    def pod(self, key: str) -> Optional[Pod]:
        node = self._pod_node.get(key)
        if node is None:
            return None
        return self._pods_by_node.get(node, {}).get(key)

    def node_count(self) -> int:
        return len(self._nodes)

    # -- assumed-pod state machine ----------------------------------------

    def assume_pod(self, pod: Pod, node_name: str) -> None:
        """cache.go:275 AssumePod — place the pod in the cache now, before
        the binding is durable."""
        key = pod.key()
        if key in self._pod_state:
            raise CacheError(f"pod {key} already in cache ({self._pod_state[key]})")
        self.packer.intern_pod(pod)
        p = dataclasses.replace(pod, node_name=node_name)
        self._pods_by_node.setdefault(node_name, {})[key] = p
        self._pod_state[key] = _ASSUMED
        self._pod_node[key] = node_name
        self._mark_dirty(node_name)

    def finish_binding(self, pod_key: str) -> None:
        """cache.go FinishBinding — arm the TTL; the watch-confirmed AddPod
        must arrive before it fires."""
        if self._pod_state.get(pod_key) == _ASSUMED:
            self._pod_state[pod_key] = _EXPIRING
            self._pod_deadline[pod_key] = self.clock() + self.ttl_s

    def forget_pod(self, pod_key: str) -> None:
        """cache.go ForgetPod — undo an assumption (bind failed)."""
        if self._pod_state.get(pod_key) not in (_ASSUMED, _EXPIRING):
            raise CacheError(f"pod {pod_key} is not assumed")
        self._drop_pod(pod_key)

    def cleanup_expired(self) -> List[str]:
        """cache.go:674 cleanupAssumedPods — expire overdue assumptions;
        returns the expired keys (the driver logs/metrics them)."""
        now = self.clock()
        expired = [
            k
            for k, d in self._pod_deadline.items()
            if d <= now and self._pod_state.get(k) == _EXPIRING
        ]
        for k in expired:
            self._drop_pod(k)
        return expired

    # -- watch-driven mutations -------------------------------------------

    def add_pod(self, pod: Pod) -> None:
        """Watch AddPod for an assigned pod: confirms an assumption or adds
        an unseen pod (cache.go AddPod)."""
        key = pod.key()
        state = self._pod_state.get(key)
        if state in (_ASSUMED, _EXPIRING):
            cached_node = self._pod_node.get(key)
            if cached_node != pod.node_name:
                # assumed onto the wrong node — trust the API (cache.go logs
                # and re-adds)
                self._drop_pod(key)
                self._insert_pod(pod)
            else:
                self._pod_state[key] = _ADDED
                self._pod_deadline.pop(key, None)
                # refresh the stored object to the API's version
                self._pods_by_node[pod.node_name][key] = pod
                self._mark_dirty(pod.node_name)
        elif state is None:
            self._insert_pod(pod)
        # state == ADDED: duplicate add — treat as update
        else:
            self.update_pod(pod)

    def update_pod(self, pod: Pod) -> None:
        key = pod.key()
        old_node = self._pod_node.get(key)
        if old_node is not None and old_node != pod.node_name:
            self._drop_pod(key)
            self._insert_pod(pod)
            return
        if old_node is None:
            self._insert_pod(pod)
            return
        self.packer.intern_pod(pod)
        self._pods_by_node[old_node][key] = pod
        self._mark_dirty(old_node)

    def remove_pod(self, pod_key: str) -> None:
        if pod_key in self._pod_node:
            self._drop_pod(pod_key)

    def add_node(self, node: Node) -> None:
        self.packer.intern_node(node)
        self._nodes[node.name] = node
        self._pods_by_node.setdefault(node.name, {})
        self._shape_dirty = True

    def update_node(self, node: Node) -> None:
        if node.name not in self._nodes:
            self.add_node(node)
            return
        self.packer.intern_node(node)
        self._nodes[node.name] = node
        self._mark_dirty(node.name)

    def remove_node(self, name: str) -> None:
        self._nodes.pop(name, None)
        # pods on the node stay until their own delete events arrive
        # (reference keeps the NodeInfo if pods remain; we simply drop the
        # row — those pods no longer contribute to any schedulable node)
        self._shape_dirty = True

    # -- internals ---------------------------------------------------------

    def _insert_pod(self, pod: Pod) -> None:
        if not pod.node_name:
            raise CacheError(f"pod {pod.key()} has no node assignment")
        self.packer.intern_pod(pod)
        self._pods_by_node.setdefault(pod.node_name, {})[pod.key()] = pod
        self._pod_state[pod.key()] = _ADDED
        self._pod_node[pod.key()] = pod.node_name
        self._mark_dirty(pod.node_name)

    def _drop_pod(self, key: str) -> None:
        node = self._pod_node.pop(key)
        self._pod_state.pop(key, None)
        self._pod_deadline.pop(key, None)
        pods = self._pods_by_node.get(node)
        if pods:
            pods.pop(key, None)
        self._mark_dirty(node)

    def _mark_dirty(self, node_name: str) -> None:
        if node_name in self._nodes:
            self._dirty.add(node_name)

    def invalidate_snapshot(self) -> None:
        """Force a full repack on the next snapshot(). Needed when state
        OUTSIDE the node/pod tables changes row contents — e.g. a PVC
        rebinding changes which volume tokens scheduled pods resolve to
        without any node or pod mutation marking rows dirty."""
        self._shape_dirty = True

    # -- snapshot ----------------------------------------------------------

    def snapshot(self) -> NodeTable:
        """UpdateNodeInfoSnapshot (cache.go:211): return the columnar
        NodeTable, recomputing only dirty rows when shapes allow. Interning
        happens at mutation time (add/update/assume), so a clean-cache call
        is O(1) — the width comparison below catches any universe growth
        those mutations (or the driver interning pending pods) caused."""
        wkey = tuple(sorted(self.packer.widths().items()))

        if (
            self._shape_dirty
            or self._table is None
            or wkey != self._widths_key
        ):
            return self._full_repack(wkey)

        if not self._dirty:
            return self._table

        # incremental: repack only dirty rows. pack_nodes row computation is
        # node-local (cross-node info lives in the shared universe), so a
        # subset pack yields rows identical to a full pack.
        dirty = [n for n in self._dirty if n in self._nodes]
        sub_nodes = [self._nodes[n] for n in dirty]
        sub_pods = [p for n in dirty for p in self._pods_by_node.get(n, {}).values()]
        sub = self.packer.pack_nodes(sub_nodes, sub_pods)
        if tuple(sorted(self.packer.widths().items())) != wkey:
            # packing grew a universe mid-flight — fall back to full
            return self._full_repack(tuple(sorted(self.packer.widths().items())))
        t = self._table
        for j, name in enumerate(dirty):
            i = self._row_of[name]
            for f in dataclasses.fields(NodeTable):
                if f.name in ("n", "zone_valid"):
                    continue
                getattr(t, f.name)[i] = getattr(sub, f.name)[j]
        # zone_valid is universe-shaped; refresh from the subset pack
        self._table = dataclasses.replace(t, zone_valid=sub.zone_valid)
        self._dirty.clear()
        return self._table

    def _full_repack(self, wkey: Tuple) -> NodeTable:
        nodes = list(self._nodes.values())
        pods = [
            p
            for name in self._nodes
            for p in self._pods_by_node.get(name, {}).values()
        ]
        self._table = self.packer.pack_nodes(nodes, pods)
        self._row_of = {nd.name: i for i, nd in enumerate(nodes)}
        self._widths_key = tuple(sorted(self.packer.widths().items()))
        self._dirty.clear()
        self._shape_dirty = False
        return self._table

    def node_order(self) -> List[str]:
        """Row order of the last snapshot (row index -> node name)."""
        out = [""] * len(self._row_of)
        for name, i in self._row_of.items():
            out[i] = name
        return out
