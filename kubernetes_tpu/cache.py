"""Scheduler cache — in-memory truth about nodes and (assumed) pods, with
generation-tracked incremental snapshot packing.

Reference: ``pkg/scheduler/internal/cache/cache.go``. Two ideas carry over
directly:

1. **Assumed-pod state machine** (``cache/interface.go:36-47``): the driver
   optimistically AssumePod()s a pod onto its chosen node the moment the
   algorithm picks it, so the next cycle sees the capacity as used while the
   binding RPC is still in flight. FinishBinding starts a TTL; if the bound
   pod add never arrives from the watch before the TTL, the assumption
   expires and capacity frees (``cache.go:674`` cleanupAssumedPods).
   ForgetPod undoes an assumption on bind failure (``scheduler.go:447``).

2. **Generation-ordered incremental snapshots** (``cache.go:211``
   UpdateNodeInfoSnapshot, ``cache.go:135`` moveNodeInfoToHead): every
   mutation bumps a per-node generation; snapshotting recomputes only rows
   whose generation passed the last snapshot. Here the columnar NodeTable is
   cached and only dirty node rows are repacked (a full repack happens only
   when universe widths or the node set shape change — rare by design,
   since widths are power-of-two bucketed).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.sanitize import assert_held, make_lock
from kubernetes_tpu.snapshot import NodeTable, SnapshotPacker

#: cache.go — factory.NewConfigFactory wires a 30 s assumed-pod TTL.
DEFAULT_ASSUME_TTL_S = 30.0

# assumed-pod states
_ASSUMED = "assumed"  # Assume() called, bind in flight
_EXPIRING = "expiring"  # FinishBinding() called, TTL armed
_ADDED = "added"  # confirmed via watch AddPod


class CacheError(Exception):
    pass


class SchedulerCache:
    """Host-side cluster cache. Thread-free by design (the driver is a
    single loop around device dispatch); the watch pump calls the mutators
    between cycles."""

    def __init__(
        self,
        packer: Optional[SnapshotPacker] = None,
        ttl_s: float = DEFAULT_ASSUME_TTL_S,
        clock: Callable[[], float] = time.monotonic,
        max_dirty_frac: float = 0.25,
        lock_factory=None,
    ) -> None:
        self.packer = packer or SnapshotPacker()
        self.ttl_s = ttl_s
        self.clock = clock
        self._nodes: Dict[str, Node] = {}
        self._pods_by_node: Dict[str, Dict[str, Pod]] = {}
        self._pod_state: Dict[str, str] = {}  # key -> assumed state
        self._pod_node: Dict[str, str] = {}  # key -> node name
        self._pod_deadline: Dict[str, float] = {}  # key -> expiry (EXPIRING only)
        self._dirty: Set[str] = set()  # node names needing row repack
        self._shape_dirty = True  # node set / widths changed => full repack
        # cached snapshot state
        self._table: Optional[NodeTable] = None
        self._row_of: Dict[str, int] = {}
        self._widths_key: Optional[Tuple] = None
        # ---- device-resident snapshot state (device_snapshot) ------------
        #: dirty-row fraction above which patching the resident device
        #: table costs more than re-uploading it (the delta pack + scatter
        #: approach full-pack cost as the fraction grows)
        self.max_dirty_frac = max_dirty_frac
        self._dev = None  # resident ops.arrays.DeviceNodes
        self._dev_pad: int = 0  # its padded row count
        #: host refreshes the device hasn't applied yet: [(idx, sub)]
        #: deltas queued by _refresh_host (a host-only snapshot() caller
        #: consumes the dirty set; the device drains this queue later)
        self._pending_dev: List[Tuple[List[int], NodeTable]] = []
        #: a full host repack happened since the device last uploaded
        self._dev_stale: bool = True
        #: serializes snapshot refreshes: the cache is thread-free by
        #: design for MUTATIONS (driver loop), but server.py's
        #: extender-serving handler threads call the host snapshot()
        #: concurrently with the scheduler's device_snapshot() — without
        #: this lock a half-patched host table could be uploaded and
        #: then persist as the resident device snapshot
        self._snap_lock = make_lock(lock_factory, "cache.snap", "rlock")
        #: how the last device_snapshot() was produced: full | delta | clean
        self.last_snapshot_mode: str = ""
        #: host rows actually (re)packed + uploaded by the last call — the
        #: observability surface for "cost proportional to what changed"
        self.last_upload_rows: int = 0
        #: bytes the last call moved across the device boundary (full
        #: table or delta rows) — feeds the h2d transfer accounting
        self.last_upload_nbytes: int = 0
        #: faults.FaultInjector (or None): the chaos seam for the
        #: device-resident snapshot — "snapshot:device" rules
        #: (device_lost / device_oom) raise from device_snapshot(),
        #: exercising the scheduler's resident-rebuild recovery
        self.fault_injector = None
        #: obs.memledger.MemoryLedger (or None): device-memory
        #: accounting for the resident table + score summary — the
        #: scheduler attaches it post-construction (duck-typed, same
        #: contract as the injector above). Registrations ride the
        #: cache's OWN upload/drop edges so the ledger can never show
        #: a resident this cache already dropped
        self.memledger = None
        # ---- incremental-solve score cache (ops/fused_score) ---------
        #: device-resident NodeSummary aligned row-for-row with the
        #: resident DeviceNodes: the per-node slice of the score/
        #: feasibility plane the restricted solve picks candidates
        #: from. Maintained HERE, next to the snapshot, under the same
        #: full-vs-delta discipline — full uploads invalidate it
        #: (rebuilt lazily from the new resident table), delta cycles
        #: patch exactly the scattered rows with the same donated-
        #: scatter, clean cycles touch nothing — so it can never drift
        #: from the table it summarizes.
        self._summary = None
        #: bumps whenever the summary's row universe is rebuilt (full
        #: upload, drop, mesh change, enable) — the scheduler keys its
        #: warm-solve state (Sinkhorn potentials) on it so takeover /
        #: device-loss / epoch-growth invalidation is one comparison
        self.summary_generation = 0
        #: node COLUMNS patched by the last device_snapshot() call (the
        #: cycle's dirty frontier — candidate selection boosts them);
        #: empty on clean cycles, meaningless on full rebuilds (the
        #: whole plane was recomputed)
        self.last_patched_idx: List[int] = []
        self._score_cache_on = False
        self._summary_flags = {"honor_conditions": True,
                               "prefer_packed": False}
        #: the last score_summary() call had to REBUILD the plane from
        #: scratch (post-drop lazy build) — the scheduler reports zero
        #: reuse for that cycle instead of pretending the fresh plane
        #: was cached
        self.last_summary_rebuilt = False
        #: jax.sharding.Mesh (or None): the sharded execution backend's
        #: node-axis mesh (set_mesh). When set, the resident DeviceNodes
        #: lives SHARDED along N across the mesh: full uploads place via
        #: parallel.shard_nodes, and the delta scatter patches each
        #: shard locally (the re-packed rows + indices replicate; the
        #: donated scatter keeps the resident sharding, so no cross-
        #: device traffic beyond the small replicated delta)
        self.mesh = None

    # -- introspection -----------------------------------------------------

    def node(self, name: str) -> Optional[Node]:
        return self._nodes.get(name)

    def nodes(self) -> List[Node]:
        return list(self._nodes.values())

    def pods_on(self, node_name: str) -> List[Pod]:
        return list(self._pods_by_node.get(node_name, {}).values())

    def is_assumed(self, pod_key: str) -> bool:
        return self._pod_state.get(pod_key) in (_ASSUMED, _EXPIRING)

    def assumed_keys(self) -> List[str]:
        """Keys of every pod still in an assumed state (ASSUMED or
        EXPIRING) — what a takeover reconciliation diffs against the
        relisted hub truth, and what a deposed leader drains."""
        return [k for k, s in self._pod_state.items()
                if s in (_ASSUMED, _EXPIRING)]

    def pod_states(self) -> Dict[str, str]:
        """key -> "assumed" | "bound" for every cached pod — the
        state-conservation auditor's view (obs/audit.py): assumed covers
        ASSUMED and EXPIRING (bind in flight / TTL armed), bound is the
        watch-confirmed ADDED state."""
        return {
            k: ("assumed" if s in (_ASSUMED, _EXPIRING) else "bound")
            for k, s in self._pod_state.items()
        }

    def pod_count(self) -> int:
        return sum(len(m) for m in self._pods_by_node.values())

    def group_members(self, group: str) -> int:
        """Count of cached pods (assumed or bound) carrying
        ``pod_group == group`` — the gang gate's credit for members
        placed in EARLIER cycles. Without it a gang member whose bind
        failed transiently re-queues ALONE and can never satisfy
        minMember from inside its own batch: the group reads
        incomplete forever while its siblings run (a livelock, not a
        guard)."""
        return sum(1 for m in self._pods_by_node.values()
                   for p in m.values() if p.pod_group == group)

    def pod(self, key: str) -> Optional[Pod]:
        node = self._pod_node.get(key)
        if node is None:
            return None
        return self._pods_by_node.get(node, {}).get(key)

    def node_count(self) -> int:
        return len(self._nodes)

    # -- assumed-pod state machine ----------------------------------------

    def assume_pod(self, pod: Pod, node_name: str) -> None:
        """cache.go:275 AssumePod — place the pod in the cache now, before
        the binding is durable."""
        key = pod.key()
        if key in self._pod_state:
            raise CacheError(f"pod {key} already in cache ({self._pod_state[key]})")
        self.packer.intern_pod(pod)
        p = dataclasses.replace(pod, node_name=node_name)
        self._pods_by_node.setdefault(node_name, {})[key] = p
        self._pod_state[key] = _ASSUMED
        self._pod_node[key] = node_name
        self._mark_dirty(node_name)

    def finish_binding(self, pod_key: str) -> None:
        """cache.go FinishBinding — arm the TTL; the watch-confirmed AddPod
        must arrive before it fires."""
        if self._pod_state.get(pod_key) == _ASSUMED:
            self._pod_state[pod_key] = _EXPIRING
            self._pod_deadline[pod_key] = self.clock() + self.ttl_s

    def forget_pod(self, pod_key: str) -> None:
        """cache.go ForgetPod — undo an assumption (bind failed)."""
        if self._pod_state.get(pod_key) not in (_ASSUMED, _EXPIRING):
            raise CacheError(f"pod {pod_key} is not assumed")
        self._drop_pod(pod_key)

    def pop_expired(self) -> List[Pod]:
        """cache.go:674 cleanupAssumedPods — expire overdue assumptions,
        returning the expired POD OBJECTS (node_name still carrying the
        node they were assumed onto) so the driver can log, count, emit
        an event, and requeue them instead of letting the pod vanish
        silently (scheduler._reap_expired_assumptions)."""
        now = self.clock()
        expired_keys = [
            k
            for k, d in self._pod_deadline.items()
            if d <= now and self._pod_state.get(k) == _EXPIRING
        ]
        out: List[Pod] = []
        for k in expired_keys:
            p = self.pod(k)
            self._drop_pod(k)
            if p is not None:
                out.append(p)
        return out

    def cleanup_expired(self) -> List[str]:
        """Key-returning wrapper over :meth:`pop_expired` (the original
        surface — existing callers and tests pin the key list)."""
        return [p.key() for p in self.pop_expired()]

    # -- watch-driven mutations -------------------------------------------

    def add_pod(self, pod: Pod) -> None:
        """Watch AddPod for an assigned pod: confirms an assumption or adds
        an unseen pod (cache.go AddPod)."""
        key = pod.key()
        state = self._pod_state.get(key)
        if state in (_ASSUMED, _EXPIRING):
            cached_node = self._pod_node.get(key)
            if cached_node != pod.node_name:
                # assumed onto the wrong node — trust the API (cache.go logs
                # and re-adds)
                self._drop_pod(key)
                self._insert_pod(pod)
            else:
                self._pod_state[key] = _ADDED
                self._pod_deadline.pop(key, None)
                # refresh the stored object to the API's version
                self._pods_by_node[pod.node_name][key] = pod
                self._mark_dirty(pod.node_name)
        elif state is None:
            self._insert_pod(pod)
        # state == ADDED: duplicate add — treat as update
        else:
            self.update_pod(pod)

    def update_pod(self, pod: Pod) -> None:
        key = pod.key()
        old_node = self._pod_node.get(key)
        if old_node is not None and old_node != pod.node_name:
            self._drop_pod(key)
            self._insert_pod(pod)
            return
        if old_node is None:
            self._insert_pod(pod)
            return
        self.packer.intern_pod(pod)
        self._pods_by_node[old_node][key] = pod
        self._mark_dirty(old_node)

    def remove_pod(self, pod_key: str) -> None:
        if pod_key in self._pod_node:
            self._drop_pod(pod_key)

    def add_node(self, node: Node) -> None:
        self.packer.intern_node(node)
        self._nodes[node.name] = node
        self._pods_by_node.setdefault(node.name, {})
        self._shape_dirty = True

    def update_node(self, node: Node) -> None:
        if node.name not in self._nodes:
            self.add_node(node)
            return
        self.packer.intern_node(node)
        self._nodes[node.name] = node
        self._mark_dirty(node.name)

    def remove_node(self, name: str) -> None:
        self._nodes.pop(name, None)
        # pods on the node stay until their own delete events arrive
        # (reference keeps the NodeInfo if pods remain; we simply drop the
        # row — those pods no longer contribute to any schedulable node)
        self._shape_dirty = True

    # -- internals ---------------------------------------------------------

    def _insert_pod(self, pod: Pod) -> None:
        if not pod.node_name:
            raise CacheError(f"pod {pod.key()} has no node assignment")
        self.packer.intern_pod(pod)
        self._pods_by_node.setdefault(pod.node_name, {})[pod.key()] = pod
        self._pod_state[pod.key()] = _ADDED
        self._pod_node[pod.key()] = pod.node_name
        self._mark_dirty(pod.node_name)

    def _drop_pod(self, key: str) -> None:
        node = self._pod_node.pop(key)
        self._pod_state.pop(key, None)
        self._pod_deadline.pop(key, None)
        pods = self._pods_by_node.get(node)
        if pods:
            pods.pop(key, None)
        self._mark_dirty(node)

    def _mark_dirty(self, node_name: str) -> None:
        if node_name in self._nodes:
            self._dirty.add(node_name)

    def invalidate_snapshot(self) -> None:
        """Force a full repack on the next snapshot(). Needed when state
        OUTSIDE the node/pod tables changes row contents — e.g. a PVC
        rebinding changes which volume tokens scheduled pods resolve to
        without any node or pod mutation marking rows dirty."""
        self._shape_dirty = True

    # -- snapshot ----------------------------------------------------------

    def snapshot(self) -> NodeTable:
        """UpdateNodeInfoSnapshot (cache.go:211): return the columnar
        NodeTable, recomputing only dirty rows when shapes allow. Interning
        happens at mutation time (add/update/assume), so a clean-cache call
        is O(1) — the width comparison below catches any universe growth
        those mutations (or the driver interning pending pods) caused."""
        table, _mode, _idx, _sub = self._refresh_host()
        return table

    def _refresh_host(self):
        with self._snap_lock:
            return self._refresh_host_locked()

    def _refresh_host_locked(self):
        """Bring the cached host NodeTable up to date. Returns
        ``(table, mode, idx, sub)`` where mode is ``full`` | ``clean`` |
        ``delta``; on ``delta``, ``idx`` is the patched row indices and
        ``sub`` the delta NodeTable whose row j landed at ``idx[j]``.

        Device coherence: every host mutation is ALSO queued on
        ``_pending_dev`` (deltas) / flagged on ``_dev_stale`` (fulls), so
        a host-only ``snapshot()`` caller (server.py's extender-serving
        path) consuming the dirty set can never leave the resident
        device table silently stale — device_snapshot() drains the queue
        it missed."""
        assert_held(self._snap_lock, "cache._refresh_host_locked")
        # EXACT universe signature, not the bucketed widths: interner
        # growth WITHIN a power-of-two bucket still changes clean rows
        # (a pending pod interning a new selector pair must light
        # pair_mh on every node carrying that label) — the delta-vs-full
        # property test caught exactly that staleness against the old
        # widths-only key.
        wkey = self.packer.universe_node_sig()

        if (
            self._shape_dirty
            or self._table is None
            or wkey != self._widths_key
        ):
            self._dev_stale = True
            self._pending_dev.clear()
            return self._full_repack(), "full", None, None

        if not self._dirty:
            return self._table, "clean", None, None

        # incremental: repack only dirty rows. pack_nodes row computation is
        # node-local (cross-node info lives in the shared universe), so a
        # subset pack yields rows identical to a full pack.
        dirty = [n for n in self._dirty if n in self._nodes]
        sub_nodes = [self._nodes[n] for n in dirty]
        sub_pods = [p for n in dirty for p in self._pods_by_node.get(n, {}).values()]
        sub = self.packer.pack_nodes_delta(sub_nodes, sub_pods)
        if self.packer.universe_node_sig() != wkey:
            # packing grew a universe mid-flight — fall back to full
            self._dev_stale = True
            self._pending_dev.clear()
            return (
                self._full_repack(), "full", None, None,
            )
        t = self._table
        idx = []
        for j, name in enumerate(dirty):
            i = self._row_of[name]
            idx.append(i)
            for f in dataclasses.fields(NodeTable):
                if f.name in ("n", "zone_valid"):
                    continue
                getattr(t, f.name)[i] = getattr(sub, f.name)[j]
        # zone_valid is universe-shaped; refresh from the subset pack
        self._table = dataclasses.replace(t, zone_valid=sub.zone_valid)
        self._dirty.clear()
        if self._dev is not None and not self._dev_stale:
            self._pending_dev.append((idx, sub))
        return self._table, "delta", idx, sub

    def device_snapshot(self):
        """The device-resident snapshot: returns ``(table, dev, mode)``
        where ``dev`` is a DeviceNodes that lives on device ACROSS cycles.

        Steady-state cost is proportional to what changed: a clean cache
        returns the resident arrays untouched; a small dirty set re-packs
        only those rows on host and patches them in with one jitted
        scatter (buffer-donated, so no reallocation); a full rebuild
        happens only on node-set shape changes, universe width growth,
        explicit invalidation, or when the dirty fraction exceeds
        ``max_dirty_frac`` (patching would cost more than re-uploading).
        The delta-vs-full property test pins bit-identical arrays."""
        import numpy as np

        from kubernetes_tpu.ops.arrays import nodes_to_device, scatter_node_rows
        from kubernetes_tpu.utils.interner import bucket_size

        from kubernetes_tpu.obs.jaxtel import tree_nbytes

        # the SAME lock _refresh_host takes (RLock): branch selection,
        # pending-queue drain, and the upload itself must see one
        # consistent host table even while server handler threads call
        # the host-only snapshot() concurrently
        with self._snap_lock:
            return self._device_snapshot_locked(tree_nbytes)

    def _device_snapshot_locked(self, tree_nbytes):
        assert_held(self._snap_lock, "cache._device_snapshot_locked")
        import numpy as np

        from kubernetes_tpu.ops.arrays import nodes_to_device, scatter_node_rows
        from kubernetes_tpu.utils.interner import bucket_size

        if self.fault_injector is not None:
            # chaos seam: an armed device_lost/device_oom rule raises
            # here, standing in for a real XLA device error during the
            # scatter/upload — the driver's recovery drops the resident
            # table and rebuilds from the host mirror
            self.fault_injector.device_hook("snapshot:device")
        table, _mode, _idx, _sub = self._refresh_host()
        n_pad = bucket_size(max(table.n, 1))
        if self.mesh is not None:
            # the node bucket must divide across the mesh: both are
            # powers of two, so padding up to the device count suffices
            # (a 2-node cluster on an 8-device mesh rides 8 rows)
            n_pad = max(n_pad, int(self.mesh.devices.size))
        self.last_upload_rows = 0
        self.last_upload_nbytes = 0
        self.last_patched_idx = []
        pending_rows = sum(len(i) for i, _ in self._pending_dev)
        if (self._dev is None or self._dev_stale or n_pad != self._dev_pad
                or pending_rows > self.max_dirty_frac * max(table.n, 1)):
            # clear BEFORE the upload: a delta appended concurrently by a
            # host-only snapshot() (server.py runs in a handler thread)
            # then survives for the next drain — re-applying rows the
            # full table already carries is idempotent; dropping a delta
            # queued mid-upload would not be
            self._pending_dev.clear()
            if self.mesh is not None:
                # full rebuilds, interner-growth repacks, and post-
                # device-loss rebuilds all re-place onto the mesh here —
                # one seam (parallel.place_node_table, shared with the
                # non-resident scheduler paths), so no recovery path can
                # resurrect a single-device resident table under a
                # mesh-on scheduler
                from kubernetes_tpu.parallel.mesh import place_node_table

                self._dev = place_node_table(table, self.mesh,
                                             pad_to=n_pad)
            else:
                self._dev = nodes_to_device(table, pad_to=n_pad)
            self._dev_pad = n_pad
            self._dev_stale = False
            self.last_snapshot_mode = "full"
            self.last_upload_rows = table.n
            self.last_upload_nbytes = tree_nbytes(self._dev)
            self._mem_register("cache.node_table", self.last_upload_nbytes,
                               shape=f"N{n_pad}")
            if self._score_cache_on:
                # full rebuild: the whole score plane is recomputed —
                # drop the summary (rebuilt lazily from the new resident
                # table) and bump the generation so warm-solve state
                # keyed on it (Sinkhorn potentials) is invalidated too
                self._summary = None
                self.summary_generation += 1
                self._mem_deregister("cache.score_summary")
        elif not self._pending_dev:
            self.last_snapshot_mode = "clean"
        else:
            # delta: convert ONLY the queued dirty rows to device layout
            # and scatter them into the resident arrays (one jitted call
            # per queued host refresh — usually exactly one per cycle);
            # padded index slots point out of bounds and are dropped.
            # Pop-drain, never iterate-then-clear: a delta appended
            # concurrently must survive for the next drain instead of
            # being discarded unapplied.
            while self._pending_dev:
                idx, sub = self._pending_dev.pop(0)
                d_pad = bucket_size(max(len(idx), 1), 4)
                sub_dev = nodes_to_device(sub, pad_to=d_pad)
                pidx = np.full((d_pad,), n_pad, np.int32)
                pidx[: len(idx)] = idx
                if self.mesh is not None:
                    # replicate the delta rows so each shard applies its
                    # own slice locally (the donated scatter preserves
                    # the resident node-axis sharding; rows landing on
                    # other shards drop out of this shard's window)
                    from kubernetes_tpu.parallel.mesh import replicate

                    sub_dev = replicate(sub_dev, self.mesh)
                if self._score_cache_on and self._summary is not None:
                    # patch the score summary's SAME rows from the SAME
                    # delta pack — clean columns of the cached plane are
                    # reused untouched, only the dirty columns recompute
                    # (O(churn))
                    from kubernetes_tpu.ops.fused_score import (
                        node_summary,
                        patch_node_summary,
                    )

                    sub_sum = node_summary(sub_dev, **self._summary_flags)
                    self._summary = patch_node_summary(
                        self._summary, sub_sum, pidx)
                self._dev = scatter_node_rows(self._dev, sub_dev, pidx)
                self.last_upload_rows += len(idx)
                self.last_upload_nbytes += tree_nbytes(sub_dev)
                self.last_patched_idx.extend(idx)
            self.last_snapshot_mode = "delta"
        return table, self._dev, self.last_snapshot_mode

    def set_mesh(self, mesh) -> None:
        """Attach (or detach, with ``None``) the node-axis device mesh.
        Changing the mesh invalidates the resident table: its buffers
        live on the old device set, and the next device_snapshot()
        re-places in full onto the new one."""
        if mesh is not self.mesh:
            self.mesh = mesh
            self.drop_device_snapshot()

    def drop_device_snapshot(self) -> None:
        """Release the resident device table (tests / memory pressure);
        the next device_snapshot() re-uploads in full. The score-cache
        summary drops with it — every invalidation edge that lands here
        (takeover reconcile, device-loss recovery, mesh change) also
        drops the cached score plane and bumps its generation."""
        self._dev = None
        self._dev_pad = 0
        self._dev_stale = True
        self._pending_dev.clear()
        self._summary = None
        self.last_patched_idx = []
        self.summary_generation += 1
        # every ledger byte this cache owns dies with the drop — a
        # registration surviving here is exactly the leak the soak's
        # mem_residents sentinel exists to catch
        self._mem_deregister("cache.node_table", "cache.score_summary")

    def has_device_snapshot(self) -> bool:
        """Whether a resident device table currently exists (no upload,
        no lazy build) — the scheduler's state_sizes device keys and the
        drop-audit tests read this."""
        return self._dev is not None

    def _mem_register(self, name: str, nbytes: int, shape: str = "") -> None:
        """Duck-typed memory-ledger registration (no-op unattached)."""
        ml = self.memledger
        if ml is not None and getattr(ml, "enabled", False):
            ml.register(name, nbytes, shape=shape)

    def _mem_deregister(self, *names: str) -> None:
        ml = self.memledger
        if ml is not None and getattr(ml, "enabled", False):
            for n in names:
                ml.deregister(n)

    # -- incremental-solve score cache --------------------------------------

    def enable_score_cache(self, honor_conditions: bool = True,
                           prefer_packed: bool = False) -> None:
        """Turn the device-resident score/feasibility summary on (the
        scheduler does this when ``incremental.enabled``). The flags pin
        the summary's semantics to the scheduler's Policy/objective:
        whether node-condition predicates gate candidate eligibility,
        and whether the candidate ranking prefers packed (fullest-first)
        columns. Off by default — non-incremental users pay nothing."""
        self._score_cache_on = True
        self._summary_flags = {"honor_conditions": bool(honor_conditions),
                               "prefer_packed": bool(prefer_packed)}
        self._summary = None
        self.summary_generation += 1
        self._mem_deregister("cache.score_summary")

    def drop_score_summary(self) -> None:
        """Drop ONLY the cached score plane (the resident node table is
        still coherent): the next score_summary() rebuilds from the
        resident table, and the generation bump kills any warm-solve
        state keyed on the old plane. The scheduler's dirty-frac blowout
        route lands here — the snapshot's own blowout goes through the
        full-upload branch above."""
        with self._snap_lock:
            self._summary = None
            self.summary_generation += 1
            self._mem_deregister("cache.score_summary")

    def has_score_summary(self) -> bool:
        """Whether a cached score plane currently exists (no lazy
        build) — the scheduler's invalidation accounting asks before
        counting a drop that would be a no-op."""
        return self._summary is not None

    def score_summary(self):
        """The device-resident NodeSummary aligned row-for-row with the
        resident DeviceNodes (None when the cache is off or no resident
        table exists — e.g. host-mode snapshots during a device
        cooloff). Built lazily from the resident table on first demand
        after a full rebuild; thereafter patched in place by the delta
        path above. ``last_summary_rebuilt`` reports which of the two
        happened."""
        with self._snap_lock:
            self.last_summary_rebuilt = False
            if not self._score_cache_on or self._dev is None:
                return None
            if self._summary is None:
                from kubernetes_tpu.obs.jaxtel import tree_nbytes
                from kubernetes_tpu.ops.fused_score import node_summary

                self._summary = node_summary(self._dev,
                                             **self._summary_flags)
                self.last_summary_rebuilt = True
                self._mem_register("cache.score_summary",
                                   tree_nbytes(self._summary),
                                   shape=f"N{self._dev_pad}")
            return self._summary

    def _full_repack(self) -> NodeTable:
        nodes = list(self._nodes.values())
        pods = [
            p
            for name in self._nodes
            for p in self._pods_by_node.get(name, {}).values()
        ]
        self._table = self.packer.pack_nodes(nodes, pods)
        self._row_of = {nd.name: i for i, nd in enumerate(nodes)}
        # the pack itself may intern (first sight of a node's taints /
        # images) — the stored signature must be the POST-pack state
        self._widths_key = self.packer.universe_node_sig()
        self._dirty.clear()
        self._shape_dirty = False
        return self._table

    def node_order(self) -> List[str]:
        """Row order of the last snapshot (row index -> node name)."""
        out = [""] * len(self._row_of)
        for name, i in self._row_of.items():
            out[i] = name
        return out
