"""Prometheus-compatible metrics, mirroring the reference scheduler's
metric names and shapes (``pkg/scheduler/metrics/metrics.go``) so existing
dashboards/SLO scrapes (e.g. the e2e latency gates,
test/e2e/framework/metrics/latencies.go:257) keep working:

- ``scheduler_schedule_attempts_total{result}`` (counter; result ∈
  scheduled|unschedulable|error — metrics.go:54)
- ``scheduler_scheduling_duration_seconds{operation}`` (summary by phase —
  metrics.go:66; quantiles 0.5/0.9/0.99)
- ``scheduler_e2e_scheduling_duration_seconds`` (histogram, buckets
  exp(0.001, ×2, 15) — metrics.go:88)
- per-phase algorithm histograms, binding latency, preemption counters,
  ``scheduler_pending_pods{queue}`` gauges.

Beyond the reference set: degradation-ladder telemetry (fallbacks,
breaker states, per-tier latency), runtime JAX telemetry
(compile/retrace/transfer counters), and the PR-4 explainability +
queue-observability block —
``scheduler_unschedulable_pods_total{reason}`` /
``scheduler_unschedulable_node_counts{reason}`` (from the batched
why-pending reduction, ``obs/explain.py``),
``scheduler_queue_pod_age_seconds{queue}`` sub-queue residency
histograms, the ``scheduler_pod_scheduling_attempts`` histogram, and
``scheduler_queue_incoming_pods_total{event}`` queue-event counters; plus
the streaming-serving block (``kubernetes_tpu/serving``) —
``scheduler_doorbell_rings_total{reason}``,
``scheduler_microbatch_flushes_total{trigger}`` /
``scheduler_microbatch_window_seconds``,
``scheduler_flowcontrol_{rejected_requests_total,current_inflight_requests}``,
and ``scheduler_watch_evictions_total``; plus the crash/failover
recovery block — ``scheduler_recovery_*_total`` (takeovers, adopted /
forgotten / requeued / drained pods, fenced binds, device resets) and
``scheduler_cache_expired_assumptions_total``; plus the scenario-pack
block (``kubernetes_tpu/scenarios``) —
``scheduler_scenario_quality{score}`` placement-quality gauges and the
in-batch preemption-cascade counters
``scheduler_scenario_{cascade_victims,displaced_replaced}_total``; plus
the incremental-solve block (docs/perf.md §5) —
``scheduler_incremental_cycles_total{scope}`` (restricted | full |
declined | under-placed), the ``scheduler_incremental_reuse_fraction``
gauge, and
``scheduler_incremental_invalidations_total{reason}``; plus the
perf-ledger block (``obs/ledger.py``) —
``scheduler_cycle_model_efficiency`` /
``scheduler_cycle_modeled_cost_seconds`` measured-vs-modeled gauges,
``scheduler_cycle_phase_seconds{phase}`` per-phase attribution (stale
phases read 0, the explain-gauge freshness rule), and
``scheduler_slo_burn_rate{objective,window}``; plus the device-memory
ledger block (``obs/memledger.py``) —
``scheduler_device_memory_bytes{kind,device}`` (resident | peak |
limit measured per device, modeled = the ledger's resident
registrations; stale device series read 0),
``scheduler_memory_model_efficiency`` (modeled/measured bytes at the
last sampled cycle boundary, -1 sentinel on sample-free cycles), and
``scheduler_memory_preflight_total{action}`` (ok | split | shed
capacity-preflight verdicts); plus the network-fault
robustness block (PR 15) —
``scheduler_bind_ambiguous_total{resolution}`` (the ambiguous-RPC bind
protocol's read-your-write verdicts) and
``scheduler_invariant_violations_total{invariant}`` (the
state-conservation auditor, ``obs/audit.py``). Note
``scheduler_e2e_scheduling_duration_seconds`` observes PER-POD
create-to-bind latency (queue-add stamp to bind) since the serving PR,
matching the reference's per-pod scheduleOne observation.

Implementation is a small text-exposition registry (no client library in
the image); histograms use the reference's bucket layouts. Exposition
follows the text-format grammar (HELP/TYPE before samples, cumulative
buckets with ``+Inf`` == ``_count``, label-value escaping) — pinned by
the conformance test in ``tests/test_metrics_exposition.py``.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple


def exponential_buckets(start: float, factor: float, count: int) -> List[float]:
    return [start * (factor ** i) for i in range(count)]


_DEF_BUCKETS = exponential_buckets(0.001, 2, 15)  # metrics.go:91 et al.


def escape_label_value(v: str) -> str:
    """Prometheus text-format label escaping (backslash, quote, newline)
    — free-text labels (solver rejection reasons, extender names) must
    never break the exposition line grammar."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        return tuple(labels.get(k, "") for k in self.label_names)

    def _fmt_labels(self, key: Tuple[str, ...], extra: str = "") -> str:
        parts = [
            f'{k}="{escape_label_value(v)}"'
            for k, v in zip(self.label_names, key)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        k = self._key(labels)
        self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def expose(self) -> List[str]:
        out = []
        for k, v in sorted(self._values.items()):
            out.append(f"{self.name}{self._fmt_labels(k)} {v}")
        return out


class Gauge(Counter):
    kind = "gauge"

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        #: monotone write counter — freshness probes (soak sentinels)
        #: need "was this gauge WRITTEN recently", and a value
        #: fingerprint alone can't tell maintained-and-idle (depth set
        #: back to 0 every cycle) from abandoned (nobody sets it)
        self.writes = 0

    def set(self, value: float, **labels) -> None:
        self.writes += 1
        self._values[self._key(labels)] = float(value)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, label_names=(), buckets: Optional[List[float]] = None):
        super().__init__(name, help_, label_names)
        self.buckets = sorted(buckets or _DEF_BUCKETS)
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sum: Dict[Tuple[str, ...], float] = {}
        self._n: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float, **labels) -> None:
        # per-bucket (non-cumulative) storage + one bisect insert: the
        # hot path is O(log buckets), not O(buckets) — per-pod callers
        # (e2e latency, the six journey-phase observes per bound pod)
        # sit on the bind path and pay this on every pod. The slot past
        # the last bucket holds the +Inf overflow; expose()/quantile()
        # rebuild the cumulative view on the cold path.
        k = self._key(labels)
        counts = self._counts.get(k)
        if counts is None:
            counts = self._counts[k] = [0] * (len(self.buckets) + 1)
        counts[bisect.bisect_left(self.buckets, value)] += 1
        self._sum[k] = self._sum.get(k, 0.0) + value
        self._n[k] = self._n.get(k, 0) + 1

    def child(self, **labels):
        """Precomputed-label observe handle for per-pod hot paths (the
        journey tracker's six phase observes per bound pod): binds the
        label key once, so each call is one bisect + three dict writes
        instead of re-deriving the key tuple from kwargs."""
        k = self._key(labels)
        counts = self._counts.get(k)
        if counts is None:
            counts = self._counts[k] = [0] * (len(self.buckets) + 1)
        buckets = self.buckets

        def observe(value: float) -> None:
            counts[bisect.bisect_left(buckets, value)] += 1
            self._sum[k] = self._sum.get(k, 0.0) + value
            self._n[k] = self._n.get(k, 0) + 1

        return observe

    def count(self, **labels) -> int:
        return self._n.get(self._key(labels), 0)

    def quantile(self, q: float, **labels) -> float:
        """Prometheus histogram_quantile analog: linear interpolation
        inside the first bucket whose cumulative count reaches q·n."""
        k = self._key(labels)
        n = self._n.get(k, 0)
        if n == 0:
            return 0.0
        target = q * n
        counts = self._counts[k]
        lo = 0.0
        cum = 0
        for i, b in enumerate(self.buckets):
            prev = cum
            cum += counts[i]
            if cum >= target:
                frac = (target - prev) / max(counts[i], 1)
                return lo + (b - lo) * min(frac, 1.0)
            lo = b
        return self.buckets[-1]

    def expose(self) -> List[str]:
        out = []
        for k in sorted(self._n):
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += self._counts[k][i]
                le = 'le="%s"' % b
                out.append(
                    f"{self.name}_bucket{self._fmt_labels(k, le)} {cum}"
                )
            le_inf = 'le="+Inf"'
            out.append(
                f"{self.name}_bucket{self._fmt_labels(k, le_inf)} {self._n[k]}"
            )
            out.append(f"{self.name}_sum{self._fmt_labels(k)} {self._sum[k]}")
            out.append(f"{self.name}_count{self._fmt_labels(k)} {self._n[k]}")
        return out


class Summary(_Metric):
    """SummaryVec analog (scheduling_duration_seconds is a summary with
    precomputed quantiles, metrics.go:64). Keeps a bounded sample window."""

    kind = "summary"
    objectives = (0.5, 0.9, 0.99)

    def __init__(self, name, help_, label_names=(), max_samples: int = 4096):
        super().__init__(name, help_, label_names)
        self.max_samples = max_samples
        self._samples: Dict[Tuple[str, ...], List[float]] = {}
        self._sum: Dict[Tuple[str, ...], float] = {}
        self._n: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float, **labels) -> None:
        k = self._key(labels)
        s = self._samples.setdefault(k, [])
        s.append(value)
        if len(s) > self.max_samples:
            del s[: len(s) // 2]
        self._sum[k] = self._sum.get(k, 0.0) + value
        self._n[k] = self._n.get(k, 0) + 1

    def quantile(self, q: float, **labels) -> float:
        s = sorted(self._samples.get(self._key(labels), []))
        if not s:
            return float("nan")
        return s[min(len(s) - 1, int(math.ceil(q * len(s))) - 1)]

    def expose(self) -> List[str]:
        out = []
        for k in sorted(self._n):
            for q in self.objectives:
                qlabel = 'quantile="%s"' % q
                out.append(
                    f"{self.name}{self._fmt_labels(k, qlabel)} "
                    f"{self.quantile(q, **dict(zip(self.label_names, k)))}"
                )
            out.append(f"{self.name}_sum{self._fmt_labels(k)} {self._sum[k]}")
            out.append(f"{self.name}_count{self._fmt_labels(k)} {self._n[k]}")
        return out


class Registry:
    def __init__(self) -> None:
        self._metrics: List[_Metric] = []
        self._lock = threading.Lock()

    def register(self, m):
        with self._lock:
            self._metrics.append(m)
        return m

    def expose(self) -> str:
        lines: List[str] = []
        with self._lock:
            for m in self._metrics:
                lines.append(f"# HELP {m.name} {m.help}")
                lines.append(f"# TYPE {m.name} {m.kind}")
                lines.extend(m.expose())
        return "\n".join(lines) + "\n"


class SchedulerMetrics:
    """The reference's metric set (metrics.Register, metrics.go:186),
    recorded by the driver each cycle."""

    # result labels (metrics.go:41-49)
    SCHEDULED, UNSCHEDULABLE, ERROR = "scheduled", "unschedulable", "error"

    def __init__(self, registry: Optional[Registry] = None) -> None:
        r = self.registry = registry or Registry()
        self.schedule_attempts = r.register(Counter(
            "scheduler_schedule_attempts_total",
            "Number of attempts to schedule pods, by result.",
            ["result"],
        ))
        self.scheduling_duration = r.register(Summary(
            "scheduler_scheduling_duration_seconds",
            "Scheduling latency split by sub-parts of the scheduling operation.",
            ["operation"],
        ))
        self.e2e_scheduling_duration = r.register(Histogram(
            "scheduler_e2e_scheduling_duration_seconds",
            "E2e scheduling latency (scheduling algorithm + binding).",
        ))
        self.algorithm_duration = r.register(Histogram(
            "scheduler_scheduling_algorithm_duration_seconds",
            "Scheduling algorithm latency.",
        ))
        self.predicate_duration = r.register(Histogram(
            "scheduler_scheduling_algorithm_predicate_evaluation_seconds",
            "Scheduling algorithm predicate evaluation duration.",
        ))
        self.priority_duration = r.register(Histogram(
            "scheduler_scheduling_algorithm_priority_evaluation_seconds",
            "Scheduling algorithm priority evaluation duration.",
        ))
        self.preemption_duration = r.register(Histogram(
            "scheduler_scheduling_algorithm_preemption_evaluation_seconds",
            "Scheduling algorithm preemption evaluation duration.",
        ))
        self.binding_duration = r.register(Histogram(
            "scheduler_binding_duration_seconds", "Binding latency.",
        ))
        self.preemption_victims = r.register(Counter(
            "scheduler_pod_preemption_victims", "Number of selected preemption victims",
        ))
        self.preemption_attempts = r.register(Counter(
            "scheduler_total_preemption_attempts",
            "Total preemption attempts in the cluster till now",
        ))
        self.pending_pods = r.register(Gauge(
            "scheduler_pending_pods",
            "Number of pending pods, by the queue type.",
            ["queue"],
        ))
        # -- degradation-ladder observability (no reference analog; the
        # robustness layer around the out-of-process batch solver) -----
        self.solver_fallbacks = r.register(Counter(
            "scheduler_solver_fallback_total",
            "Solve attempts that fell from one ladder tier to the next.",
            ["from_tier", "to_tier"],
        ))
        self.breaker_state = r.register(Gauge(
            "scheduler_circuit_breaker_state",
            "Circuit breaker state per target (0=closed, 1=half-open, "
            "2=open).",
            ["target"],
        ))
        self.solver_tier_duration = r.register(Histogram(
            "scheduler_solver_tier_duration_seconds",
            "Solve latency per degradation-ladder tier.",
            ["tier"],
        ))
        self.solver_rejections = r.register(Counter(
            "scheduler_solver_result_rejections_total",
            "Solver results rejected by validation, by tier and reason.",
            ["tier", "reason"],
        ))
        self.solver_retries = r.register(Counter(
            "scheduler_solver_retries_total",
            "In-cycle solver retries before falling through, by tier.",
            ["tier"],
        ))
        self.extender_degraded = r.register(Counter(
            "scheduler_extender_degraded_total",
            "Extender calls shed by an open breaker or a blown cycle "
            "deadline.",
            ["extender"],
        ))
        self.deadline_exceeded = r.register(Counter(
            "scheduler_cycle_deadline_exceeded_total",
            "Cycles whose deadline expired before the ladder finished.",
        ))
        # -- crash / failover / device-loss recovery (config.Recovery-
        # Config; scheduler.reconcile + fenced binds + resident rebuild)
        self.cache_expired_assumptions = r.register(Counter(
            "scheduler_cache_expired_assumptions_total",
            "Assumed pods whose bind confirmation never arrived within "
            "the assume TTL — capacity freed and the pod requeued.",
        ))
        self.recovery_takeovers = r.register(Counter(
            "scheduler_recovery_takeovers_total",
            "Leadership takeover / cold-start reconciliations run "
            "(relist truth, adopt, forget, requeue, rebuild residents).",
        ))
        self.recovery_adopted = r.register(Counter(
            "scheduler_recovery_adopted_pods_total",
            "Bound pods adopted from the relisted hub truth during a "
            "takeover reconciliation (bound by a dead incarnation or "
            "another writer).",
        ))
        self.recovery_forgotten = r.register(Counter(
            "scheduler_recovery_forgotten_assumptions_total",
            "Cached assumptions the relisted hub truth contradicted "
            "(pod gone, recreated uid, or bound elsewhere) — forgotten "
            "during takeover reconciliation.",
        ))
        self.recovery_requeued = r.register(Counter(
            "scheduler_recovery_requeued_pods_total",
            "Unbound responsible pods (re)queued by a takeover "
            "reconciliation so every schedulable pod is eventually "
            "bound.",
        ))
        self.recovery_drained = r.register(Counter(
            "scheduler_recovery_drained_pods_total",
            "In-flight pods (Permit-parked or assumed) drained and "
            "requeued when this scheduler stopped leading.",
        ))
        self.recovery_fenced_binds = r.register(Counter(
            "scheduler_recovery_fenced_binds_total",
            "Binds aborted by the lease fence (deposed or renew-stalled "
            "leader) instead of racing the new leader at the hub.",
        ))
        self.recovery_device_resets = r.register(Counter(
            "scheduler_recovery_device_resets_total",
            "Resident device snapshot drops + rebuilds after a device "
            "error (device lost / OOM).",
        ))
        # -- network-fault robustness (PR 15): the ambiguous-RPC bind
        # protocol and the state-conservation auditor ------------------
        self.bind_ambiguous = r.register(Counter(
            "scheduler_bind_ambiguous_total",
            "Ambiguously timed-out bind RPCs by read-your-write "
            "resolution: adopted (the hub HAD committed — confirmed, "
            "never re-bound), requeued (verified not committed — safe "
            "retry), conflict (bound elsewhere / recreated uid), gone "
            "(pod deleted mid-bind), deferred (verification itself "
            "unreachable — pod parked assumed, re-probed later). "
            "expired-* variants are the same verdicts reached from an "
            "assume-TTL expiry (lost watch confirmation) instead of an "
            "in-cycle bind timeout.",
            ["resolution"],
        ))
        self.invariant_violations = r.register(Counter(
            "scheduler_invariant_violations_total",
            "State-conservation auditor violations by invariant "
            "(multi-state, capacity, lost-pod, double-bind-risk, "
            "stale-entry — obs/audit.py). Any nonzero value is a "
            "correctness bug, never noise.",
            ["invariant"],
        ))
        self.lock_sanitizer_findings = r.register(Counter(
            "scheduler_lock_sanitizer_findings_total",
            "Instrumented-lock sanitizer findings by kind (order-cycle "
            "= the acquisition-order graph gained a cycle, a potential "
            "deadlock; held-too-long = a lock exceeded its hold budget; "
            "guard-violation = an assert_held declaration was false — "
            "kubernetes_tpu/sanitize.py). Only emitted when "
            "observability.lockSanitizer armed the sanitizer; any "
            "order-cycle or guard-violation is a correctness bug.",
            ["kind"],
        ))
        # -- runtime JAX telemetry (kubernetes_tpu/obs): the dynamic twin
        # of graftlint's static R3 rule, plus host-boundary transfer
        # accounting and Sinkhorn convergence ---------------------------
        self.jax_compile_cache = r.register(Counter(
            "scheduler_jax_compile_cache_total",
            "Jitted-call observations by site and class (hit = abstract "
            "signature seen before; compile = site's first signature; "
            "retrace = NEW signature at a warmed site, i.e. an XLA "
            "recompile).",
            ["site", "result"],
        ))
        self.jax_retraces = r.register(Counter(
            "scheduler_jax_retrace_total",
            "Retraces (new abstract signature at an already-compiled call "
            "site) — each one is a synchronous XLA recompile on the hot "
            "path.",
            ["site"],
        ))
        self.jax_retrace_storms = r.register(Counter(
            "scheduler_jax_retrace_storm_total",
            "Retrace storms: threshold-many retraces at one site within "
            "the call window (bucketed batch shapes exist to keep this 0).",
            ["site"],
        ))
        self.host_transfers = r.register(Counter(
            "scheduler_host_transfer_total",
            "Device<->host transfers at declared host boundaries, by site "
            "and direction (h2d upload / d2h readback).",
            ["site", "direction"],
        ))
        self.host_transfer_bytes = r.register(Counter(
            "scheduler_host_transfer_bytes_total",
            "Bytes moved across the device boundary at declared host "
            "boundaries.",
            ["site", "direction"],
        ))
        self.readback_bytes = r.register(Counter(
            "scheduler_readback_bytes_total",
            "Device->host readback bytes per declared site — the readback "
            "wall's dedicated meter (PR 7 shrank the steady-state cycle "
            "to one small solve-result transfer; this is what keeps it "
            "measurable after the fall).",
            ["site"],
        ))
        self.sinkhorn_iterations = r.register(Histogram(
            "scheduler_sinkhorn_iterations",
            "Sinkhorn scaling iterations until the row-potential delta "
            "dropped under tolerance (== configured iters when it never "
            "converged).",
            buckets=[1, 2, 4, 8, 16, 32, 64, 128],
        ))
        self.sinkhorn_residual = r.register(Gauge(
            "scheduler_sinkhorn_final_residual",
            "Final max row-potential delta of the last Sinkhorn solve "
            "(log-domain; lower is more converged).",
        ))
        # -- incremental snapshot + pipelined executor (PR 5) -----------
        self.snapshot_packs = r.register(Counter(
            "scheduler_snapshot_packs_total",
            "Device snapshot refreshes by mode: full = whole-table pack "
            "+ upload; delta = dirty rows re-packed and scattered into "
            "the resident device table; clean = nothing changed, the "
            "resident arrays were reused untouched.",
            ["mode"],
        ))
        self.snapshot_rows_packed = r.register(Counter(
            "scheduler_snapshot_rows_packed_total",
            "Node rows re-packed on host and uploaded across snapshot "
            "refreshes — steady-state cost proportional to what changed.",
        ))
        self.pipeline_chunks = r.register(Counter(
            "scheduler_pipeline_chunks_total",
            "Sub-batches executed by the pipelined cycle executor "
            "(pack/solve/readback/bind overlapped across chunks).",
        ))
        self.warmup_compiles = r.register(Counter(
            "scheduler_warmup_compiles_total",
            "Bucketed solve shapes compiled ahead of time by the warmup "
            "pass (cli --warmup / Scheduler.warmup).",
        ))
        # -- incremental solve (restricted candidate-column cycles) -----
        self.incremental_cycles = r.register(Counter(
            "scheduler_incremental_cycles_total",
            "Scheduling cycles by solve scope under the incremental "
            "mode: restricted = solved against the cached score plane's "
            "candidate columns (O(churn)); full = the cold dense solve "
            "(fallback or ineligible); declined = a restricted attempt "
            "that errored/failed validation; under-placed = a restricted "
            "attempt that could not place every pod (both re-solve cold "
            "in the same cycle and ALSO count under full).",
            ["scope"],
        ))
        self.incremental_reuse_fraction = r.register(Gauge(
            "scheduler_incremental_reuse_fraction",
            "Fraction of the score plane's node columns REUSED from the "
            "device-resident cache by the last cycle (1 - recomputed/"
            "live; 0 on full solves) — cost proportional to churn, "
            "measured.",
        ))
        self.incremental_invalidations = r.register(Counter(
            "scheduler_incremental_invalidations_total",
            "Score-cache + warm-potential drops by invalidation edge: "
            "full-snapshot (node-set/interner/pack-epoch growth), "
            "dirty-frac blowout, takeover reconciliation, device-loss "
            "recovery, restricted-error.",
            ["reason"],
        ))
        # -- sharded execution backend (kubernetes_tpu/parallel) --------
        self.mesh_devices = r.register(Gauge(
            "scheduler_mesh_devices",
            "Devices in the node-axis mesh of the sharded execution "
            "backend (parallel.mesh config; 0 = single-device mode).",
        ))
        # -- perf ledger + SLO watchdog (obs/ledger.py) -----------------
        self.cycle_model_efficiency = r.register(Gauge(
            "scheduler_cycle_model_efficiency",
            "Last cycle's modeled/measured solve-cost ratio (1 = the "
            "cost model's prediction matched the measured solve; <1 = "
            "the cycle ran slower than the model claims — the runtime "
            "confrontation of parallel/costmodel.py with reality; -1 = "
            "the last cycle ran no solve, so no verdict).",
        ))
        self.cycle_modeled_cost = r.register(Gauge(
            "scheduler_cycle_modeled_cost_seconds",
            "The cost model's predicted solve seconds for the last "
            "cycle's batch shape (XLA cost_analysis flops when "
            "captured at warmup, analytic P*N plane otherwise, with "
            "the collective model folded in under a mesh; -1 = the "
            "last cycle ran no solve).",
        ))
        self.cycle_phase_seconds = r.register(Gauge(
            "scheduler_cycle_phase_seconds",
            "Last cycle's measured wall seconds per canonical phase "
            "(snapshot, pack, dispatch, solve, validate, readback, "
            "bind, ...) — per-phase attribution of where the cycle "
            "went; phases the last cycle did not run read 0.",
            ["phase"],
        ))
        self.slo_burn_rate = r.register(Gauge(
            "scheduler_slo_burn_rate",
            "Multi-window SLO burn rate per objective (violating "
            "fraction / error budget; >= the configured threshold in "
            "BOTH windows trips SchedulerSLOBurn and engages APF "
            "backpressure).",
            ["objective", "window"],
        ))
        # -- device-memory ledger (obs/memledger.py) --------------------
        self.device_memory_bytes = r.register(Gauge(
            "scheduler_device_memory_bytes",
            "Device memory by kind: resident = measured bytes in use "
            "per device (memory_stats; the bounded live-array census "
            "on backends without it, device=\"census\"), peak = the "
            "allocator's high watermark, limit = the device capacity "
            "(0 = unknown), modeled = the ledger's summed resident "
            "registrations (device=\"all\"). Devices that stop "
            "reporting read 0 (freshness rule).",
            ["kind", "device"],
        ))
        self.memory_model_efficiency = r.register(Gauge(
            "scheduler_memory_model_efficiency",
            "Modeled resident bytes / measured bytes in use at the "
            "last sampled cycle boundary (1 = the byte model explains "
            "everything the allocator holds; low = untracked device "
            "memory — a leak or an unregistered resident; -1 = the "
            "last boundary took no sample, same sentinel rule as "
            "scheduler_cycle_model_efficiency).",
        ))
        self.memory_preflight = r.register(Counter(
            "scheduler_memory_preflight_total",
            "Capacity-preflight verdicts per cycle shape against the "
            "warmed per-bucket memory_analysis table: ok = fits (or "
            "not judgeable), split = trimmed to a smaller warmed "
            "bucket, shed = requeued whole rather than OOMing.",
            ["action"],
        ))
        # -- pod journeys & incident autopsies (obs/journey.py,
        # obs/incidents.py): where each bound pod's e2e seconds went,
        # and the correlated-bundle trigger counts ----------------------
        self.pod_journey_phase_seconds = r.register(Histogram(
            "scheduler_pod_journey_phase_seconds",
            "Per-phase share of each bound pod's create-to-bind "
            "latency (queue-wait | backoff | solve | bind-rpc | "
            "ambiguous | permit — disjoint; a pod's phases sum to its "
            "e2e latency). Every bound pod observes EVERY phase, zeros "
            "included, so per-phase sample counts stay comparable.",
            ["phase"],
            buckets=exponential_buckets(0.001, 2, 15),
        ))
        self.pod_journeys_total = r.register(Counter(
            "scheduler_pod_journeys_total",
            "Completed pod journeys by outcome: bound = confirmed "
            "bind, gone = left unbound (deleted, terminating, pruned "
            "by reconcile, taken by another writer).",
            ["outcome"],
        ))
        self.incidents_total = r.register(Counter(
            "scheduler_incidents_total",
            "Incident bundles captured by trigger (slo-burn | "
            "invariant-violation | oom | retrace-storm | "
            "ladder-fallback); cooldown-suppressed repeats don't "
            "count. Each bundle correlates the flight window, ledger "
            "+ memory + queue snapshots, and the slowest in-flight "
            "journeys at /debug/incidents.",
            ["trigger"],
        ))
        # -- scenario packs (kubernetes_tpu/scenarios) ------------------
        self.scenario_quality = r.register(Gauge(
            "scheduler_scenario_quality",
            "Last cycle's placement-quality scores under the active "
            "scenario pack (nodes_used, headroom, fragmentation, "
            "gang_success_rate, ... — docs/scenarios.md quality table).",
            ["score"],
        ))
        self.scenario_cascade_victims = r.register(Counter(
            "scheduler_scenario_cascade_victims_total",
            "Victims evicted by the in-batch preemption cascade "
            "(scenario packs; the per-pod path counts under "
            "scheduler_preemption_victims_total).",
        ))
        self.scenario_displaced_replaced = r.register(Counter(
            "scheduler_scenario_displaced_replaced_total",
            "Cascade victims that re-placed onto another node in the "
            "SAME cycle's dense re-solve (migrated rather than lost).",
        ))
        self.scenario_repacks = r.register(Counter(
            "scheduler_scenario_repacks_total",
            "Steady-state consolidation re-pack sweeps that drained at "
            "least one pod (scenario.repackInterval cadence).",
        ))
        self.scenario_repack_drained = r.register(Counter(
            "scheduler_scenario_repack_drained_total",
            "Pods drained off under-utilized nodes by the steady-state "
            "re-pack cadence and requeued for consolidation.",
        ))
        # -- schedulability explainer (obs/explain.py): the batched
        # why-pending reduction over the (pod x node) failure bitmask ---
        self.unschedulable_pods = r.register(Counter(
            "scheduler_unschedulable_pods_total",
            "Unschedulable pod observations per cycle, by the predicate "
            "that blocked them on at least one node (one pod can count "
            "under several reasons).",
            ["reason"],
        ))
        self.unschedulable_node_counts = r.register(Gauge(
            "scheduler_unschedulable_node_counts",
            "Last cycle's total (pod, node) predicate-failure pairs per "
            "reason — how many node exclusions each constraint class "
            "caused across the residual queue.",
            ["reason"],
        ))
        # -- streaming serving mode (kubernetes_tpu/serving): doorbell,
        # micro-batch window, APF-style load shedding, watch fan-out ----
        self.doorbell_rings = r.register(Counter(
            "scheduler_doorbell_rings_total",
            "Doorbell rings by source (queue events, informer sweeps, "
            "REST mutations) — what wakes the event-driven serving loop "
            "instead of a fixed-interval timer.",
            ["reason"],
        ))
        self.microbatch_flushes = r.register(Counter(
            "scheduler_microbatch_flushes_total",
            "Micro-batch window flushes by trigger (bucket-fill = the "
            "accumulated depth hit a warmed power-of-two bucket; "
            "max-wait = the latency ceiling expired).",
            ["trigger"],
        ))
        self.microbatch_window = r.register(Histogram(
            "scheduler_microbatch_window_seconds",
            "How long the serving loop's accumulation window held "
            "before flushing into a cycle.",
            buckets=exponential_buckets(0.001, 2, 12),
        ))
        self.apf_rejected = r.register(Counter(
            "scheduler_flowcontrol_rejected_requests_total",
            "Requests shed by the APF-style flow controller (answered "
            "429 + Retry-After), by flow and shed reason (queue-full, "
            "timeout, saturated).",
            ["flow", "reason"],
        ))
        self.apf_inflight = r.register(Gauge(
            "scheduler_flowcontrol_current_inflight_requests",
            "Requests currently holding a seat per flow schema.",
            ["flow"],
        ))
        self.watch_evictions = r.register(Counter(
            "scheduler_watch_evictions_total",
            "Watchers disconnected (410 Gone -> relist) because their "
            "bounded send buffer overflowed — slow consumers are cut "
            "loose instead of stalling the fan-out hub.",
        ))
        # -- queue observability (scheduler_queue.go metrics parity) ----
        self.queue_pod_age = r.register(Histogram(
            "scheduler_queue_pod_age_seconds",
            "Time pods spent in a scheduling sub-queue before leaving it "
            "(observed at queue exit), by sub-queue.",
            ["queue"],
            # residency runs minutes-to-hours (the unschedulable flush
            # alone is 60s), so the default 1ms..16s latency layout
            # would collapse every sample into +Inf — span 10ms..~87min
            buckets=exponential_buckets(0.01, 2, 20),
        ))
        self.pod_scheduling_attempts = r.register(Histogram(
            "scheduler_pod_scheduling_attempts",
            "Number of attempts it took to successfully schedule a pod.",
            buckets=[1, 2, 4, 8, 16],
        ))
        self.queue_incoming_pods = r.register(Counter(
            "scheduler_queue_incoming_pods_total",
            "Pods added to scheduling queues, by the event that moved "
            "them (PodAdd, PodUpdate, ScheduleAttemptFailure, "
            "BackoffComplete, UnschedulableTimeout, MoveAllToActive, "
            "MovePodsToActive).",
            ["event"],
        ))
