"""Lightweight API object model — the slice of the Kubernetes v1 API the
scheduler consumes.

Mirrors (in spirit, not in code) the generated Go types under
``staging/src/k8s.io/api/core/v1`` that the reference scheduler reads:
Pod spec fields consumed by predicates/priorities
(``pkg/scheduler/algorithm/predicates/predicates.go``) and Node status/spec
fields aggregated into ``NodeInfo`` (``pkg/scheduler/nodeinfo/node_info.go``).

These are plain Python dataclasses used at the host boundary only; the hot
path operates on the columnar tensors built from them (see
``kubernetes_tpu.snapshot``).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Resources
# ---------------------------------------------------------------------------

#: Default requests used for scoring when a container declares none —
#: reference: priorities/util/non_zero.go:31-33.
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024

#: MaxPriority for 0-10 score scaling — reference: pkg/scheduler/api/types.go:35.
MAX_PRIORITY = 10


@dataclass
class Resources:
    """Aggregate resource quantities (the reference's ``nodeinfo.Resource``,
    node_info.go:146): milli-CPU, memory bytes, ephemeral-storage bytes,
    allowed pod count, plus named scalar/extended resources."""

    cpu_milli: float = 0
    memory: float = 0
    ephemeral_storage: float = 0
    pods: float = 0
    scalars: Dict[str, float] = field(default_factory=dict)

    def add(self, other: "Resources") -> "Resources":
        out = Resources(
            self.cpu_milli + other.cpu_milli,
            self.memory + other.memory,
            self.ephemeral_storage + other.ephemeral_storage,
            self.pods + other.pods,
            dict(self.scalars),
        )
        for k, v in other.scalars.items():
            out.scalars[k] = out.scalars.get(k, 0) + v
        return out


# ---------------------------------------------------------------------------
# Selectors / affinity
# ---------------------------------------------------------------------------

#: Node-selector operators — apimachinery selection ops used by
#: NodeSelectorRequirement (staging/src/k8s.io/api/core/v1/types.go).
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"
OP_LT = "Lt"


@dataclass
class Requirement:
    """One match expression: ``key <op> values``."""

    key: str
    operator: str
    values: Tuple[str, ...] = ()


@dataclass
class NodeSelectorTerm:
    """AND of requirements. Terms are ORed together within a selector."""

    match_expressions: Tuple[Requirement, ...] = ()


@dataclass
class PreferredSchedulingTerm:
    weight: int = 1
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass
class LabelSelector:
    """Label selector over *pods* (used by pod (anti)affinity, topology
    spread, selector-spread owners, PDBs). ``match_labels`` is AND of
    equality pairs; ``match_expressions`` AND of set requirements."""

    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: Tuple[Requirement, ...] = ()

    def matches(self, labels: Dict[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for r in self.match_expressions:
            if r.operator == OP_IN:
                if labels.get(r.key) not in r.values:
                    return False
            elif r.operator == OP_NOT_IN:
                if r.key in labels and labels[r.key] in r.values:
                    return False
            elif r.operator == OP_EXISTS:
                if r.key not in labels:
                    return False
            elif r.operator == OP_DOES_NOT_EXIST:
                if r.key in labels:
                    return False
            else:
                raise ValueError(f"bad pod label selector op {r.operator}")
        return True


@dataclass
class PodAffinityTerm:
    """Reference: v1.PodAffinityTerm — pods matching ``label_selector`` in
    ``namespaces`` co-located by ``topology_key``."""

    label_selector: LabelSelector = field(default_factory=LabelSelector)
    topology_key: str = ""
    namespaces: Tuple[str, ...] = ()  # empty => pod's own namespace


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 1
    term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class Affinity:
    node_required: Tuple[NodeSelectorTerm, ...] = ()  # ORed terms
    node_preferred: Tuple[PreferredSchedulingTerm, ...] = ()
    pod_affinity_required: Tuple[PodAffinityTerm, ...] = ()
    pod_affinity_preferred: Tuple[WeightedPodAffinityTerm, ...] = ()
    pod_anti_affinity_required: Tuple[PodAffinityTerm, ...] = ()
    pod_anti_affinity_preferred: Tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass
class TopologySpreadConstraint:
    """Reference: v1.TopologySpreadConstraint (EvenPodsSpread feature,
    predicates.go:1720 / priorities/even_pods_spread.go:86)."""

    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = "DoNotSchedule"  # or "ScheduleAnyway"
    label_selector: LabelSelector = field(default_factory=LabelSelector)


# ---------------------------------------------------------------------------
# Taints / tolerations
# ---------------------------------------------------------------------------

EFFECT_NO_SCHEDULE = "NoSchedule"
EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
EFFECT_NO_EXECUTE = "NoExecute"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = EFFECT_NO_SCHEDULE


@dataclass(frozen=True)
class Toleration:
    """Reference: v1.Toleration. ``operator`` is Exists or Equal; empty key
    with Exists tolerates everything; empty effect matches all effects.
    ``toleration_seconds`` (NoExecute only): None = tolerate forever;
    N = the NoExecute taint manager evicts after N seconds
    (pkg/controller/nodelifecycle/scheduler/taint_manager.go)."""

    key: str = ""
    operator: str = "Equal"
    value: str = ""
    effect: str = ""
    toleration_seconds: Optional[float] = None

    def tolerates(self, taint: Taint) -> bool:
        # Reference: pkg/apis/core/v1/helper/helpers.go ToleratesTaint.
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


# ---------------------------------------------------------------------------
# Volumes
# ---------------------------------------------------------------------------

#: Volume source kinds the volume predicates recognize (the slice of
#: v1.VolumeSource / v1.PersistentVolumeSource the reference's volume
#: predicates consume — predicates.go:216 isVolumeConflict,
#: :555-620 VolumeFilters, csi_volume_predicate.go).
VOL_GCE_PD = "gce-pd"
VOL_AWS_EBS = "aws-ebs"
VOL_AZURE_DISK = "azure-disk"
VOL_CINDER = "cinder"
VOL_RBD = "rbd"
VOL_ISCSI = "iscsi"
VOL_CSI = "csi"

#: binding modes (storage.k8s.io/v1 VolumeBindingMode)
BINDING_IMMEDIATE = "Immediate"
BINDING_WAIT_FOR_FIRST_CONSUMER = "WaitForFirstConsumer"


@dataclass(frozen=True)
class PodVolume:
    """One spec.volumes entry reduced to what the volume predicates read.

    Either an inline cloud volume (``kind`` + ``handle``: pdName / volumeID /
    diskName / "pool/image" for RBD / IQN for ISCSI) or a PVC reference
    (``pvc`` set; kind/handle then resolve through PVC -> PV)."""

    kind: str = ""
    handle: str = ""
    read_only: bool = False
    pvc: str = ""  # persistentVolumeClaim.claimName


@dataclass
class PersistentVolume:
    """Slice of v1.PersistentVolume: source identity, zone labels
    (VolumeZoneChecker reads only the two failure-domain label keys,
    predicates.go:645), node affinity (volume binder), claim binding."""

    name: str
    kind: str = ""  # VOL_* source kind; VOL_CSI uses ``driver`` too
    handle: str = ""
    driver: str = ""  # CSI driver name when kind == VOL_CSI
    labels: Dict[str, str] = field(default_factory=dict)
    node_affinity: Tuple[NodeSelectorTerm, ...] = ()  # ORed terms
    storage_class: str = ""
    claim_ref: str = ""  # "namespace/name" of bound claim; "" = available
    #: metadata.deletionTimestamp analog (0 = live): the PV-protection
    #: finalizer keeps a claimed PV terminating-but-present until its
    #: claim releases it (pv_protection_controller.go)
    deletion_timestamp: float = 0.0


@dataclass
class PersistentVolumeClaim:
    name: str
    namespace: str = "default"
    volume_name: str = ""  # bound PV name; "" = unbound
    storage_class: str = ""
    #: metadata.deletionTimestamp analog (0 = live): the PVC-protection
    #: finalizer keeps an in-use claim terminating-but-present until no
    #: live pod references it (pvc_protection_controller.go)
    deletion_timestamp: float = 0.0


@dataclass
class StorageClass:
    name: str
    binding_mode: str = BINDING_IMMEDIATE
    #: provisioner name; non-empty and not the no-provisioner sentinel means
    #: dynamic provisioning can satisfy an unbound delayed-binding claim
    #: (volume scheduling lib: checkVolumeProvisions).
    provisioner: str = ""

    def provisionable(self) -> bool:
        return bool(self.provisioner) and self.provisioner != "kubernetes.io/no-provisioner"


# ---------------------------------------------------------------------------
# Pod / Node
# ---------------------------------------------------------------------------


#: v1.PodPhase values (core/v1/types.go PodPhase) — the hollow lifecycle
#: runs Pending -> Running -> Succeeded/Failed; deletion is the terminal
#: observable either way in this hub
POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"
#: node-unreachable: the pod may well still be running and holding its
#: node's resources — NOT terminal (gc_controller.go:100)
POD_UNKNOWN = "Unknown"


def is_pod_terminated(pod) -> bool:
    """isPodTerminated (pkg/controller/podgc/gc_controller.go:100): any
    phase other than Pending/Running/Unknown is terminal. Terminal pods
    hold no node resources (the kubelet has released them) and are
    invisible to the scheduler — the reference scheduler's informer uses
    a ``status.phase!=Succeeded,status.phase!=Failed`` field selector
    (factory.go NewPodInformer), so a terminal phase hop reaches it as a
    DELETE event."""
    return pod.phase not in (POD_PENDING, POD_RUNNING, POD_UNKNOWN)


@dataclass(frozen=True)
class OwnerReference:
    """The metav1.OwnerReference slice the GC dependency graph consumes
    (garbagecollector.go:65 builds its graph from these): controller
    kind + name. ``uid`` exists for wire-shape parity only — the hub's
    GC matches by (kind, name), so a recreated same-name owner keeps the
    previous incarnation's pods alive. That is a DOCUMENTED deviation
    approximating the reference's adoption semantics (a recreated
    controller with the same selector adopts matching orphans and
    reaches the same end state for controller pods)."""

    kind: str
    name: str
    uid: str = ""


@dataclass
class ReadinessProbe:
    """The slice of v1.Probe the hollow prober consumes
    (prober/worker.go): result gates the pod's Ready condition, which in
    turn gates Endpoints membership. The probe TARGET is hollow — app
    health is injected per pod via ``hub.set_app_health`` (the fake
    runtime's answer), so tests drive readiness flips deterministically."""

    initial_delay_s: float = 0.0


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    node_name: str = ""  # spec.nodeName: set once bound (or pre-pinned)
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Affinity = field(default_factory=Affinity)
    tolerations: Tuple[Toleration, ...] = ()
    priority: int = 0
    #: spec.priorityClassName — resolved to ``priority`` (and
    #: ``preemption_policy``) by the Priority admission plugin
    #: (plugin/pkg/admission/priority/admission.go); the scheduler itself
    #: only ever reads the resolved integer.
    priority_class_name: str = ""
    #: spec.schedulerName — which scheduler is responsible for this pod
    #: (eventhandlers.go:328 responsibleForPod; the multi-scheduler seam,
    #: test/integration/scheduler TestMultipleSchedulers)
    scheduler_name: str = "default-scheduler"
    requests: Resources = field(default_factory=Resources)
    host_ports: Tuple[Tuple[str, str, int], ...] = ()  # (protocol, hostIP, port)
    topology_spread: Tuple[TopologySpreadConstraint, ...] = ()
    images: Tuple[str, ...] = ()  # container image names (ImageLocality)
    #: selectors of owning Services/RCs/RSs/StatefulSets, provided by the
    #: driver's listers — feeds SelectorSpreadPriority
    #: (selector_spreading.go:99).
    spread_selectors: Tuple[LabelSelector, ...] = ()
    #: gang/coscheduling group (PodGroup); empty = no gang.
    pod_group: str = ""
    #: PodGroup minMember: the group schedules only when at least this many
    #: members are present AND all present members place together. 0 =
    #: all-present-members atomicity only (single-batch gangs). Declaring
    #: the true group size makes atomicity hold across batches: a straggler
    #: group fragment (late arrival, backoff desync, max_batch split) rolls
    #: back instead of binding partially.
    pod_group_min_available: int = 0
    #: UID of the controller ownerReference (RC/RS), feeds
    #: NodePreferAvoidPodsPriority (node_prefer_avoid_pods.go).
    owner_uid: str = ""
    #: monotonically increasing arrival stamp used for queue ordering
    #: (the reference orders activeQ by priority then timestamp).
    queued_at: float = 0.0
    #: status.nominatedNodeName — set by preemption so the victim's node
    #: holds capacity for this pod while it retries (scheduler.go:316).
    nominated_node_name: str = ""
    #: status.startTime (seconds) — preemption tie-break tier 5
    #: (generic_scheduler.go:862 pickOneNodeForPreemption: latest start time
    #: of the highest-priority victim wins).
    start_time: float = 0.0
    #: spec.preemptionPolicy: "PreemptLowerPriority" (default) or "Never".
    #: Honored when the NonPreemptingPriority feature gate is on
    #: (podEligibleToPreemptOthers, generic_scheduler.go:1191).
    preemption_policy: str = "PreemptLowerPriority"
    #: metadata.deletionTimestamp analog (0 = live). A terminating
    #: lower-priority pod on the nominated node blocks re-preemption
    #: (generic_scheduler.go:1190 podEligibleToPreemptOthers).
    deletion_timestamp: float = 0.0
    #: spec.volumes reduced to what the volume predicates consume.
    volumes: Tuple[PodVolume, ...] = ()
    #: container resource LIMITS (cpu/mem only) — consumed solely by
    #: ResourceLimitsPriority (priorities/resource_limits.go getResourceLimits:
    #: sum of containers, max'd with init containers).
    limits: Resources = field(default_factory=Resources)
    #: status.phase — maintained by the hollow kubelet lifecycle pass
    #: (kuberuntime_manager.go:558 SyncPod compressed to phase hops)
    phase: str = POD_PENDING
    #: status Ready condition — meaningful only when ``readiness_probe``
    #: is set (probe-less pods are ready the moment they run, the
    #: no-probes default of the reference's status_manager)
    ready: bool = False
    readiness_probe: Optional[ReadinessProbe] = None
    #: metadata.ownerReferences — the GC graph edges; a pod whose every
    #: referenced controller is gone gets background-deleted
    #: (sim.HollowCluster.gc_owner_graph)
    owner_refs: Tuple["OwnerReference", ...] = ()
    #: run-to-completion analog (a container that exits 0 after this many
    #: seconds of Running): the hollow kubelet hops the phase to
    #: Succeeded and LEAVES the object in the store — the real kubelet
    #: never deletes API pods; cleanup of terminal pods is the pod GC
    #: controller's job (podgc/gc_controller.go:94 terminatedPodThreshold).
    #: None = a service-style pod that runs until deleted.
    run_duration_s: Optional[float] = None

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def effective_requests(self) -> Resources:
        r = dataclasses.replace(self.requests, scalars=dict(self.requests.scalars))
        r.pods = 1
        return r

    def nonzero_requests(self) -> Tuple[float, float]:
        """(cpu_milli, memory) with scoring defaults — non_zero.go:42,:48."""
        cpu = self.requests.cpu_milli or DEFAULT_MILLI_CPU_REQUEST
        mem = self.requests.memory or DEFAULT_MEMORY_REQUEST
        return cpu, mem

    def tolerates(self, taint: Taint) -> bool:
        return any(t.tolerates(taint) for t in self.tolerations)


@dataclass
class PodDisruptionBudget:
    """The slice of policy/v1beta1 PodDisruptionBudget preemption consumes:
    selector + status.disruptionsAllowed (checked by
    ``filterPodsWithPDBViolation``, generic_scheduler.go:1129)."""

    name: str = ""
    namespace: str = "default"
    selector: LabelSelector = field(default_factory=LabelSelector)
    disruptions_allowed: int = 0
    #: spec.minAvailable (int form): when set, a disruption controller
    #: (pkg/controller/disruption) maintains ``disruptions_allowed`` =
    #: max(0, currentHealthy - minAvailable); when None the status field
    #: is whatever the feed set (static-lister mode).
    min_available: Optional[int] = None

    def matches(self, pod: Pod) -> bool:
        return pod.namespace == self.namespace and self.selector.matches(pod.labels)


@dataclass
class NodeCondition:
    ready: bool = True
    memory_pressure: bool = False
    disk_pressure: bool = False
    pid_pressure: bool = False
    network_unavailable: bool = False


@dataclass
class Node:
    name: str
    labels: Dict[str, str] = field(default_factory=dict)
    allocatable: Resources = field(default_factory=lambda: Resources(pods=110))
    taints: Tuple[Taint, ...] = ()
    unschedulable: bool = False
    conditions: NodeCondition = field(default_factory=NodeCondition)
    images: Dict[str, int] = field(default_factory=dict)  # name -> size bytes
    #: owner UIDs from the scheduler.alpha.kubernetes.io/preferAvoidPods
    #: annotation (NodePreferAvoidPodsPriority).
    prefer_avoid_owner_uids: Tuple[str, ...] = ()
    #: metadata.annotations slice the hollow controllers write (the TTL
    #: controller's node.alpha.kubernetes.io/ttl lives here)
    annotations: Dict[str, str] = field(default_factory=dict)
    #: spec.podCIDR — allocated by the nodeipam range allocator
    #: (pkg/controller/nodeipam/ipam/range_allocator.go)
    pod_cidr: str = ""

    def zone(self) -> Optional[str]:
        # Reference zone labels: failure-domain.beta.kubernetes.io/zone.
        return self.labels.get("failure-domain.beta.kubernetes.io/zone") or self.labels.get(
            "topology.kubernetes.io/zone"
        )

    def region(self) -> Optional[str]:
        return self.labels.get("failure-domain.beta.kubernetes.io/region") or self.labels.get(
            "topology.kubernetes.io/region"
        )

    def zone_key(self) -> Optional[Tuple[str, str]]:
        """utilnode.GetZoneKey analog: (region, zone), None when unlabeled."""
        z, r = self.zone(), self.region()
        if z is None and r is None:
            return None
        return (r or "", z or "")
