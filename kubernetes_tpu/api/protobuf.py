"""Typed protobuf codecs for Pod/Node — the protobuf serializer analog
(runtime/serializer/protobuf/protobuf.go:95).

The JSON converters (extender.pod_to_json / node_to_json and their
inverses) define the published wire SLICE; these codecs carry exactly
that slice in typed proto fields (proto/corev1.proto), so for any object
``from_pb(to_pb(x))`` equals ``from_json(to_json(x))`` — pinned by
tests/test_protobuf_codec.py. Responses ride the reference's envelope:
the 4-byte magic ``k8s\\x00`` followed by a runtime.Unknown message
(protobuf.go:42 serializes exactly this shape).

Why it exists (VERDICT r4 missing #5): JSON-serializing a 50k-node
snapshot is the reference's known control-plane wire cost; the typed
codec cuts both bytes and encode time (measured:
benchres/proto_codec_cpu.json) for the REST facade's
``Accept: application/vnd.kubernetes.protobuf`` lists and the gRPC
SyncState delta feed.
"""

from __future__ import annotations

from kubernetes_tpu.api.types import (
    Node,
    NodeCondition,
    OwnerReference,
    Pod,
    ReadinessProbe,
    Resources,
    Taint,
)
from kubernetes_tpu.proto import corev1_pb2 as pb

#: protobuf.go:42 — the recognizer prefix of the k8s proto wire format
MAGIC = b"k8s\x00"
PROTO_CONTENT_TYPE = "application/vnd.kubernetes.protobuf"


def pod_to_pb(pod: Pod) -> pb.PodMsg:
    m = pb.PodMsg(
        name=pod.name,
        namespace=pod.namespace,
        uid=pod.uid or pod.key(),
        node_name=pod.node_name,
        priority=int(pod.priority),
        scheduler_name=pod.scheduler_name,
        preemption_policy=pod.preemption_policy,
        cpu_milli=float(pod.requests.cpu_milli),
        memory=float(pod.requests.memory),
        has_probe=pod.readiness_probe is not None,
        probe_initial_delay_s=(
            float(pod.readiness_probe.initial_delay_s)
            if pod.readiness_probe is not None else 0.0),
        # the Ready condition exists only for probed pods in the JSON
        # slice (pod_to_json emits it conditionally) — mirror that here
        # or from_pb(to_pb(x)) and from_json(to_json(x)) diverge on a
        # probe-less ready=True pod
        ready=bool(pod.ready) if pod.readiness_probe is not None else False,
        nominated_node_name=pod.nominated_node_name,
        phase=pod.phase,
        deletion_timestamp=float(pod.deletion_timestamp),
    )
    m.labels.update(pod.labels)
    m.node_selector.update(pod.node_selector)
    m.scalars.update({k: float(v) for k, v in pod.requests.scalars.items()})
    for r in pod.owner_refs:
        m.owner_refs.add(kind=r.kind, name=r.name, uid=r.uid)
    return m


def pod_from_pb(m: pb.PodMsg) -> Pod:
    req = Resources(cpu_milli=m.cpu_milli, memory=m.memory)
    req.scalars.update(dict(m.scalars))
    return Pod(
        name=m.name,
        namespace=m.namespace or "default",
        uid=m.uid,
        labels=dict(m.labels),
        owner_refs=tuple(
            OwnerReference(kind=r.kind, name=r.name, uid=r.uid)
            for r in m.owner_refs),
        node_name=m.node_name,
        node_selector=dict(m.node_selector),
        priority=int(m.priority),
        scheduler_name=m.scheduler_name or "default-scheduler",
        preemption_policy=m.preemption_policy or "PreemptLowerPriority",
        requests=req,
        readiness_probe=(ReadinessProbe(
            initial_delay_s=m.probe_initial_delay_s)
            if m.has_probe else None),
        ready=m.ready,
        nominated_node_name=m.nominated_node_name,
        phase=m.phase or "Pending",
        deletion_timestamp=m.deletion_timestamp,
    )


def node_to_pb(node: Node) -> pb.NodeMsg:
    c = node.conditions
    m = pb.NodeMsg(
        name=node.name,
        cpu_milli=float(node.allocatable.cpu_milli),
        memory=float(node.allocatable.memory),
        pods=float(node.allocatable.pods),
        ephemeral_storage=float(node.allocatable.ephemeral_storage),
        unschedulable=node.unschedulable,
        pod_cidr=node.pod_cidr,
        ready=c.ready,
        memory_pressure=c.memory_pressure,
        disk_pressure=c.disk_pressure,
        pid_pressure=c.pid_pressure,
        network_unavailable=c.network_unavailable,
    )
    m.labels.update(node.labels)
    m.annotations.update(node.annotations)
    m.prefer_avoid_owner_uids.extend(node.prefer_avoid_owner_uids)
    m.scalars.update(
        {k: float(v) for k, v in node.allocatable.scalars.items()})
    for t in node.taints:
        m.taints.add(key=t.key, value=t.value, effect=t.effect)
    m.images.update({k: int(v) for k, v in node.images.items()})
    return m


def node_from_pb(m: pb.NodeMsg) -> Node:
    alloc = Resources(cpu_milli=m.cpu_milli, memory=m.memory, pods=m.pods,
                      ephemeral_storage=m.ephemeral_storage)
    alloc.scalars.update(dict(m.scalars))
    return Node(
        name=m.name,
        labels=dict(m.labels),
        annotations=dict(m.annotations),
        allocatable=alloc,
        taints=tuple(Taint(key=t.key, value=t.value, effect=t.effect)
                     for t in m.taints),
        unschedulable=m.unschedulable,
        pod_cidr=m.pod_cidr,
        conditions=NodeCondition(
            ready=m.ready, memory_pressure=m.memory_pressure,
            disk_pressure=m.disk_pressure, pid_pressure=m.pid_pressure,
            network_unavailable=m.network_unavailable),
        images=dict(m.images),
        prefer_avoid_owner_uids=tuple(m.prefer_avoid_owner_uids),
    )


def pod_list_to_pb(pods, resource_version: int) -> pb.PodListMsg:
    lst = pb.PodListMsg(resource_version=int(resource_version))
    for p in pods:
        lst.items.append(pod_to_pb(p))
    return lst


def node_list_to_pb(nodes, resource_version: int) -> pb.NodeListMsg:
    lst = pb.NodeListMsg(resource_version=int(resource_version))
    for n in nodes:
        lst.items.append(node_to_pb(n))
    return lst


def encode_envelope(kind: str, message) -> bytes:
    """runtime.Unknown behind the magic prefix — what the reference's
    proto serializer writes on the wire (protobuf.go:42,:95)."""
    unk = pb.Unknown(type_meta=pb.TypeMeta(api_version="v1", kind=kind),
                     raw=message.SerializeToString())
    return MAGIC + unk.SerializeToString()


def decode_envelope(data: bytes):
    """-> (kind, raw bytes); raises ValueError on a bad magic/envelope."""
    if not data.startswith(MAGIC):
        raise ValueError("not k8s protobuf wire data (bad magic)")
    unk = pb.Unknown()
    unk.ParseFromString(data[len(MAGIC):])
    return unk.type_meta.kind, unk.raw
