"""Versioned API machinery — the ``runtime.Scheme`` analog.

The reference's entire API-stability story runs through one registry
(staging/src/k8s.io/apimachinery/pkg/runtime/scheme.go:46): types are
registered under a (group, version, kind), versioned objects get
DEFAULTING functions, and CONVERSION functions map between each
versioned type and a single internal ("hub") type.  Decoding is then
always the same pipeline (serializer/codec_factory.go + conversion in
scheme.go:340 Convert):

    bytes -> recognize apiVersion/kind -> build the VERSIONED object
    (strict: unknown fields are errors, serializer/json strict mode)
    -> apply that version's defaults -> convert to INTERNAL

and encoding is the reverse (internal -> convert to the requested
version).  This module is that pipeline over plain dataclasses: versioned
types are dataclasses whose FIELD NAMES are the wire spelling (camelCase,
as in the reference's external types), the internal types are whatever
the framework uses natively (snake_case dataclasses).

Used by apis/config (the scheduler ComponentConfig scheme,
pkg/scheduler/apis/config/scheme/scheme.go:31): see
:mod:`kubernetes_tpu.api.config_v1alpha1`.
"""

from __future__ import annotations

import dataclasses
import functools
import typing
from typing import Callable, Dict, List, Tuple, Type


class SchemeError(ValueError):
    """Decode/conversion failure; ``errors`` lists field-path messages."""

    def __init__(self, errors: List[str]):
        super().__init__("; ".join(errors))
        self.errors = list(errors)


class Scheme:
    """Type registry + defaulting + conversion (scheme.go:46).

    - :meth:`register` a versioned dataclass under its (apiVersion, kind);
    - :meth:`add_defaulting` that version's SetDefaults_* function
      (mutates or returns the versioned object — defaulting runs BEFORE
      conversion, scheme.go:764 Default);
    - :meth:`add_conversion` a (src_type, dst_type) function pair —
      registered both ways for a round-trippable version;
    - :meth:`decode` a JSON/YAML mapping all the way to the internal type;
    - :meth:`convert` between any two registered types;
    - :meth:`encode` an internal object back to a versioned mapping.
    """

    def __init__(self) -> None:
        self._kinds: Dict[Tuple[str, str], Type] = {}
        self._defaulters: Dict[Type, Callable] = {}
        self._conversions: Dict[Tuple[Type, Type], Callable] = {}

    # -- registration -------------------------------------------------------

    def register(self, api_version: str, kind: str, typ: Type) -> None:
        if not dataclasses.is_dataclass(typ):
            raise TypeError(f"{typ!r} must be a dataclass")
        self._kinds[(api_version, kind)] = typ

    def add_defaulting(self, typ: Type, fn: Callable) -> None:
        self._defaulters[typ] = fn

    def add_conversion(self, src: Type, dst: Type, fn: Callable) -> None:
        self._conversions[(src, dst)] = fn

    def recognizes(self, api_version: str, kind: str) -> bool:
        return (api_version, kind) in self._kinds

    # -- pipeline -----------------------------------------------------------

    def default(self, obj):
        """Apply the registered defaulting function, if any (Default,
        scheme.go:764). Returns the defaulted object."""
        fn = self._defaulters.get(type(obj))
        if fn is None:
            return obj
        return fn(obj) or obj

    def convert(self, obj, to_type: Type):
        """Convert between registered types (Convert, scheme.go:340).
        Identity conversion is free; unknown pairs are errors, never a
        silent field-copy (the reference's reflection fallback is a
        DELIBERATE non-goal — silent structural conversion is how fields
        get dropped)."""
        if type(obj) is to_type:
            return obj
        fn = self._conversions.get((type(obj), to_type))
        if fn is None:
            raise SchemeError([
                f"no conversion registered: {type(obj).__name__} -> "
                f"{to_type.__name__}"
            ])
        return fn(obj)

    def build(self, api_version: str, kind: str, doc: dict, path: str = ""):
        """Mapping -> versioned object, strict (unknown fields are
        field-path errors, the strict-serializer posture the reference
        uses for ComponentConfig)."""
        typ = self._kinds.get((api_version, kind))
        if typ is None:
            raise SchemeError([
                f'no kind "{kind}" is registered for version "{api_version}"'
            ])
        return _build_dataclass(typ, doc, path or kind)

    def decode(self, doc: dict, internal_type: Type):
        """The full decode pipeline: recognize -> build versioned (strict)
        -> default -> convert to ``internal_type``."""
        api_version, kind, body = _split_doc(doc)
        versioned = self.build(api_version, kind, body)
        versioned = self.default(versioned)
        return self.convert(versioned, internal_type)

    def encode(self, obj, api_version: str, kind: str) -> dict:
        """internal -> versioned mapping with apiVersion/kind stamped
        (the codec's encode direction)."""
        typ = self._kinds.get((api_version, kind))
        if typ is None:
            raise SchemeError([
                f'no kind "{kind}" is registered for version "{api_version}"'
            ])
        versioned = self.convert(obj, typ)
        out = {"apiVersion": api_version, "kind": kind}
        out.update(_dataclass_to_doc(versioned))
        return out


@functools.lru_cache(maxsize=None)
def _type_hints(typ: Type) -> dict:
    """Resolved annotations per type, cached: get_type_hints re-eval()s
    every string annotation on each call, and bulk decode paths visit
    the same handful of types thousands of times. It handles
    Optional[...], cross-module references, and forward refs — the bare
    getattr-on-module lookup it replaced silently resolved those to
    None and skipped strict recursive construction, stuffing the raw
    mapping into the field (ADVICE r4)."""
    try:
        return typing.get_type_hints(typ)
    except Exception:
        return {}


def _build_dataclass(typ: Type, doc: dict, path: str):
    """Strict recursive dataclass construction: every key must name a
    field; mapping-valued fields whose type is itself a dataclass recurse
    with an extended field path (the shape of field-path errors in
    apimachinery validation)."""
    if not isinstance(doc, dict):
        raise SchemeError([f"{path}: expected a mapping"])
    fields = {f.name: f for f in dataclasses.fields(typ)}
    hints = _type_hints(typ)
    errs: List[str] = []
    kw: dict = {}
    for key, val in doc.items():
        f = fields.get(key)
        if f is None:
            errs.append(f"{path}.{key}: unknown field")
            continue
        ftyp = f.type if isinstance(f.type, type) else hints.get(key)
        origin = typing.get_origin(ftyp)
        # typing.Optional[X] has origin typing.Union; PEP 604 `X | None`
        # has origin types.UnionType — both must unwrap or a nested
        # dataclass silently skips strict construction
        import types as _types

        if origin is typing.Union or origin is _types.UnionType:
            non_none = [a for a in typing.get_args(ftyp)
                        if a is not type(None)]
            ftyp = non_none[0] if len(non_none) == 1 else None
        if not isinstance(ftyp, type):
            ftyp = None
        if ftyp is not None and dataclasses.is_dataclass(ftyp) and not (
                dataclasses.is_dataclass(type(val))):
            try:
                kw[key] = _build_dataclass(ftyp, val, f"{path}.{key}")
            except SchemeError as e:
                errs.extend(e.errors)
        else:
            kw[key] = val
    if errs:
        raise SchemeError(errs)
    try:
        return typ(**kw)
    except TypeError as e:
        raise SchemeError([f"{path}: {e}"])


def _dataclass_to_doc(obj) -> dict:
    """Versioned dataclass -> plain mapping, recursing into nested
    dataclasses, dropping None (the wire form omits unset pointers)."""
    out = {}
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        if v is None:
            continue
        if dataclasses.is_dataclass(type(v)):
            v = _dataclass_to_doc(v)
        out[f.name] = v
    return out


def _split_doc(doc: dict):
    """(apiVersion, kind, body) of a wire document — the one recognize+
    strip both codecs (typed Scheme.decode and decode_unstructured)
    validate through, so the dynamic and typed paths can never drift on
    what counts as a decodable document."""
    if not isinstance(doc, dict):
        raise SchemeError(["document: expected a mapping"])
    api_version = doc.get("apiVersion", "")
    kind = doc.get("kind", "")
    if not api_version or not kind:
        raise SchemeError(["apiVersion and kind are required"])
    body = {k: v for k, v in doc.items()
            if k not in ("apiVersion", "kind")}
    return api_version, kind, body


class Unstructured:
    """apimachinery's unstructured.Unstructured analog
    (apimachinery/pkg/apis/meta/v1/unstructured/unstructured.go:41): a
    dict-backed object for kinds no typed codec is registered for —
    what dynamic clients and the GC's partial-metadata reads decode
    into. The document IS the object; accessors read the well-known
    metadata paths without requiring them."""

    def __init__(self, doc: dict) -> None:
        if not isinstance(doc, dict):
            raise SchemeError(["unstructured: expected a mapping"])
        self.doc = dict(doc)

    @property
    def api_version(self) -> str:
        return self.doc.get("apiVersion", "")

    @property
    def kind(self) -> str:
        return self.doc.get("kind", "")

    @property
    def name(self) -> str:
        return (self.doc.get("metadata") or {}).get("name", "")

    @property
    def namespace(self) -> str:
        return (self.doc.get("metadata") or {}).get("namespace", "")

    @property
    def labels(self) -> dict:
        return dict((self.doc.get("metadata") or {}).get("labels") or {})

    def get(self, *path, default=None):
        """NestedFieldNoCopy (unstructured helpers): walk a field path,
        None-safe — ``u.get("spec", "replicas")``."""
        cur = self.doc
        for p in path:
            if not isinstance(cur, dict) or p not in cur:
                return default
            cur = cur[p]
        return cur

    def to_doc(self) -> dict:
        return dict(self.doc)

    def __eq__(self, other) -> bool:
        return isinstance(other, Unstructured) and self.doc == other.doc

    def __repr__(self) -> str:
        return f"Unstructured({self.api_version}/{self.kind} {self.name})"


def decode_unstructured(scheme: Scheme, doc: dict):
    """UnstructuredJSONScheme's decode split (the dynamic client's
    codec): a registered (apiVersion, kind) routes through the TYPED
    strict pipeline (built + defaulted at its versioned type — the
    caller converts onward when it wants an internal form); anything
    else becomes :class:`Unstructured`. apiVersion/kind are still
    required — the reference's unstructured decoder rejects kind-less
    documents too."""
    api_version, kind, body = _split_doc(doc)
    if not scheme.recognizes(api_version, kind):
        return Unstructured(doc)
    return scheme.default(scheme.build(api_version, kind, body))
