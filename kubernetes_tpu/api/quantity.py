"""resource.Quantity parsing/formatting — the apimachinery slice the
framework's seams need (SURVEY §2.2 "apimachinery: ...unstructured,
field/label selectors..."; reference
``staging/src/k8s.io/apimachinery/pkg/api/resource/quantity.go`` —
``ParseQuantity`` and the suffixer tables in ``suffix.go``).

Quantities appear wherever Kubernetes JSON crosses our wire seams:
``resources.requests.cpu: "250m"``, ``memory: "1Gi"``. Internally the
framework is float milli-CPU / float bytes (the columnar tensors), so
this module only converts at the boundary; it is NOT the reference's
infinite-precision decimal — inputs beyond float64 precision are out of
scope for a scheduler (the reference itself caps at 2^63-1).

``parse_cpu`` returns milli-CPU (the scheduler's unit,
``MilliValue`` in the reference); ``parse_memory`` returns bytes.
"""

from __future__ import annotations

import re
from typing import Union

#: binary suffixes (suffix.go binSuffixes): 1024-based
_BINARY = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40,
           "Pi": 2**50, "Ei": 2**60}
#: decimal SI suffixes (decSuffixes): 1000-based; "m" = milli, "" = 1
_DECIMAL = {"n": 1e-9, "u": 1e-6, "m": 1e-3, "": 1.0, "k": 1e3,
            "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)"
    r"(?:(?P<exp>[eE][+-]?\d+)|(?P<suffix>[KMGTPE]i|[numkMGTPE]?))$"
)


def parse_quantity(s: Union[str, int, float]) -> float:
    """ParseQuantity analog: "250m" → 0.25, "1Gi" → 1073741824,
    "1e3" → 1000.0, bare numbers pass through. Raises ValueError on
    malformed input (quantity.go ErrFormatWrong)."""
    if isinstance(s, (int, float)):
        return float(s)
    m = _QUANTITY_RE.match(s.strip())
    if m is None:
        raise ValueError(
            f"quantities must match the regular expression "
            f"'^([+-]?[0-9.]+)([eEinumkKMGTP]*[-+]?[0-9]*)$': {s!r}"
        )
    val = float(m.group("num"))
    if m.group("exp"):
        val = float(m.group("num") + m.group("exp"))
    else:
        suffix = m.group("suffix") or ""
        if suffix in _BINARY:
            val *= _BINARY[suffix]
        else:
            val *= _DECIMAL[suffix]
    return -val if m.group("sign") == "-" else val


def parse_cpu(s: Union[str, int, float]) -> float:
    """CPU quantity → milli-CPU (Quantity.MilliValue): "250m" → 250,
    "2" → 2000, 1.5 → 1500."""
    return parse_quantity(s) * 1000.0


def parse_memory(s: Union[str, int, float]) -> float:
    """Memory quantity → bytes: "1Gi" → 2**30, "500M" → 5e8."""
    return parse_quantity(s)


def format_cpu(milli: float) -> str:
    """Milli-CPU → canonical string ("250m", "2"). Whole cores render
    bare (CanonicalizeBytes picks the largest exact suffix)."""
    if milli == int(milli) and int(milli) % 1000 == 0:
        return str(int(milli) // 1000)
    if milli == int(milli):
        return f"{int(milli)}m"
    return f"{milli:g}m"


def format_memory(b: float) -> str:
    """Bytes → canonical binary-suffix string when exact ("1Gi"), bare
    integer otherwise."""
    for suffix in ("Ei", "Pi", "Ti", "Gi", "Mi", "Ki"):
        unit = _BINARY[suffix]
        if b >= unit and b == (b // unit) * unit:
            return f"{int(b // unit)}{suffix}"
    return f"{b:g}"
