from kubernetes_tpu.api import types  # noqa: F401
