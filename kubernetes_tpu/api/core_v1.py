"""The core/v1 object codec scheme — Pod and Node through the same
``runtime.Scheme`` pipeline the ComponentConfig uses.

The reference decodes EVERY API object through one registry
(apimachinery runtime/scheme.go:46; the core group's registration in
pkg/api/legacyscheme + k8s.io/api/core/v1): bytes -> versioned ->
convert -> internal. This module registers the v1 wire forms of the two
kinds this framework's clients exchange — Pod and Node — on a Scheme, so
codec access is uniform (``decode_any`` on any apiVersion/kind mapping)
while the conversion functions themselves are the ALREADY-TESTED wire
converters the gRPC/REST seams use (extender.pod_to_json/node_to_json,
server.pod_from_json, grpc_shim.node_from_json): one converter set, two
access paths, zero drift.

The versioned "types" here are deliberately thin mapping holders (the
wire document), not field-by-field dataclasses: the wire shape is
already defined by the JSON converters, and duplicating it as a second
dataclass tree would create exactly the drift the Scheme exists to
prevent.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubernetes_tpu.api.scheme import Scheme, SchemeError
from kubernetes_tpu.api.types import Node, Pod


@dataclass
class PodV1:
    """v1.Pod wire document (held as the parsed mapping)."""

    doc: dict = field(default_factory=dict)


@dataclass
class NodeV1:
    """v1.Node wire document (held as the parsed mapping)."""

    doc: dict = field(default_factory=dict)


def _pod_to_internal(v: PodV1) -> Pod:
    from kubernetes_tpu.server import pod_from_json

    return pod_from_json(v.doc)


def _pod_from_internal(p: Pod) -> PodV1:
    from kubernetes_tpu.extender import pod_to_json

    return PodV1(doc=pod_to_json(p))


def _node_to_internal(v: NodeV1) -> Node:
    from kubernetes_tpu.grpc_shim import node_from_json

    return node_from_json(v.doc)


def _node_from_internal(n: Node) -> NodeV1:
    from kubernetes_tpu.extender import node_to_json

    return NodeV1(doc=node_to_json(n))


#: the ONE kind table: kind -> (versioned holder, internal type,
#: to_internal, from_internal). Registration, decode, and encode all
#: derive from it — adding a kind is one row here.
_KIND_TABLE = {
    "Pod": (PodV1, Pod, _pod_to_internal, _pod_from_internal),
    "Node": (NodeV1, Node, _node_to_internal, _node_from_internal),
}


def new_scheme() -> Scheme:
    s = Scheme()
    for kind, (versioned, internal, to_int, from_int) in _KIND_TABLE.items():
        s.register("v1", kind, versioned)
        s.add_conversion(versioned, internal, to_int)
        s.add_conversion(internal, versioned, from_int)
    return s


SCHEME = new_scheme()


def decode_any(doc: dict):
    """Mapping -> internal object by its own apiVersion/kind (the
    UniversalDeserializer shape, serializer/codec_factory.go). Unlike
    the config scheme's strict dataclass build, core objects keep the
    wire document intact (unknown fields are legal on API objects —
    strictness is a ComponentConfig posture)."""
    if not isinstance(doc, dict):
        raise SchemeError(["document: expected a mapping"])
    api_version = doc.get("apiVersion", "v1")
    kind = doc.get("kind", "")
    if api_version != "v1" or kind not in _KIND_TABLE:
        raise SchemeError([
            f'no kind "{kind}" is registered for version "{api_version}"'
        ])
    versioned_type, internal, _, _ = _KIND_TABLE[kind]
    return SCHEME.convert(versioned_type(doc=doc), internal)


def encode(obj) -> dict:
    """Internal Pod/Node -> v1 wire mapping with apiVersion/kind stamped."""
    kind = type(obj).__name__
    if kind not in _KIND_TABLE:
        raise SchemeError([f"no v1 encoding registered for {kind}"])
    versioned = SCHEME.convert(obj, _KIND_TABLE[kind][0])
    return {"apiVersion": "v1", "kind": kind, **versioned.doc}
