"""The scheduler ComponentConfig scheme: v1alpha1 <-> internal.

The reference keeps the kube-scheduler's config types in two parallel
packages — the internal form the code consumes
(pkg/scheduler/apis/config/types.go:43) and the versioned wire form
(pkg/scheduler/apis/config/v1alpha1, staging .../kube-scheduler/config/
v1alpha1/types.go) — glued by a scheme that registers conversion and
defaulting (pkg/scheduler/apis/config/scheme/scheme.go:31 AddToScheme).
Here the internal form is :class:`kubernetes_tpu.config.
KubeSchedulerConfiguration` (snake_case, float seconds) and this module
is the versioned side:

- :class:`KubeSchedulerConfigurationV1alpha1` — wire spelling
  (camelCase field names, metav1.Duration strings like ``"15s"``);
- ``set_defaults_*`` — v1alpha1 defaulting (v1alpha1/defaults.go:42):
  note percentageOfNodesToScore defaults to 0 (= the adaptive 50%->5%
  rule) in the VERSIONED type while this framework's internal default is
  100 (dense batch solver scores everything) — exactly the kind of skew
  the versioned/internal split exists to express;
- conversions both ways, registered on :data:`SCHEME`;
- :func:`parse_duration` / :func:`format_duration` — the metav1.Duration
  wire form (Go time.ParseDuration subset).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.api.scheme import Scheme, SchemeError
from kubernetes_tpu.config import (
    FeatureGates,
    KubeSchedulerConfiguration,
    LeaderElectionConfig,
)

GROUP_VERSION = "kubescheduler.config.k8s.io/v1alpha1"
KIND = "KubeSchedulerConfiguration"

_DUR_RE = re.compile(r"(\d+(?:\.\d+)?)(ms|us|µs|ns|h|m|s)")
_UNIT_S = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 1e-3, "us": 1e-6,
           "µs": 1e-6, "ns": 1e-9}


def parse_duration(s) -> float:
    """'1m30s' -> 90.0 (Go time.ParseDuration subset: positive decimal
    components with h/m/s/ms/us/ns units; bare numbers rejected the way
    metav1.Duration rejects them)."""
    if isinstance(s, (int, float)) and not isinstance(s, bool):
        # tolerate a raw number as seconds (YAML authors do this);
        # the reference's strict JSON would reject it, but a one-way
        # tolerance loses no information
        return float(s)
    if not isinstance(s, str) or not s:
        raise SchemeError([f"duration: invalid value {s!r}"])
    pos = 0
    total = 0.0
    for m in _DUR_RE.finditer(s):
        if m.start() != pos:
            raise SchemeError([f"duration: invalid value {s!r}"])
        total += float(m.group(1)) * _UNIT_S[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise SchemeError([f"duration: invalid value {s!r}"])
    return total


def format_duration(seconds: float) -> str:
    """Seconds -> the canonical wire string ('90s' stays '1m30s'-free:
    the reference emits the largest exact unit mix; whole seconds are by
    far the common case so h/m/s composition is enough)."""
    if seconds != seconds or seconds < 0:
        raise SchemeError([f"duration: invalid value {seconds!r}"])
    ns = round(seconds * 1e9)
    if ns == 0:
        return "0s"
    out = []
    for unit, unit_ns in (("h", 3_600_000_000_000), ("m", 60_000_000_000),
                          ("s", 1_000_000_000), ("ms", 1_000_000),
                          ("us", 1_000), ("ns", 1)):
        q, ns = divmod(ns, unit_ns)
        if q:
            out.append(f"{q}{unit}")
    return "".join(out)


# -- versioned types (wire spelling) ----------------------------------------


@dataclass
class SchedulerAlgorithmSource:
    """v1alpha1 SchedulerAlgorithmSource (types.go AlgorithmSource):
    provider XOR policy; here policy carries the inline Policy mapping."""

    provider: Optional[str] = None
    policy: Optional[dict] = None


@dataclass
class LeaderElectionConfigurationV1alpha1:
    leaderElect: Optional[bool] = None
    leaseDuration: Optional[str] = None
    renewDeadline: Optional[str] = None
    retryPeriod: Optional[str] = None
    lockObjectNamespace: Optional[str] = None
    lockObjectName: Optional[str] = None


@dataclass
class RobustnessConfigurationV1alpha1:
    """Versioned spelling of the degradation-ladder knobs
    (config.RobustnessConfig): camelCase, durations as metav1.Duration
    strings like every other versioned time field."""

    cycleDeadline: Optional[str] = None
    solverRetries: Optional[int] = None
    transportRetries: Optional[int] = None
    retryBackoffBase: Optional[str] = None
    retryBackoffMax: Optional[str] = None
    retryJitter: Optional[float] = None
    breakerFailureThreshold: Optional[int] = None
    breakerOpenDuration: Optional[str] = None
    breakerHalfOpenProbes: Optional[int] = None
    validateResults: Optional[bool] = None
    hostValidate: Optional[bool] = None
    fallbackChain: Optional[list] = None
    extenderDegradeToIgnorable: Optional[bool] = None
    bindVerifyRetries: Optional[int] = None
    watchProgressDeadline: Optional[str] = None  # "0s" = stall det. off


@dataclass
class RecoveryConfigurationV1alpha1:
    """Versioned spelling of the crash/failover/device-loss recovery
    knobs (config.RecoveryConfig): camelCase, the cooloff as a
    metav1.Duration string like every other versioned time field."""

    fencedBinds: Optional[bool] = None
    reconcileOnTakeover: Optional[bool] = None
    releaseLeaseOnShutdown: Optional[bool] = None
    deviceResetLimit: Optional[int] = None
    deviceCooloff: Optional[str] = None


@dataclass
class LedgerConfigurationV1alpha1:
    """Versioned spelling of the perf-ledger / SLO-watchdog block
    (config.LedgerConfig): camelCase, the objective and windows as
    metav1.Duration strings like every other versioned time field."""

    enabled: Optional[bool] = None
    history: Optional[int] = None
    distWindow: Optional[int] = None
    baselineDecay: Optional[float] = None
    e2eP99Objective: Optional[str] = None  # "0s" = objective off
    costDriftRatio: Optional[float] = None  # 0 = objective off
    fastWindow: Optional[str] = None
    slowWindow: Optional[str] = None
    burnThreshold: Optional[float] = None
    engagePressure: Optional[bool] = None


@dataclass
class MemoryLedgerConfigurationV1alpha1:
    """Versioned spelling of the device-memory ledger block
    (config.MemoryLedgerConfig): camelCase, the sample interval as a
    metav1.Duration string like every other versioned time field."""

    enabled: Optional[bool] = None
    sampleInterval: Optional[str] = None  # "0s" = every cycle boundary
    preflight: Optional[bool] = None
    headroomFrac: Optional[float] = None
    limitBytes: Optional[int] = None  # 0 = device-reported limit
    history: Optional[int] = None
    censusLimit: Optional[int] = None


@dataclass
class JourneysConfigurationV1alpha1:
    """Versioned spelling of the per-pod journey tracer block
    (config.JourneysConfig): camelCase, the retention window as a
    metav1.Duration string like every other versioned time field."""

    enabled: Optional[bool] = None
    slowK: Optional[int] = None
    sampleEvery: Optional[int] = None  # 0 = completion sampling off
    window: Optional[str] = None
    maxPending: Optional[int] = None
    maxEvents: Optional[int] = None


@dataclass
class IncidentsConfigurationV1alpha1:
    """Versioned spelling of the incident-autopsy block
    (config.IncidentsConfig): camelCase (no duration fields — the
    cooldown and flight window are cycle counts by design)."""

    enabled: Optional[bool] = None
    capacity: Optional[int] = None
    flightWindow: Optional[int] = None
    journeysK: Optional[int] = None
    cooldownCycles: Optional[int] = None
    fallbackBurstThreshold: Optional[int] = None  # 0 = trigger off
    profileCycles: Optional[int] = None  # 0 = incident-armed off
    profileDir: Optional[str] = None  # "" = profiling off entirely
    maxProfiles: Optional[int] = None


@dataclass
class LockSanitizerConfigurationV1alpha1:
    """Versioned spelling of the instrumented-lock sanitizer block
    (sanitize.LockSanitizerConfig): camelCase, the hold budget as a
    metav1.Duration string like every other versioned time field."""

    enabled: Optional[bool] = None
    holdBudget: Optional[str] = None  # "0s" = hold check off
    debugGuards: Optional[bool] = None
    maxFindings: Optional[int] = None


@dataclass
class ObservabilityConfigurationV1alpha1:
    """Versioned spelling of the observability knobs
    (config.ObservabilityConfig): camelCase, the trace threshold as a
    metav1.Duration string like every other versioned time field."""

    enabled: Optional[bool] = None
    traceThreshold: Optional[str] = None
    traceSampling: Optional[float] = None
    recorderCapacity: Optional[int] = None
    traceRingCapacity: Optional[int] = None
    retraceStormThreshold: Optional[int] = None
    retraceStormWindow: Optional[int] = None
    sinkhornTelemetry: Optional[bool] = None
    explain: Optional[bool] = None
    explainTopK: Optional[int] = None
    auditInterval: Optional[str] = None  # "0s" = serving auditor off
    ledger: "LedgerConfigurationV1alpha1" = field(
        default_factory=LedgerConfigurationV1alpha1)
    memoryLedger: "MemoryLedgerConfigurationV1alpha1" = field(
        default_factory=MemoryLedgerConfigurationV1alpha1)
    journeys: "JourneysConfigurationV1alpha1" = field(
        default_factory=JourneysConfigurationV1alpha1)
    incidents: "IncidentsConfigurationV1alpha1" = field(
        default_factory=IncidentsConfigurationV1alpha1)
    lockSanitizer: "LockSanitizerConfigurationV1alpha1" = field(
        default_factory=LockSanitizerConfigurationV1alpha1)


@dataclass
class WarmupConfigurationV1alpha1:
    """Versioned spelling of the AOT-warmup block (config.WarmupConfig):
    camelCase keys, explicit bucket list."""

    enabled: Optional[bool] = None
    podBuckets: Optional[list] = None
    minBucket: Optional[int] = None
    includeFilter: Optional[bool] = None
    hostFallback: Optional[bool] = None


@dataclass
class IncrementalConfigurationV1alpha1:
    """Versioned spelling of the incremental-solve block
    (config.IncrementalConfig): camelCase; fractions stay raw floats
    (no duration fields to re-spell)."""

    enabled: Optional[bool] = None
    candidateBucket: Optional[int] = None
    maxBatchFrac: Optional[float] = None
    maxDirtyFrac: Optional[float] = None
    warmPotentials: Optional[bool] = None
    warmTol: Optional[float] = None
    qualityDelta: Optional[float] = None
    primary: Optional[bool] = None
    coldBlocks: Optional[int] = None
    autoTune: Optional[bool] = None
    groupQuotaFrac: Optional[float] = None


@dataclass
class ParallelConfigurationV1alpha1:
    """Versioned spelling of the sharded-execution block
    (config.ParallelConfig): ``mesh`` is ``"off"`` | ``"auto"`` | an
    integer device count, same vocabulary as the internal type (no
    duration fields to re-spell)."""

    mesh: Optional[object] = None  # "off" | "auto" | int


@dataclass
class ScenarioConfigurationV1alpha1:
    """Versioned spelling of the scenario-pack block
    (config.ScenarioConfig): camelCase; the pack vocabulary is the
    internal one (no duration fields to re-spell)."""

    pack: Optional[str] = None
    costWeight: Optional[float] = None
    fillBlock: Optional[int] = None
    preemptInBatch: Optional[bool] = None
    cascadeMaxPods: Optional[int] = None
    superpod: Optional[int] = None
    quality: Optional[bool] = None
    repackInterval: Optional[str] = None  # duration; "0s" = off
    repackMaxPods: Optional[int] = None


@dataclass
class ServingConfigurationV1alpha1:
    """Versioned spelling of the streaming-serving block
    (config.ServingConfig): camelCase, windows as metav1.Duration
    strings like every other versioned time field."""

    enabled: Optional[bool] = None
    minWait: Optional[str] = None
    maxWait: Optional[str] = None
    targetBucket: Optional[int] = None
    idleWait: Optional[str] = None
    flowConcurrency: Optional[int] = None
    watchConcurrency: Optional[int] = None
    flowQueueLength: Optional[int] = None
    queueTimeout: Optional[str] = None
    retryAfter: Optional[str] = None
    watchBuffer: Optional[int] = None
    shedQueueBound: Optional[int] = None
    degradedPressureFactor: Optional[float] = None


@dataclass
class KubeSchedulerConfigurationV1alpha1:
    schedulerName: Optional[str] = None
    algorithmSource: "SchedulerAlgorithmSource" = field(
        default_factory=SchedulerAlgorithmSource)
    hardPodAffinitySymmetricWeight: Optional[int] = None
    percentageOfNodesToScore: Optional[int] = None
    bindTimeoutSeconds: Optional[float] = None
    leaderElection: "LeaderElectionConfigurationV1alpha1" = field(
        default_factory=LeaderElectionConfigurationV1alpha1)
    featureGates: Optional[dict] = None
    #: framework plugins: a flat enabled-name list (the per-extension-
    #: point Plugins struct is recast — see config.py) and the
    #: reference-shaped pluginConfig list of {name, args}
    #: (apis/config/types.go:127)
    plugins: Optional[list] = None
    pluginConfig: Optional[list] = None
    # this implementation's solver block, versioned alongside (camelCase
    # on the wire like every other field)
    solver: Optional[str] = None
    perNodeCap: Optional[int] = None
    maxRounds: Optional[int] = None
    maxBatch: Optional[int] = None
    # pipelined cycle executor + incremental device-resident snapshot
    pipelineDepth: Optional[int] = None
    pipelineChunk: Optional[int] = None
    deviceResidentSnapshot: Optional[bool] = None
    snapshotMaxDirtyFrac: Optional[float] = None
    incremental: "IncrementalConfigurationV1alpha1" = field(
        default_factory=IncrementalConfigurationV1alpha1)
    warmup: "WarmupConfigurationV1alpha1" = field(
        default_factory=WarmupConfigurationV1alpha1)
    robustness: "RobustnessConfigurationV1alpha1" = field(
        default_factory=RobustnessConfigurationV1alpha1)
    recovery: "RecoveryConfigurationV1alpha1" = field(
        default_factory=RecoveryConfigurationV1alpha1)
    observability: "ObservabilityConfigurationV1alpha1" = field(
        default_factory=ObservabilityConfigurationV1alpha1)
    serving: "ServingConfigurationV1alpha1" = field(
        default_factory=ServingConfigurationV1alpha1)
    parallel: "ParallelConfigurationV1alpha1" = field(
        default_factory=ParallelConfigurationV1alpha1)
    scenario: "ScenarioConfigurationV1alpha1" = field(
        default_factory=ScenarioConfigurationV1alpha1)


# -- defaulting (v1alpha1/defaults.go:42) -----------------------------------


def set_defaults_kube_scheduler_configuration(
        obj: KubeSchedulerConfigurationV1alpha1):
    if obj.schedulerName is None:
        obj.schedulerName = "default-scheduler"
    if obj.algorithmSource.provider is None and obj.algorithmSource.policy is None:
        obj.algorithmSource.provider = "DefaultProvider"
    if obj.hardPodAffinitySymmetricWeight is None:
        obj.hardPodAffinitySymmetricWeight = 1
    if obj.percentageOfNodesToScore is None:
        # 0 selects the reference's adaptive 50%->5% rule — the versioned
        # default; the internal type's own default is 100 (see module doc)
        obj.percentageOfNodesToScore = 0
    if obj.bindTimeoutSeconds is None:
        obj.bindTimeoutSeconds = 600.0
    le = obj.leaderElection
    if le.leaderElect is None:
        le.leaderElect = True
    if le.leaseDuration is None:
        le.leaseDuration = "15s"
    if le.renewDeadline is None:
        le.renewDeadline = "10s"
    if le.retryPeriod is None:
        le.retryPeriod = "2s"
    if le.lockObjectNamespace is None:
        le.lockObjectNamespace = "kube-system"
    if le.lockObjectName is None:
        le.lockObjectName = "kube-scheduler"
    if obj.solver is None:
        obj.solver = "batch"
    if obj.perNodeCap is None:
        obj.perNodeCap = 4
    if obj.maxRounds is None:
        obj.maxRounds = 128
    if obj.maxBatch is None:
        obj.maxBatch = 8192
    if obj.pipelineDepth is None:
        obj.pipelineDepth = 2
    if obj.pipelineChunk is None:
        obj.pipelineChunk = 4096
    if obj.deviceResidentSnapshot is None:
        obj.deviceResidentSnapshot = True
    if obj.snapshotMaxDirtyFrac is None:
        obj.snapshotMaxDirtyFrac = 0.25
    inc = obj.incremental
    if inc.enabled is None:
        inc.enabled = False
    if inc.candidateBucket is None:
        inc.candidateBucket = 256
    if inc.maxBatchFrac is None:
        inc.maxBatchFrac = 0.5
    if inc.maxDirtyFrac is None:
        inc.maxDirtyFrac = 0.25
    if inc.warmPotentials is None:
        inc.warmPotentials = True
    if inc.warmTol is None:
        inc.warmTol = 1e-3
    if inc.qualityDelta is None:
        inc.qualityDelta = 0.02
    if inc.primary is None:
        inc.primary = False
    if inc.coldBlocks is None:
        inc.coldBlocks = 0
    if inc.autoTune is None:
        inc.autoTune = False
    if inc.groupQuotaFrac is None:
        inc.groupQuotaFrac = 0.5
    wu = obj.warmup
    if wu.enabled is None:
        wu.enabled = False
    if wu.podBuckets is None:
        wu.podBuckets = []
    if wu.minBucket is None:
        wu.minBucket = 256
    if wu.includeFilter is None:
        wu.includeFilter = True
    if wu.hostFallback is None:
        wu.hostFallback = False
    rb = obj.robustness
    if rb.cycleDeadline is None:
        rb.cycleDeadline = "0s"  # 0 = unbounded (the internal default)
    if rb.solverRetries is None:
        rb.solverRetries = 1
    if rb.transportRetries is None:
        rb.transportRetries = 2
    if rb.retryBackoffBase is None:
        rb.retryBackoffBase = "50ms"
    if rb.retryBackoffMax is None:
        rb.retryBackoffMax = "2s"
    if rb.retryJitter is None:
        rb.retryJitter = 0.2
    if rb.breakerFailureThreshold is None:
        rb.breakerFailureThreshold = 3
    if rb.breakerOpenDuration is None:
        rb.breakerOpenDuration = "30s"
    if rb.breakerHalfOpenProbes is None:
        rb.breakerHalfOpenProbes = 1
    if rb.validateResults is None:
        rb.validateResults = True
    if rb.hostValidate is None:
        rb.hostValidate = False
    if rb.fallbackChain is None:
        rb.fallbackChain = ["batch-cpu", "greedy"]
    if rb.extenderDegradeToIgnorable is None:
        rb.extenderDegradeToIgnorable = True
    if rb.bindVerifyRetries is None:
        rb.bindVerifyRetries = 3
    if rb.watchProgressDeadline is None:
        rb.watchProgressDeadline = "30s"
    rv = obj.recovery
    if rv.fencedBinds is None:
        rv.fencedBinds = True
    if rv.reconcileOnTakeover is None:
        rv.reconcileOnTakeover = True
    if rv.releaseLeaseOnShutdown is None:
        rv.releaseLeaseOnShutdown = True
    if rv.deviceResetLimit is None:
        rv.deviceResetLimit = 2
    if rv.deviceCooloff is None:
        rv.deviceCooloff = "5s"
    ob = obj.observability
    if ob.enabled is None:
        ob.enabled = True
    if ob.traceThreshold is None:
        ob.traceThreshold = "1s"
    if ob.traceSampling is None:
        ob.traceSampling = 1.0
    if ob.recorderCapacity is None:
        ob.recorderCapacity = 256
    if ob.traceRingCapacity is None:
        ob.traceRingCapacity = 64
    if ob.retraceStormThreshold is None:
        ob.retraceStormThreshold = 8
    if ob.retraceStormWindow is None:
        ob.retraceStormWindow = 64
    if ob.sinkhornTelemetry is None:
        ob.sinkhornTelemetry = True
    if ob.explain is None:
        ob.explain = True
    if ob.explainTopK is None:
        ob.explainTopK = 3
    if ob.auditInterval is None:
        ob.auditInterval = "0s"  # serving-runtime auditor off (internal default)
    lg = ob.ledger
    if lg.enabled is None:
        lg.enabled = True
    if lg.history is None:
        lg.history = 256
    if lg.distWindow is None:
        lg.distWindow = 256
    if lg.baselineDecay is None:
        lg.baselineDecay = 0.05
    if lg.e2eP99Objective is None:
        lg.e2eP99Objective = "0s"  # objective off (the internal default)
    if lg.costDriftRatio is None:
        lg.costDriftRatio = 0.0
    if lg.fastWindow is None:
        lg.fastWindow = "1m0s"
    if lg.slowWindow is None:
        lg.slowWindow = "10m0s"
    if lg.burnThreshold is None:
        lg.burnThreshold = 1.0
    if lg.engagePressure is None:
        lg.engagePressure = True
    mlg = ob.memoryLedger
    if mlg.enabled is None:
        mlg.enabled = True
    # internal default: census off the per-cycle path ("0s" opts into
    # every-boundary sampling)
    if mlg.sampleInterval is None:
        mlg.sampleInterval = "500ms"
    if mlg.preflight is None:
        mlg.preflight = True
    if mlg.headroomFrac is None:
        mlg.headroomFrac = 0.9
    if mlg.limitBytes is None:
        mlg.limitBytes = 0  # device-reported limit
    if mlg.history is None:
        mlg.history = 128
    if mlg.censusLimit is None:
        mlg.censusLimit = 4096
    jy = ob.journeys
    if jy.enabled is None:
        jy.enabled = True
    if jy.slowK is None:
        jy.slowK = 8
    if jy.sampleEvery is None:
        jy.sampleEvery = 100
    if jy.window is None:
        jy.window = "5m0s"
    if jy.maxPending is None:
        jy.maxPending = 4096
    if jy.maxEvents is None:
        jy.maxEvents = 64
    ic = ob.incidents
    if ic.enabled is None:
        ic.enabled = True
    if ic.capacity is None:
        ic.capacity = 16
    if ic.flightWindow is None:
        ic.flightWindow = 16
    if ic.journeysK is None:
        ic.journeysK = 4
    if ic.cooldownCycles is None:
        ic.cooldownCycles = 64
    if ic.fallbackBurstThreshold is None:
        ic.fallbackBurstThreshold = 3
    if ic.profileCycles is None:
        ic.profileCycles = 0  # incident-armed profiling off
    if ic.profileDir is None:
        ic.profileDir = ""  # profiling off entirely
    if ic.maxProfiles is None:
        ic.maxProfiles = 4
    ls = ob.lockSanitizer
    if ls.enabled is None:
        ls.enabled = False  # plain threading locks by default
    if ls.holdBudget is None:
        ls.holdBudget = "250ms"
    if ls.debugGuards is None:
        ls.debugGuards = True
    if ls.maxFindings is None:
        ls.maxFindings = 256
    sv = obj.serving
    if sv.enabled is None:
        sv.enabled = False
    if sv.minWait is None:
        sv.minWait = "5ms"
    if sv.maxWait is None:
        sv.maxWait = "50ms"
    if sv.targetBucket is None:
        sv.targetBucket = 1024
    if sv.idleWait is None:
        sv.idleWait = "500ms"
    if sv.flowConcurrency is None:
        sv.flowConcurrency = 16
    if sv.watchConcurrency is None:
        sv.watchConcurrency = 8
    if sv.flowQueueLength is None:
        sv.flowQueueLength = 64
    if sv.queueTimeout is None:
        sv.queueTimeout = "1s"
    if sv.retryAfter is None:
        sv.retryAfter = "1s"
    if sv.watchBuffer is None:
        sv.watchBuffer = 4096
    if sv.shedQueueBound is None:
        sv.shedQueueBound = 0
    if sv.degradedPressureFactor is None:
        sv.degradedPressureFactor = 4.0
    pl = obj.parallel
    if pl.mesh is None:
        pl.mesh = "off"
    sn = obj.scenario
    if sn.pack is None:
        sn.pack = ""
    if sn.costWeight is None:
        sn.costWeight = 4.0
    if sn.fillBlock is None:
        sn.fillBlock = 64
    if sn.preemptInBatch is None:
        sn.preemptInBatch = True
    if sn.cascadeMaxPods is None:
        sn.cascadeMaxPods = 1024
    if sn.superpod is None:
        sn.superpod = 4
    if sn.quality is None:
        sn.quality = True
    if sn.repackInterval is None:
        sn.repackInterval = "0s"
    if sn.repackMaxPods is None:
        sn.repackMaxPods = 64
    return obj


# -- conversions (v1alpha1/zz_generated.conversion.go shape) ----------------


def _dur(field_name: str, value, prefix: str = "leaderElection") -> float:
    """parse_duration with the FIELD PATH stamped into the error — the
    module's error contract; a bare 'duration: invalid' gives the user
    no way to locate which of several duration fields failed."""
    try:
        return parse_duration(value)
    except SchemeError:
        raise SchemeError([
            f"{prefix}.{field_name}: invalid duration {value!r}"
        ])


def _to_internal(v: KubeSchedulerConfigurationV1alpha1) -> KubeSchedulerConfiguration:
    """Conversion proper. The default table lives in exactly one place
    (set_defaults_*): defaulting is idempotent, so it is re-applied here
    on a COPY unconditionally — Scheme.decode callers pay a no-op pass,
    direct convert() callers with raw/partial objects get correct
    defaults instead of a crash. Every error surfaces as SchemeError
    with a field path, never a raw ValueError/KeyError."""
    import copy

    from kubernetes_tpu.config import load_policy

    v = set_defaults_kube_scheduler_configuration(copy.deepcopy(v))
    le = v.leaderElection
    policy = None
    if v.algorithmSource.policy is not None:
        try:
            policy = load_policy(v.algorithmSource.policy)
        except SchemeError:
            raise
        except Exception as e:
            raise SchemeError([f"algorithmSource.policy: {e}"])
    try:
        gates = FeatureGates(overrides=dict(v.featureGates or {}))
    except ValueError as e:
        raise SchemeError([f"featureGates: {e}"])
    plugins = v.plugins or []
    if not (isinstance(plugins, list)
            and all(isinstance(p, str) for p in plugins)):
        # a scalar string would tuple() into characters; the reference's
        # per-extension-point Plugins dict would tuple() into its keys —
        # both decode into garbage silently without this check
        raise SchemeError([
            "plugins: expected a list of plugin names "
            f"(got {type(plugins).__name__})"
        ])
    plugin_config = {}
    for i, entry in enumerate(v.pluginConfig or []):
        if not isinstance(entry, dict) or not entry.get("name"):
            raise SchemeError([f"pluginConfig[{i}].name: Required value"])
        unknown = set(entry) - {"name", "args"}
        if unknown:
            # strict-serializer posture, same as every other field
            raise SchemeError([
                f"pluginConfig[{i}].{k}: unknown field"
                for k in sorted(unknown)
            ])
        args = entry.get("args") or {}
        if not isinstance(args, dict):
            raise SchemeError([
                f"pluginConfig[{i}].args: expected a mapping "
                f"(got {type(args).__name__})"
            ])
        plugin_config[entry["name"]] = dict(args)
    try:
        bind_timeout = float(v.bindTimeoutSeconds)
    except (TypeError, ValueError):
        raise SchemeError([
            f"bindTimeoutSeconds: invalid value {v.bindTimeoutSeconds!r}"
        ])
    return KubeSchedulerConfiguration(
        scheduler_name=v.schedulerName,
        algorithm_provider=v.algorithmSource.provider or "DefaultProvider",
        policy=policy,
        hard_pod_affinity_symmetric_weight=v.hardPodAffinitySymmetricWeight,
        percentage_of_nodes_to_score=v.percentageOfNodesToScore,
        bind_timeout_seconds=bind_timeout,
        leader_election=LeaderElectionConfig(
            leader_elect=le.leaderElect,
            lease_duration_s=_dur("leaseDuration", le.leaseDuration),
            renew_deadline_s=_dur("renewDeadline", le.renewDeadline),
            retry_period_s=_dur("retryPeriod", le.retryPeriod),
            lock_object_namespace=le.lockObjectNamespace,
            lock_object_name=le.lockObjectName,
        ),
        feature_gates=gates,
        plugins=tuple(plugins),
        plugin_config=plugin_config,
        solver=v.solver,
        per_node_cap=v.perNodeCap,
        max_rounds=v.maxRounds,
        max_batch=v.maxBatch,
        pipeline_depth=v.pipelineDepth,
        pipeline_chunk=v.pipelineChunk,
        device_resident_snapshot=v.deviceResidentSnapshot,
        snapshot_max_dirty_frac=v.snapshotMaxDirtyFrac,
        incremental=_incremental_to_internal(v.incremental),
        warmup=_warmup_to_internal(v.warmup),
        robustness=_robustness_to_internal(v.robustness),
        recovery=_recovery_to_internal(v.recovery),
        observability=_observability_to_internal(v.observability),
        serving=_serving_to_internal(v.serving),
        parallel=_parallel_to_internal(v.parallel),
        scenario=_scenario_to_internal(v.scenario),
    )


def _scenario_to_internal(sn: ScenarioConfigurationV1alpha1):
    from kubernetes_tpu.config import ScenarioConfig

    if not isinstance(sn.pack, str):
        raise SchemeError([
            f"scenario.pack: invalid value {sn.pack!r}: expected a pack "
            "name string ('' = off)"
        ])
    return ScenarioConfig(
        pack=sn.pack,
        cost_weight=sn.costWeight,
        fill_block=sn.fillBlock,
        preempt_in_batch=sn.preemptInBatch,
        cascade_max_pods=sn.cascadeMaxPods,
        superpod=sn.superpod,
        quality=sn.quality,
        repack_interval_s=_dur("repackInterval", sn.repackInterval,
                               "scenario"),
        repack_max_pods=sn.repackMaxPods,
    )


def _incremental_to_internal(inc: IncrementalConfigurationV1alpha1):
    from kubernetes_tpu.config import IncrementalConfig

    return IncrementalConfig(
        enabled=inc.enabled,
        candidate_bucket=inc.candidateBucket,
        max_batch_frac=inc.maxBatchFrac,
        max_dirty_frac=inc.maxDirtyFrac,
        warm_potentials=inc.warmPotentials,
        warm_tol=inc.warmTol,
        quality_delta=inc.qualityDelta,
        primary=inc.primary,
        cold_blocks=inc.coldBlocks,
        auto_tune=inc.autoTune,
        group_quota_frac=inc.groupQuotaFrac,
    )


def _parallel_to_internal(pl: ParallelConfigurationV1alpha1):
    from kubernetes_tpu.config import ParallelConfig

    mesh = pl.mesh
    ok = mesh in ("off", "auto") or (
        isinstance(mesh, int) and not isinstance(mesh, bool) and mesh >= 1)
    if not ok:
        raise SchemeError([
            f"parallel.mesh: invalid value {mesh!r}: expected 'off', "
            "'auto', or a positive device count"
        ])
    return ParallelConfig(mesh=mesh)


def _recovery_to_internal(rv: RecoveryConfigurationV1alpha1):
    from kubernetes_tpu.config import RecoveryConfig

    return RecoveryConfig(
        fenced_binds=rv.fencedBinds,
        reconcile_on_takeover=rv.reconcileOnTakeover,
        release_lease_on_shutdown=rv.releaseLeaseOnShutdown,
        device_reset_limit=rv.deviceResetLimit,
        device_cooloff_s=_dur("deviceCooloff", rv.deviceCooloff,
                              "recovery"),
    )


def _serving_to_internal(sv: ServingConfigurationV1alpha1):
    from kubernetes_tpu.config import ServingConfig

    return ServingConfig(
        enabled=sv.enabled,
        min_wait_s=_dur("minWait", sv.minWait, "serving"),
        max_wait_s=_dur("maxWait", sv.maxWait, "serving"),
        target_bucket=sv.targetBucket,
        idle_wait_s=_dur("idleWait", sv.idleWait, "serving"),
        flow_concurrency=sv.flowConcurrency,
        watch_concurrency=sv.watchConcurrency,
        flow_queue_length=sv.flowQueueLength,
        queue_timeout_s=_dur("queueTimeout", sv.queueTimeout, "serving"),
        retry_after_s=_dur("retryAfter", sv.retryAfter, "serving"),
        watch_buffer=sv.watchBuffer,
        shed_queue_bound=sv.shedQueueBound,
        degraded_pressure_factor=sv.degradedPressureFactor,
    )


def _warmup_to_internal(wu: WarmupConfigurationV1alpha1):
    from kubernetes_tpu.config import WarmupConfig

    buckets = wu.podBuckets
    if not (isinstance(buckets, list)
            and all(isinstance(b, int) and not isinstance(b, bool)
                    for b in buckets)):
        raise SchemeError([
            "warmup.podBuckets: expected a list of integers "
            f"(got {type(buckets).__name__})"
        ])
    return WarmupConfig(
        enabled=wu.enabled,
        pod_buckets=tuple(buckets),
        min_bucket=wu.minBucket,
        include_filter=wu.includeFilter,
        host_fallback=wu.hostFallback,
    )


def _observability_to_internal(ob: ObservabilityConfigurationV1alpha1):
    from kubernetes_tpu.config import (
        IncidentsConfig,
        JourneysConfig,
        LedgerConfig,
        MemoryLedgerConfig,
        ObservabilityConfig,
    )
    from kubernetes_tpu.sanitize import LockSanitizerConfig

    lg = ob.ledger
    mlg = ob.memoryLedger
    jy = ob.journeys
    ic = ob.incidents
    ls = ob.lockSanitizer
    return ObservabilityConfig(
        enabled=ob.enabled,
        trace_threshold_s=_dur("traceThreshold", ob.traceThreshold,
                               "observability"),
        trace_sampling=ob.traceSampling,
        recorder_capacity=ob.recorderCapacity,
        trace_ring_capacity=ob.traceRingCapacity,
        retrace_storm_threshold=ob.retraceStormThreshold,
        retrace_storm_window=ob.retraceStormWindow,
        sinkhorn_telemetry=ob.sinkhornTelemetry,
        explain=ob.explain,
        explain_top_k=ob.explainTopK,
        audit_interval_s=_dur("auditInterval", ob.auditInterval,
                              "observability"),
        ledger=LedgerConfig(
            enabled=lg.enabled,
            history=lg.history,
            dist_window=lg.distWindow,
            baseline_decay=lg.baselineDecay,
            e2e_p99_objective_s=_dur("ledger.e2eP99Objective",
                                     lg.e2eP99Objective, "observability"),
            cost_drift_ratio=lg.costDriftRatio,
            fast_window_s=_dur("ledger.fastWindow", lg.fastWindow,
                               "observability"),
            slow_window_s=_dur("ledger.slowWindow", lg.slowWindow,
                               "observability"),
            burn_threshold=lg.burnThreshold,
            engage_pressure=lg.engagePressure,
        ),
        memory_ledger=MemoryLedgerConfig(
            enabled=mlg.enabled,
            sample_interval_s=_dur("memoryLedger.sampleInterval",
                                   mlg.sampleInterval, "observability"),
            preflight=mlg.preflight,
            headroom_frac=mlg.headroomFrac,
            limit_bytes=mlg.limitBytes,
            history=mlg.history,
            census_limit=mlg.censusLimit,
        ),
        journeys=JourneysConfig(
            enabled=jy.enabled,
            slow_k=jy.slowK,
            sample_every=jy.sampleEvery,
            window_s=_dur("journeys.window", jy.window, "observability"),
            max_pending=jy.maxPending,
            max_events=jy.maxEvents,
        ),
        incidents=IncidentsConfig(
            enabled=ic.enabled,
            capacity=ic.capacity,
            flight_window=ic.flightWindow,
            journeys_k=ic.journeysK,
            cooldown_cycles=ic.cooldownCycles,
            fallback_burst_threshold=ic.fallbackBurstThreshold,
            profile_cycles=ic.profileCycles,
            profile_dir=ic.profileDir,
            max_profiles=ic.maxProfiles,
        ),
        lock_sanitizer=LockSanitizerConfig(
            enabled=ls.enabled,
            hold_budget_s=_dur("lockSanitizer.holdBudget", ls.holdBudget,
                               "observability"),
            debug_guards=ls.debugGuards,
            max_findings=ls.maxFindings,
        ),
    )


def _robustness_to_internal(rb: RobustnessConfigurationV1alpha1):
    from kubernetes_tpu.config import RobustnessConfig

    chain = rb.fallbackChain
    if not (isinstance(chain, list)
            and all(isinstance(t, str) for t in chain)):
        raise SchemeError([
            "robustness.fallbackChain: expected a list of tier names "
            f"(got {type(chain).__name__})"
        ])
    return RobustnessConfig(
        cycle_deadline_s=_dur("cycleDeadline", rb.cycleDeadline,
                              "robustness"),
        solver_retries=rb.solverRetries,
        transport_retries=rb.transportRetries,
        retry_backoff_base_s=_dur("retryBackoffBase", rb.retryBackoffBase,
                                  "robustness"),
        retry_backoff_max_s=_dur("retryBackoffMax", rb.retryBackoffMax,
                                 "robustness"),
        retry_jitter=rb.retryJitter,
        breaker_failure_threshold=rb.breakerFailureThreshold,
        breaker_open_duration_s=_dur("breakerOpenDuration",
                                     rb.breakerOpenDuration, "robustness"),
        breaker_half_open_probes=rb.breakerHalfOpenProbes,
        validate_results=rb.validateResults,
        host_validate=rb.hostValidate,
        fallback_chain=tuple(chain),
        extender_degrade_to_ignorable=rb.extenderDegradeToIgnorable,
        bind_verify_retries=rb.bindVerifyRetries,
        watch_progress_deadline_s=_dur("watchProgressDeadline",
                                       rb.watchProgressDeadline,
                                       "robustness"),
    )


def _from_internal(c: KubeSchedulerConfiguration) -> KubeSchedulerConfigurationV1alpha1:
    le = c.leader_election
    rc = c.robustness
    gates = c.feature_gates.overrides() or None
    return KubeSchedulerConfigurationV1alpha1(
        schedulerName=c.scheduler_name,
        algorithmSource=SchedulerAlgorithmSource(
            provider=c.algorithm_provider if c.policy is None else None,
            policy=None,  # Policy objects don't encode back (one-way,
            # like the reference's file-referenced policy source)
        ),
        hardPodAffinitySymmetricWeight=c.hard_pod_affinity_symmetric_weight,
        percentageOfNodesToScore=c.percentage_of_nodes_to_score,
        bindTimeoutSeconds=c.bind_timeout_seconds,
        leaderElection=LeaderElectionConfigurationV1alpha1(
            leaderElect=le.leader_elect,
            leaseDuration=format_duration(le.lease_duration_s),
            renewDeadline=format_duration(le.renew_deadline_s),
            retryPeriod=format_duration(le.retry_period_s),
            lockObjectNamespace=le.lock_object_namespace,
            lockObjectName=le.lock_object_name,
        ),
        featureGates=gates,
        plugins=list(c.plugins) or None,
        pluginConfig=[{"name": k, "args": dict(v)}
                      for k, v in c.plugin_config.items()] or None,
        solver=c.solver,
        perNodeCap=c.per_node_cap,
        maxRounds=c.max_rounds,
        maxBatch=c.max_batch,
        pipelineDepth=c.pipeline_depth,
        pipelineChunk=c.pipeline_chunk,
        deviceResidentSnapshot=c.device_resident_snapshot,
        snapshotMaxDirtyFrac=c.snapshot_max_dirty_frac,
        incremental=IncrementalConfigurationV1alpha1(
            enabled=c.incremental.enabled,
            candidateBucket=c.incremental.candidate_bucket,
            maxBatchFrac=c.incremental.max_batch_frac,
            maxDirtyFrac=c.incremental.max_dirty_frac,
            warmPotentials=c.incremental.warm_potentials,
            warmTol=c.incremental.warm_tol,
            qualityDelta=c.incremental.quality_delta,
            primary=c.incremental.primary,
            coldBlocks=c.incremental.cold_blocks,
            autoTune=c.incremental.auto_tune,
            groupQuotaFrac=c.incremental.group_quota_frac,
        ),
        warmup=WarmupConfigurationV1alpha1(
            enabled=c.warmup.enabled,
            podBuckets=list(c.warmup.pod_buckets),
            minBucket=c.warmup.min_bucket,
            includeFilter=c.warmup.include_filter,
            hostFallback=c.warmup.host_fallback,
        ),
        robustness=RobustnessConfigurationV1alpha1(
            cycleDeadline=format_duration(rc.cycle_deadline_s),
            solverRetries=rc.solver_retries,
            transportRetries=rc.transport_retries,
            retryBackoffBase=format_duration(rc.retry_backoff_base_s),
            retryBackoffMax=format_duration(rc.retry_backoff_max_s),
            retryJitter=rc.retry_jitter,
            breakerFailureThreshold=rc.breaker_failure_threshold,
            breakerOpenDuration=format_duration(rc.breaker_open_duration_s),
            breakerHalfOpenProbes=rc.breaker_half_open_probes,
            validateResults=rc.validate_results,
            hostValidate=rc.host_validate,
            fallbackChain=list(rc.fallback_chain),
            extenderDegradeToIgnorable=rc.extender_degrade_to_ignorable,
            bindVerifyRetries=rc.bind_verify_retries,
            watchProgressDeadline=format_duration(
                rc.watch_progress_deadline_s),
        ),
        recovery=RecoveryConfigurationV1alpha1(
            fencedBinds=c.recovery.fenced_binds,
            reconcileOnTakeover=c.recovery.reconcile_on_takeover,
            releaseLeaseOnShutdown=c.recovery.release_lease_on_shutdown,
            deviceResetLimit=c.recovery.device_reset_limit,
            deviceCooloff=format_duration(c.recovery.device_cooloff_s),
        ),
        observability=ObservabilityConfigurationV1alpha1(
            enabled=c.observability.enabled,
            traceThreshold=format_duration(c.observability.trace_threshold_s),
            traceSampling=c.observability.trace_sampling,
            recorderCapacity=c.observability.recorder_capacity,
            traceRingCapacity=c.observability.trace_ring_capacity,
            retraceStormThreshold=c.observability.retrace_storm_threshold,
            retraceStormWindow=c.observability.retrace_storm_window,
            sinkhornTelemetry=c.observability.sinkhorn_telemetry,
            explain=c.observability.explain,
            explainTopK=c.observability.explain_top_k,
            auditInterval=format_duration(
                c.observability.audit_interval_s),
            ledger=LedgerConfigurationV1alpha1(
                enabled=c.observability.ledger.enabled,
                history=c.observability.ledger.history,
                distWindow=c.observability.ledger.dist_window,
                baselineDecay=c.observability.ledger.baseline_decay,
                e2eP99Objective=format_duration(
                    c.observability.ledger.e2e_p99_objective_s),
                costDriftRatio=c.observability.ledger.cost_drift_ratio,
                fastWindow=format_duration(
                    c.observability.ledger.fast_window_s),
                slowWindow=format_duration(
                    c.observability.ledger.slow_window_s),
                burnThreshold=c.observability.ledger.burn_threshold,
                engagePressure=c.observability.ledger.engage_pressure,
            ),
            memoryLedger=MemoryLedgerConfigurationV1alpha1(
                enabled=c.observability.memory_ledger.enabled,
                sampleInterval=format_duration(
                    c.observability.memory_ledger.sample_interval_s),
                preflight=c.observability.memory_ledger.preflight,
                headroomFrac=c.observability.memory_ledger.headroom_frac,
                limitBytes=c.observability.memory_ledger.limit_bytes,
                history=c.observability.memory_ledger.history,
                censusLimit=c.observability.memory_ledger.census_limit,
            ),
            journeys=JourneysConfigurationV1alpha1(
                enabled=c.observability.journeys.enabled,
                slowK=c.observability.journeys.slow_k,
                sampleEvery=c.observability.journeys.sample_every,
                window=format_duration(
                    c.observability.journeys.window_s),
                maxPending=c.observability.journeys.max_pending,
                maxEvents=c.observability.journeys.max_events,
            ),
            incidents=IncidentsConfigurationV1alpha1(
                enabled=c.observability.incidents.enabled,
                capacity=c.observability.incidents.capacity,
                flightWindow=c.observability.incidents.flight_window,
                journeysK=c.observability.incidents.journeys_k,
                cooldownCycles=c.observability.incidents.cooldown_cycles,
                fallbackBurstThreshold=(
                    c.observability.incidents.fallback_burst_threshold),
                profileCycles=c.observability.incidents.profile_cycles,
                profileDir=c.observability.incidents.profile_dir,
                maxProfiles=c.observability.incidents.max_profiles,
            ),
            lockSanitizer=LockSanitizerConfigurationV1alpha1(
                enabled=c.observability.lock_sanitizer.enabled,
                holdBudget=format_duration(
                    c.observability.lock_sanitizer.hold_budget_s),
                debugGuards=c.observability.lock_sanitizer.debug_guards,
                maxFindings=c.observability.lock_sanitizer.max_findings,
            ),
        ),
        serving=ServingConfigurationV1alpha1(
            enabled=c.serving.enabled,
            minWait=format_duration(c.serving.min_wait_s),
            maxWait=format_duration(c.serving.max_wait_s),
            targetBucket=c.serving.target_bucket,
            idleWait=format_duration(c.serving.idle_wait_s),
            flowConcurrency=c.serving.flow_concurrency,
            watchConcurrency=c.serving.watch_concurrency,
            flowQueueLength=c.serving.flow_queue_length,
            queueTimeout=format_duration(c.serving.queue_timeout_s),
            retryAfter=format_duration(c.serving.retry_after_s),
            watchBuffer=c.serving.watch_buffer,
            shedQueueBound=c.serving.shed_queue_bound,
            degradedPressureFactor=c.serving.degraded_pressure_factor,
        ),
        parallel=ParallelConfigurationV1alpha1(mesh=c.parallel.mesh),
        scenario=ScenarioConfigurationV1alpha1(
            pack=c.scenario.pack,
            costWeight=c.scenario.cost_weight,
            fillBlock=c.scenario.fill_block,
            preemptInBatch=c.scenario.preempt_in_batch,
            cascadeMaxPods=c.scenario.cascade_max_pods,
            superpod=c.scenario.superpod,
            quality=c.scenario.quality,
            repackInterval=format_duration(c.scenario.repack_interval_s),
            repackMaxPods=c.scenario.repack_max_pods,
        ),
    )


def new_scheme() -> Scheme:
    """AddToScheme (scheme/scheme.go:39): register kinds, defaulting,
    and both conversion directions on a fresh Scheme."""
    s = Scheme()
    s.register(GROUP_VERSION, KIND, KubeSchedulerConfigurationV1alpha1)
    s.add_defaulting(KubeSchedulerConfigurationV1alpha1,
                     set_defaults_kube_scheduler_configuration)
    s.add_conversion(KubeSchedulerConfigurationV1alpha1,
                     KubeSchedulerConfiguration, _to_internal)
    s.add_conversion(KubeSchedulerConfiguration,
                     KubeSchedulerConfigurationV1alpha1, _from_internal)
    return s


SCHEME = new_scheme()


def decode(doc: dict) -> KubeSchedulerConfiguration:
    """Versioned mapping -> internal config (the codec path the CLI
    uses for apiVersion-tagged files)."""
    return SCHEME.decode(doc, KubeSchedulerConfiguration)


def encode(cfg: KubeSchedulerConfiguration) -> dict:
    return SCHEME.encode(cfg, GROUP_VERSION, KIND)
