"""Label and field selectors — the server-side LIST filtering library.

Every reference client filters lists AT THE SERVER: ListOptions carries
``labelSelector``/``fieldSelector`` strings
(staging/src/k8s.io/apimachinery/pkg/apis/meta/v1/types.go:322), parsed
by the labels package's requirement grammar (labels/selector.go Parse)
and the fields package's =/==/!= pair grammar (fields/selector.go
ParseSelector), then evaluated against each object by the resource's
selection predicate (pkg/registry/core/pod/strategy.go:197 MatchPod —
including the ``spec.nodeName`` field selector kubelets live on;
node/strategy.go MatchNode). Client-side filtering of a full LIST is the
exact anti-pattern the watch cache exists to prevent.

This module is that library for the REST facade and the in-process
informer seam:

- :func:`parse_label_selector` — the full requirement grammar:
  ``k=v``, ``k==v``, ``k!=v``, ``k in (a,b)``, ``k notin (a,b)``,
  ``k`` (exists), ``!k`` (not-exists), ``k>n`` / ``k<n`` (numeric),
  comma-joined (AND).
- :func:`parse_field_selector` — comma-joined ``k=v``/``k==v``/``k!=v``.
- :func:`pod_fields` / :func:`node_fields` — the supported field-label
  surface of each kind; an UNSUPPORTED key is an error at match time
  ("field label not supported", the ToSelectableFields contract), never
  a silent everything-matches.

Matching is pure host-side Python over object attributes — this runs in
the API server's request path, not on device.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, Mapping, Sequence, Tuple

__all__ = [
    "SelectorError",
    "Requirement",
    "parse_label_selector",
    "match_labels",
    "parse_field_selector",
    "match_fields",
    "pod_fields",
    "node_fields",
    "event_fields",
]


class SelectorError(ValueError):
    """Unparseable selector or unsupported field label."""


#: operators in the labels.Requirement sense (selector.go Operator)
EXISTS, NOT_EXISTS = "exists", "!"
EQ, NEQ, IN, NOT_IN, GT, LT = "=", "!=", "in", "notin", ">", "<"


@dataclass(frozen=True)
class Requirement:
    key: str
    op: str
    values: Tuple[str, ...] = ()


_KEY = r"[A-Za-z0-9](?:[-A-Za-z0-9_./]*[A-Za-z0-9])?"
_VALUE = r"[A-Za-z0-9](?:[-A-Za-z0-9_.]*[A-Za-z0-9])?|"
_SET_RE = re.compile(
    rf"^({_KEY})\s+(in|notin)\s+\(\s*([^)]*)\)$"
)
_PAIR_RE = re.compile(rf"^({_KEY})\s*(==|=|!=|>|<)\s*({_VALUE})$")
_EXISTS_RE = re.compile(rf"^(!?)({_KEY})$")


def _split_requirements(s: str) -> list:
    """Comma-split outside parentheses (set values contain commas)."""
    out, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(0, depth - 1)
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return [part.strip() for part in out if part.strip()]


def parse_label_selector(s: str) -> Tuple[Requirement, ...]:
    """labels.Parse: a comma-joined AND of requirements. Empty string =
    match everything (labels.Everything())."""
    reqs = []
    for part in _split_requirements(s or ""):
        m = _SET_RE.match(part)
        if m:
            vals = tuple(v.strip() for v in m.group(3).split(",")
                         if v.strip())
            if not vals:
                raise SelectorError(
                    f"empty value set in requirement {part!r}")
            reqs.append(Requirement(m.group(1),
                                    IN if m.group(2) == "in" else NOT_IN,
                                    vals))
            continue
        m = _PAIR_RE.match(part)
        if m:
            key, op, val = m.group(1), m.group(2), m.group(3)
            op = EQ if op in ("=", "==") else op
            if op in (GT, LT):
                try:
                    float(val)
                except ValueError:
                    raise SelectorError(
                        f"{part!r}: gt/lt require a numeric value")
            reqs.append(Requirement(key, NEQ if op == "!=" else op, (val,)))
            continue
        m = _EXISTS_RE.match(part)
        if m:
            reqs.append(Requirement(
                m.group(2), NOT_EXISTS if m.group(1) else EXISTS))
            continue
        raise SelectorError(f"unparseable selector requirement {part!r}")
    return tuple(reqs)


def match_labels(reqs: Sequence[Requirement],
                 labels: Mapping[str, str]) -> bool:
    """Requirement.Matches over a label map (selector.go:214)."""
    for r in reqs:
        has = r.key in labels
        val = labels.get(r.key, "")
        if r.op == EXISTS:
            if not has:
                return False
        elif r.op == NOT_EXISTS:
            if has:
                return False
        elif r.op == EQ:
            if not has or val != r.values[0]:
                return False
        elif r.op == NEQ:
            # the reference's != also matches ABSENT keys
            if has and val == r.values[0]:
                return False
        elif r.op == IN:
            if not has or val not in r.values:
                return False
        elif r.op == NOT_IN:
            if has and val in r.values:
                return False
        elif r.op in (GT, LT):
            if not has:
                return False
            try:
                num = float(val)
            except ValueError:
                return False  # non-numeric label value never matches
            bound = float(r.values[0])
            if r.op == GT and not num > bound:
                return False
            if r.op == LT and not num < bound:
                return False
    return True


def parse_field_selector(s: str) -> Tuple[Requirement, ...]:
    """fields.ParseSelector: comma-joined ``k=v``/``k==v``/``k!=v`` only
    (the fields grammar has no set/exists operators)."""
    reqs = []
    for part in _split_requirements(s or ""):
        if "!=" in part:
            key, _, val = part.partition("!=")
            op = NEQ
        elif "==" in part:
            key, _, val = part.partition("==")
            op = EQ
        elif "=" in part:
            key, _, val = part.partition("=")
            op = EQ
        else:
            raise SelectorError(
                f"unparseable field selector {part!r} (want k=v)")
        key = key.strip()
        if not key:
            raise SelectorError(f"empty key in field selector {part!r}")
        reqs.append(Requirement(key, op, (val.strip(),)))
    return tuple(reqs)


def match_fields(reqs: Sequence[Requirement],
                 fields: Mapping[str, str]) -> bool:
    """Field matching is exact string compare over the kind's selectable
    field set; an unknown key raises (generic/registry Store.List surfaces
    'field label not supported by the ... converter')."""
    for r in reqs:
        if r.key not in fields:
            raise SelectorError(
                f'field label not supported: "{r.key}"')
        val = fields[r.key]
        if r.op == EQ and val != r.values[0]:
            return False
        if r.op == NEQ and val == r.values[0]:
            return False
    return True


def pod_fields(pod) -> Dict[str, str]:
    """MatchPod's ToSelectableFields (pod/strategy.go:197): the pod field
    labels servers answer — spec.nodeName is the one kubelet/drain-scale
    list paths depend on."""
    return {
        "metadata.name": pod.name,
        "metadata.namespace": pod.namespace,
        "spec.nodeName": pod.node_name,
        "spec.schedulerName": pod.scheduler_name,
        "spec.restartPolicy": getattr(pod, "restart_policy", "Always"),
        "status.phase": getattr(pod, "phase", ""),
        "status.nominatedNodeName": pod.nominated_node_name,
    }


def node_fields(node) -> Dict[str, str]:
    """MatchNode's selectable fields (node/strategy.go)."""
    return {
        "metadata.name": node.name,
        "spec.unschedulable": "true" if node.unschedulable else "false",
    }


def event_fields(key: str, ev) -> Dict[str, str]:
    """The v1 Event selectable fields kubectl's --field-selector rides
    (registry/core/event/strategy.go GetAttrs ToSelectableFields):
    involvedObject identity + reason + type. ``key`` is the event's
    store key ("ns/name.series")."""
    ns, _, name = key.partition("/")
    obj_ns, _, obj_name = ev.object_key.partition("/")
    return {
        "metadata.name": name,
        "metadata.namespace": ns,
        "involvedObject.kind": getattr(ev, "involved_kind", "Pod"),
        "involvedObject.name": obj_name,
        "involvedObject.namespace": obj_ns,
        "reason": ev.reason,
        "type": ev.type,
    }


def validate_field_keys(reqs: Sequence[Requirement], kind: str) -> None:
    """Reject unsupported field labels at REQUEST/CONSTRUCTION time, not
    per object (ListOptions decoding semantics). ``kind``: "pods",
    "nodes", or "events". The one shared probe for every field-selector
    consumer (REST list/watch, Reflector) — the selectable surface
    lives only in pod_fields/node_fields/event_fields."""
    if not reqs:
        return
    from kubernetes_tpu.api.types import Node, Pod

    if kind == "events":
        from kubernetes_tpu.events import Event

        probe = event_fields("probe/probe.x", Event(
            type="Normal", reason="", object_key="probe/probe",
            message=""))
    elif kind == "pods":
        probe = pod_fields(Pod(name="probe"))
    else:
        probe = node_fields(Node(name="probe"))
    match_fields(reqs, probe)
