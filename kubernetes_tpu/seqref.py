"""A tiny, faithful Python port of the reference scheduler's *semantics*,
used ONLY as a differential-test oracle (SURVEY.md §4: "differential tests
against a tiny Go-faithful Python reference implementation").

Each function mirrors one Go predicate/priority
(pkg/scheduler/algorithm/{predicates,priorities}) evaluated the reference
way: per (pod, node), object-at-a-time, no tensors. Deliberately slow and
obvious.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from kubernetes_tpu.api.types import (
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    EFFECT_PREFER_NO_SCHEDULE,
    MAX_PRIORITY,
    Node,
    Pod,
    Requirement,
)


def _match_expressions(node: Node, exprs: Sequence[Requirement]) -> bool:
    labels = node.labels
    for r in exprs:
        if r.operator == "In":
            if labels.get(r.key) not in r.values:
                return False
        elif r.operator == "NotIn":
            if r.key in labels and labels[r.key] in r.values:
                return False
        elif r.operator == "Exists":
            if r.key not in labels:
                return False
        elif r.operator == "DoesNotExist":
            if r.key in labels:
                return False
        elif r.operator in ("Gt", "Lt"):
            if r.key not in labels:
                return False
            try:
                v = int(labels[r.key])
            except ValueError:
                return False
            lit = int(r.values[0])
            if r.operator == "Gt" and not v > lit:
                return False
            if r.operator == "Lt" and not v < lit:
                return False
        else:
            raise ValueError(r.operator)
    return True


def pod_match_node_selector(pod: Pod, node: Node) -> bool:
    """predicates.go:904 PodMatchNodeSelector."""
    for k, v in pod.node_selector.items():
        if node.labels.get(k) != v:
            return False
    terms = pod.affinity.node_required
    if terms:
        return any(_term_matches(node, t) for t in terms)
    return True


def pod_fits_host(pod: Pod, node: Node) -> bool:
    """predicates.go:916 PodFitsHost."""
    return not pod.node_name or pod.node_name == node.name


def _term_matches(node: Node, term) -> bool:
    # empty term matches no objects (apimachinery helpers semantics)
    if not term.match_expressions:
        return False
    return _match_expressions(node, term.match_expressions)


def pod_fits_resources(pod: Pod, node: Node, node_pods: Sequence[Pod]) -> bool:
    """predicates.go:779 PodFitsResources."""
    if len(node_pods) + 1 > node.allocatable.pods:
        return False
    req = pod.requests
    if (
        req.cpu_milli == 0
        and req.memory == 0
        and req.ephemeral_storage == 0
        and not req.scalars
    ):
        # all-zero request short-circuits after the pod-count cap
        # (predicates.go:803-809)
        return True
    used_cpu = sum(p.requests.cpu_milli for p in node_pods)
    used_mem = sum(p.requests.memory for p in node_pods)
    used_eph = sum(p.requests.ephemeral_storage for p in node_pods)
    if node.allocatable.cpu_milli < req.cpu_milli + used_cpu:
        return False
    if node.allocatable.memory < req.memory + used_mem:
        return False
    if node.allocatable.ephemeral_storage < req.ephemeral_storage + used_eph:
        return False
    for name, q in req.scalars.items():
        used = sum(p.requests.scalars.get(name, 0) for p in node_pods)
        if node.allocatable.scalars.get(name, 0) < q + used:
            return False
    return True


def pod_tolerates_node_taints(pod: Pod, node: Node) -> bool:
    """predicates.go:1546 — only NoSchedule/NoExecute taints are checked."""
    for t in node.taints:
        if t.effect in (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE) and not pod.tolerates(t):
            return False
    return True


def pod_fits_host_ports(pod: Pod, node_pods: Sequence[Pod]) -> bool:
    """predicates.go:1084 + nodeinfo/host_ports.go conflict semantics."""
    existing: List[Tuple[str, str, int]] = []
    for p in node_pods:
        for proto, ip, port in p.host_ports:
            existing.append((proto, ip or "0.0.0.0", port))
    for proto, ip, port in pod.host_ports:
        ip = ip or "0.0.0.0"
        for eproto, eip, eport in existing:
            if proto == eproto and port == eport:
                if ip == "0.0.0.0" or eip == "0.0.0.0" or ip == eip:
                    return False
    return True


def feasible(pod: Pod, node: Node, node_pods: Sequence[Pod]) -> bool:
    return (
        node.conditions.ready
        and not node.conditions.network_unavailable
        and not node.unschedulable
        and not node.conditions.disk_pressure
        and not node.conditions.pid_pressure
        and not (
            node.conditions.memory_pressure
            and pod.requests.cpu_milli == 0
            and pod.requests.memory == 0
            and pod.requests.ephemeral_storage == 0
            and not pod.requests.scalars
        )
        and pod_tolerates_node_taints(pod, node)
        and pod_fits_host(pod, node)
        and pod_fits_host_ports(pod, node_pods)
        and pod_match_node_selector(pod, node)
        and pod_fits_resources(pod, node, node_pods)
    )


# -- inter-pod affinity / topology spread (predicates.go:1211,:1720) --------


def _term_matches_pod(defining_pod: Pod, term, target: Pod) -> bool:
    """PodMatchesTermsNamespaceAndSelector: empty namespaces default to the
    defining pod's namespace."""
    ns = term.namespaces or (defining_pod.namespace,)
    return target.namespace in ns and term.label_selector.matches(target.labels)


def _same_topology(a: Node, b: Node, key: str) -> bool:
    """priorityutil.NodesHaveSameTopologyKey."""
    return key in a.labels and key in b.labels and a.labels[key] == b.labels[key]


def _pod_has_affinity(p: Pod) -> bool:
    a = p.affinity
    return bool(
        a.pod_affinity_required
        or a.pod_anti_affinity_required
        or a.pod_affinity_preferred
        or a.pod_anti_affinity_preferred
    )


def inter_pod_affinity_feasible(
    pod: Pod, node: Node, nodes: Sequence[Node], node_pods: Dict[str, List[Pod]]
) -> bool:
    """InterPodAffinityMatches via the metadata path (merged pair maps)."""
    by_name = {nd.name: nd for nd in nodes}
    existing = [(e, by_name[n]) for n in node_pods for e in node_pods[n] if n in by_name]

    # satisfiesExistingPodsAntiAffinity: merged (key, value) pairs from
    # existing pods' required anti terms that match the incoming pod
    anti_pairs = set()
    for e, en in existing:
        for t in e.affinity.pod_anti_affinity_required:
            if _term_matches_pod(e, t, pod):
                v = en.labels.get(t.topology_key)
                if v is not None:
                    anti_pairs.add((t.topology_key, v))
    for k, v in node.labels.items():
        if (k, v) in anti_pairs:
            return False

    aff_terms = pod.affinity.pod_affinity_required
    if aff_terms:
        pairs = set()
        for e, en in existing:
            for t in aff_terms:
                if _term_matches_pod(pod, t, e):
                    v = en.labels.get(t.topology_key)
                    if v is not None:
                        pairs.add((t.topology_key, v))
        match_all = all(
            t.topology_key in node.labels
            and (t.topology_key, node.labels[t.topology_key]) in pairs
            for t in aff_terms
        )
        if not match_all:
            self_ok = all(_term_matches_pod(pod, t, pod) for t in aff_terms)
            if not (len(pairs) == 0 and self_ok):
                return False

    anti_terms = pod.affinity.pod_anti_affinity_required
    if anti_terms:
        pairs = set()
        for e, en in existing:
            for t in anti_terms:
                if _term_matches_pod(pod, t, e):
                    v = en.labels.get(t.topology_key)
                    if v is not None:
                        pairs.add((t.topology_key, v))
        for t in anti_terms:
            v = node.labels.get(t.topology_key)
            if v is not None and (t.topology_key, v) in pairs:
                return False
    return True


def even_pods_spread_feasible(
    pod: Pod, node: Node, nodes: Sequence[Node], node_pods: Dict[str, List[Pod]]
) -> bool:
    """EvenPodsSpreadPredicate via getTPMapMatchingSpreadConstraints."""
    constraints = [c for c in pod.topology_spread if c.when_unsatisfiable == "DoNotSchedule"]
    if not constraints:
        return True

    def candidate(nd: Node) -> bool:
        return pod_match_node_selector(pod, nd) and all(
            c.topology_key in nd.labels for c in constraints
        )

    # pair -> SET of pods (union across same-key constraints, metadata.go
    # addTopologyPair uses a pod set)
    pair_pods: Dict[Tuple[str, str], set] = {}
    for nd in nodes:
        if not candidate(nd):
            continue
        for c in constraints:
            pair = (c.topology_key, nd.labels[c.topology_key])
            s = pair_pods.setdefault(pair, set())
            for e in node_pods.get(nd.name, []):
                if e.namespace == pod.namespace and c.label_selector.matches(e.labels):
                    s.add((e.namespace, e.name))
    min_match: Dict[str, int] = {}
    for (k, _v), s in pair_pods.items():
        if k not in min_match or len(s) < min_match[k]:
            min_match[k] = len(s)

    for c in constraints:
        v = node.labels.get(c.topology_key)
        if v is None:
            return False
        if c.topology_key not in min_match:
            continue  # MaxInt32 sentinel: skew can't exceed
        self_match = 1 if c.label_selector.matches(pod.labels) else 0
        match_num = len(pair_pods.get((c.topology_key, v), set()))
        if match_num + self_match - min_match[c.topology_key] > c.max_skew:
            return False
    return True


def interpod_affinity_scores(
    pods: Sequence[Pod],
    nodes: Sequence[Node],
    node_pods: Dict[str, List[Pod]],
    feasible_mask,
    hard_weight: float = 1.0,
) -> List[List[int]]:
    """CalculateInterPodAffinityPriority with full symmetry."""
    by_name = {nd.name: nd for nd in nodes}
    existing = [(e, by_name[n]) for n in node_pods for e in node_pods[n] if n in by_name]
    out = []
    for i, pod in enumerate(pods):
        has_aff = _pod_has_affinity(pod)
        counted = {
            nd.name
            for nd in nodes
            if has_aff or any(_pod_has_affinity(e) for e in node_pods.get(nd.name, []))
        }
        counts: Dict[str, float] = {n: 0.0 for n in counted}
        for e, en in existing:
            for nd in nodes:
                if nd.name not in counts:
                    continue
                a = pod.affinity
                for wt in a.pod_affinity_preferred:
                    if _term_matches_pod(pod, wt.term, e) and _same_topology(nd, en, wt.term.topology_key):
                        counts[nd.name] += wt.weight
                for wt in a.pod_anti_affinity_preferred:
                    if _term_matches_pod(pod, wt.term, e) and _same_topology(nd, en, wt.term.topology_key):
                        counts[nd.name] -= wt.weight
                ea = e.affinity
                for t in ea.pod_affinity_required:
                    if hard_weight > 0 and _term_matches_pod(e, t, pod) and _same_topology(nd, en, t.topology_key):
                        counts[nd.name] += hard_weight
                for wt in ea.pod_affinity_preferred:
                    if _term_matches_pod(e, wt.term, pod) and _same_topology(nd, en, wt.term.topology_key):
                        counts[nd.name] += wt.weight
                for wt in ea.pod_anti_affinity_preferred:
                    if _term_matches_pod(e, wt.term, pod) and _same_topology(nd, en, wt.term.topology_key):
                        counts[nd.name] -= wt.weight
        idx = [j for j in range(len(nodes)) if feasible_mask[i][j] and nodes[j].name in counts]
        mx = max([counts[nodes[j].name] for j in idx], default=0.0)
        mn = min([counts[nodes[j].name] for j in idx], default=0.0)
        mx, mn = max(mx, 0.0), min(mn, 0.0)
        row = [0] * len(nodes)
        for j in range(len(nodes)):
            if nodes[j].name in counts and mx - mn > 0:
                row[j] = int(MAX_PRIORITY * (counts[nodes[j].name] - mn) / (mx - mn))
        out.append(row)
    return out


def even_pods_spread_scores(
    pods: Sequence[Pod],
    nodes: Sequence[Node],
    node_pods: Dict[str, List[Pod]],
    feasible_mask,
) -> List[List[int]]:
    """CalculateEvenPodsSpreadPriority (even_pods_spread.go:86)."""
    out = []
    for i, pod in enumerate(pods):
        constraints = [c for c in pod.topology_spread if c.when_unsatisfiable == "ScheduleAnyway"]
        row = [0] * len(nodes)
        if not constraints:
            out.append(row)
            continue
        filtered = [nodes[j] for j in range(len(nodes)) if feasible_mask[i][j]]
        keyed = lambda nd: all(c.topology_key in nd.labels for c in constraints)
        # initialize(): eligibility + pair init from filtered keyed nodes
        eligible = {nd.name for nd in filtered if keyed(nd)}
        pair_counts: Dict[Tuple[str, str], float] = {}
        for nd in filtered:
            if keyed(nd):
                for c in constraints:
                    pair_counts.setdefault((c.topology_key, nd.labels[c.topology_key]), 0.0)
        # processAllNode: count from ALL selector-passing keyed nodes
        for nd in nodes:
            if not (pod_match_node_selector(pod, nd) and keyed(nd)):
                continue
            for c in constraints:
                pair = (c.topology_key, nd.labels[c.topology_key])
                if pair not in pair_counts:
                    continue
                pair_counts[pair] += sum(
                    1 for e in node_pods.get(nd.name, [])
                    if c.label_selector.matches(e.labels)  # NO namespace check
                )
        node_counts: Dict[str, float] = {}
        total = 0.0
        for nd in nodes:
            if nd.name not in eligible:
                continue
            s = 0.0
            for c in constraints:
                v = nd.labels.get(c.topology_key)
                if v is not None:
                    s += pair_counts.get((c.topology_key, v), 0.0)
            node_counts[nd.name] = s
            total += s
        min_count = min(node_counts.values(), default=0.0)
        diff = total - min_count
        for j, nd in enumerate(nodes):
            if nd.name not in node_counts:
                continue
            if diff == 0:
                row[j] = MAX_PRIORITY
            else:
                row[j] = int(MAX_PRIORITY * (total - node_counts[nd.name]) / diff)
        out.append(row)
    return out


# -- priorities -------------------------------------------------------------


def _nonzero_used(node_pods: Sequence[Pod]) -> Tuple[float, float]:
    cpu = sum(p.nonzero_requests()[0] for p in node_pods)
    mem = sum(p.nonzero_requests()[1] for p in node_pods)
    return cpu, mem


def least_requested_score(pod: Pod, node: Node, node_pods: Sequence[Pod]) -> int:
    """least_requested.go: int truncation preserved."""
    ucpu, umem = _nonzero_used(node_pods)
    pcpu, pmem = pod.nonzero_requests()
    rc, rm = ucpu + pcpu, umem + pmem

    def score(req, cap):
        if cap == 0 or req > cap:
            return 0
        return int((cap - req) * MAX_PRIORITY // cap)

    return (
        score(rc, node.allocatable.cpu_milli) + score(rm, node.allocatable.memory)
    ) // 2


def most_requested_score(pod: Pod, node: Node, node_pods: Sequence[Pod]) -> int:
    """most_requested.go: (requested * 10 / capacity), capped."""
    ucpu, umem = _nonzero_used(node_pods)
    pcpu, pmem = pod.nonzero_requests()
    rc, rm = ucpu + pcpu, umem + pmem

    def score(req, cap):
        if cap == 0 or req > cap:
            return 0
        return int(req * MAX_PRIORITY // cap)

    return (score(rc, node.allocatable.cpu_milli) + score(rm, node.allocatable.memory)) // 2


def balanced_allocation_score(pod: Pod, node: Node, node_pods: Sequence[Pod]) -> int:
    """balanced_resource_allocation.go (two-resource form)."""
    ucpu, umem = _nonzero_used(node_pods)
    pcpu, pmem = pod.nonzero_requests()
    rc, rm = ucpu + pcpu, umem + pmem
    cf = rc / node.allocatable.cpu_milli if node.allocatable.cpu_milli else 1.0
    mf = rm / node.allocatable.memory if node.allocatable.memory else 1.0
    if cf >= 1 or mf >= 1:
        return 0
    return int((1 - abs(cf - mf)) * MAX_PRIORITY)


def taint_toleration_scores(
    pods: Sequence[Pod], nodes: Sequence[Node], feasible_mask
) -> List[List[int]]:
    """taint_toleration.go: count intolerable PreferNoSchedule taints over
    the pod's *feasible* nodes, then NormalizeReduce(max=10, reverse=true)."""
    out = []
    for i, pod in enumerate(pods):
        idx = [j for j in range(len(nodes)) if feasible_mask[i][j]]
        counts = {}
        for j in idx:
            c = 0
            for t in nodes[j].taints:
                if t.effect == EFFECT_PREFER_NO_SCHEDULE and not pod.tolerates(t):
                    c += 1
            counts[j] = c
        mx = max(counts.values(), default=0)
        row = [0] * len(nodes)
        for j in idx:
            if mx == 0:
                row[j] = MAX_PRIORITY
            else:
                row[j] = MAX_PRIORITY - (counts[j] * MAX_PRIORITY // mx)
        out.append(row)
    return out


def node_affinity_scores(
    pods: Sequence[Pod], nodes: Sequence[Node], feasible_mask
) -> List[List[int]]:
    """node_affinity.go: weight-sum of matched preferred terms over feasible
    nodes, then NormalizeReduce(max=10, reverse=false)."""
    out = []
    for i, pod in enumerate(pods):
        idx = [j for j in range(len(nodes)) if feasible_mask[i][j]]
        raw = {}
        for j in idx:
            s = 0
            for p in pod.affinity.node_preferred:
                if p.weight and _match_expressions(nodes[j], p.preference.match_expressions):
                    s += p.weight
            raw[j] = s
        mx = max(raw.values(), default=0)
        row = [0] * len(nodes)
        for j in idx:
            row[j] = raw[j] * MAX_PRIORITY // mx if mx else 0
        out.append(row)
    return out


def selector_spread_scores(
    pods: Sequence[Pod],
    nodes: Sequence[Node],
    node_pods: Dict[str, List[Pod]],
    feasible_mask,
) -> List[List[float]]:
    """selector_spreading.go map+reduce over each pod's feasible nodes."""
    out = []
    for i, pod in enumerate(pods):
        idx = [j for j in range(len(nodes)) if feasible_mask[i][j]]
        counts = {}
        for j in idx:
            nd = nodes[j]
            c = 0
            if pod.spread_selectors:
                for q in node_pods[nd.name]:
                    if q.namespace == pod.namespace and all(
                        s.matches(q.labels) for s in pod.spread_selectors
                    ):
                        c += 1
            counts[j] = c
        max_node = max(counts.values(), default=0)
        zcounts: Dict[Tuple[str, str], int] = {}
        for j in idx:
            zk = nodes[j].zone_key()
            if zk is not None:
                zcounts[zk] = zcounts.get(zk, 0) + counts[j]
        max_zone = max(zcounts.values(), default=0)
        have_zones = len(zcounts) > 0
        row = [0.0] * len(nodes)
        for j in idx:
            f = float(MAX_PRIORITY)
            if max_node > 0:
                f = MAX_PRIORITY * (max_node - counts[j]) / max_node
            zk = nodes[j].zone_key()
            if have_zones and zk is not None:
                zs = float(MAX_PRIORITY)
                if max_zone > 0:
                    zs = MAX_PRIORITY * (max_zone - zcounts[zk]) / max_zone
                f = f * (1.0 / 3.0) + zs * (2.0 / 3.0)
            row[j] = float(int(f))
        out.append(row)
    return out


def image_locality_scores(pods: Sequence[Pod], nodes: Sequence[Node]) -> List[List[int]]:
    """image_locality.go with meta.totalNumNodes = len(nodes)."""
    mb = 1024 * 1024
    lo, hi = 23 * mb, 1000 * mb
    total = len(nodes)
    num_nodes = {}
    for nd in nodes:
        for img in nd.images:
            num_nodes[img] = num_nodes.get(img, 0) + 1
    out = []
    for pod in pods:
        row = []
        for nd in nodes:
            s = 0
            for img in pod.images:
                if img in nd.images:
                    spread = num_nodes[img] / total
                    s += int(nd.images[img] * spread)
            s = min(max(s, lo), hi)
            row.append(int(MAX_PRIORITY * (s - lo) // (hi - lo)))
        out.append(row)
    return out


def prefer_avoid_scores(pods: Sequence[Pod], nodes: Sequence[Node]) -> List[List[int]]:
    """node_prefer_avoid_pods.go."""
    return [
        [
            0 if pod.owner_uid and pod.owner_uid in nd.prefer_avoid_owner_uids else MAX_PRIORITY
            for nd in nodes
        ]
        for pod in pods
    ]


DEFAULT_WEIGHTS = {
    "SelectorSpreadPriority": 1,
    "LeastRequestedPriority": 1,
    "BalancedResourceAllocation": 1,
    "NodePreferAvoidPodsPriority": 10000,
    "NodeAffinityPriority": 1,
    "TaintTolerationPriority": 1,
    "ImageLocalityPriority": 1,
}


def serial_schedule(
    pending: Sequence[Pod],
    nodes: Sequence[Node],
    scheduled: Sequence[Pod],
) -> List[Tuple[int, float]]:
    """The reference's serial driver loop (scheduler.go:462 scheduleOne):
    pods in activeQ order (priority desc, arrival asc), each scoring the
    cluster as it stands, argmax with lowest-index tie-break. Returns
    (node_index or -1, winning score) per pod, in the original pod order.
    Base predicates/priorities only; :func:`serial_schedule_full` adds the
    topology + volume surface over the same loop."""
    return _serial_schedule(pending, nodes, scheduled, full=False,
                            vol_state=None)


def serial_schedule_full(
    pending: Sequence[Pod],
    nodes: Sequence[Node],
    scheduled: Sequence[Pod],
    vol_state=None,
) -> List[Tuple[int, float]]:
    """:func:`serial_schedule` with the FULL default surface — inter-pod
    affinity, topology spread, and (when ``vol_state`` is given) the five
    volume predicates — the end-to-end oracle for the differential fuzz
    campaign (SURVEY §4 implication (a)). Metadata is recomputed per pod
    against the live node_pods state, exactly like scheduleOne's
    GetMetadata each cycle (predicates/metadata.go:152)."""
    return _serial_schedule(pending, nodes, scheduled, full=True,
                            vol_state=vol_state)


def _oracle_assume_volumes(pod: Pod, node: Node, state) -> None:
    """Mirror VolumeBinder.assume_pod_volumes' PV picks (volumes.py:332):
    after the oracle places a pod, unbound WaitForFirstConsumer claims take
    the first compatible available PV so later pods in the same run see it
    as spoken for — without this, delayed-binding PV capacity would be
    double-spent and the oracle would diverge from the driver's
    assume-then-commit flow."""
    from kubernetes_tpu.volumes import (
        BINDING_WAIT_FOR_FIRST_CONSUMER,
        match_node_selector_terms,
    )

    for v in pod.volumes:
        if not v.pvc:
            continue
        pvc = state.pvc(pod.namespace, v.pvc)
        if pvc is None or pvc.volume_name:
            continue
        sc = state.storage_class(pvc.storage_class) if pvc.storage_class else None
        if (sc is None or sc.binding_mode != BINDING_WAIT_FOR_FIRST_CONSUMER
                or sc.provisionable()):
            continue
        for pv in state.available_pvs(pvc.storage_class):
            if not pv.node_affinity or match_node_selector_terms(
                node.labels, pv.node_affinity
            ):
                state.assumed_claims[pv.name] = f"{pod.namespace}/{pvc.name}"
                break


def _serial_schedule(
    pending: Sequence[Pod],
    nodes: Sequence[Node],
    scheduled: Sequence[Pod],
    full: bool,
    vol_state,
) -> List[Tuple[int, float]]:
    """One shared loop for both oracles (the score blend and tie-break live
    HERE only). ``full`` adds interpod-affinity + spread feasibility and
    the InterPodAffinityPriority score (weight 1, defaults.go:119);
    ``vol_state`` adds the five volume predicates plus assume-tracking.
    Placed pods keep their full spec (dataclasses.replace) so later pods
    see their labels/affinity/volumes as existing state."""
    import dataclasses

    if vol_state is not None:
        # private assumed-claims ledger: the oracle mutates it as it places
        vol_state = dataclasses.replace(
            vol_state, assumed_claims=dict(vol_state.assumed_claims)
        )
    node_pods: Dict[str, List[Pod]] = {nd.name: [] for nd in nodes}
    for p in scheduled:
        if p.node_name in node_pods:
            node_pods[p.node_name].append(p)

    order = sorted(range(len(pending)), key=lambda i: (-pending[i].priority, i))
    out: List[Tuple[int, float]] = [(-1, 0.0)] * len(pending)
    for i in order:
        pod = pending[i]
        row = []
        for nd in nodes:
            ok = feasible(pod, nd, node_pods[nd.name])
            if ok and full:
                ok = (
                    inter_pod_affinity_feasible(pod, nd, nodes, node_pods)
                    and even_pods_spread_feasible(pod, nd, nodes, node_pods)
                )
            if ok and vol_state is not None:
                ok = volumes_feasible(pod, nd, node_pods[nd.name], vol_state)
            row.append(ok)
        if not any(row):
            continue
        mask = [row]
        w = DEFAULT_WEIGHTS
        taint = taint_toleration_scores([pod], nodes, mask)[0]
        aff = node_affinity_scores([pod], nodes, mask)[0]
        spread = selector_spread_scores([pod], nodes, node_pods, mask)[0]
        img = image_locality_scores([pod], nodes)[0]
        avoid = prefer_avoid_scores([pod], nodes)[0]
        ipa = (
            interpod_affinity_scores([pod], nodes, node_pods, mask)[0]
            if full
            else [0] * len(nodes)
        )
        best_j, best_s = -1, None
        for j, nd in enumerate(nodes):
            if not row[j]:
                continue
            s = (
                w["LeastRequestedPriority"] * least_requested_score(pod, nd, node_pods[nd.name])
                + w["BalancedResourceAllocation"] * balanced_allocation_score(pod, nd, node_pods[nd.name])
                + w["TaintTolerationPriority"] * taint[j]
                + w["NodeAffinityPriority"] * aff[j]
                + w["SelectorSpreadPriority"] * spread[j]
                + w["ImageLocalityPriority"] * img[j]
                + w["NodePreferAvoidPodsPriority"] * avoid[j]
                + ipa[j]  # InterPodAffinityPriority weight 1 (defaults.go:119)
            )
            if best_s is None or s > best_s:
                best_j, best_s = j, s
        placed = dataclasses.replace(pod, node_name=nodes[best_j].name)
        node_pods[nodes[best_j].name].append(placed)
        if vol_state is not None:
            _oracle_assume_volumes(placed, nodes[best_j], vol_state)
        out[i] = (best_j, float(best_s))
    return out


# -- volume predicates (predicates.go:275,:404,:632,:1666; csi_volume_ -------
# predicate.go:54) — sequential oracles over the same VolumeState model


def _resolved(pod: Pod, state):
    """``state`` is either a VolumeState or a cached resolver callable
    (e.g. SnapshotPacker.resolve_volumes) — preemption what-ifs re-check
    the same pods many times, so the driver passes the memoized form."""
    if callable(state):
        return state(pod)
    from kubernetes_tpu.volumes import resolve_pod_volumes

    return resolve_pod_volumes(pod, state)


def no_disk_conflict(pod: Pod, node_pods: Sequence[Pod], state) -> bool:
    """NoDiskConflict (predicates.go:275): inline GCE-PD/EBS/RBD/ISCSI
    volumes vs volumes of pods already on the node; read-only mounts escape
    for every kind but EBS (isVolumeConflict :216)."""
    from kubernetes_tpu.volumes import CONFLICT_RO_ESCAPE

    mine = _resolved(pod, state).conflict
    for ep in node_pods:
        theirs = _resolved(ep, state).conflict
        for kind, handle, ro in mine:
            for ekind, ehandle, ero in theirs:
                if kind == ekind and handle == ehandle:
                    if not (CONFLICT_RO_ESCAPE[kind] and ro and ero):
                        return False
    return True


def max_pd_volume_count(
    pod: Pod, node: Node, node_pods: Sequence[Pod], state
) -> bool:
    """All four MaxPDVolumeCountChecker instances (predicates.go:404)."""
    from kubernetes_tpu.volumes import N_PD_FILTERS, node_pd_limits

    limits = node_pd_limits(node)
    new = _resolved(pod, state).pd
    if not new:
        return True
    existing: set = set()
    for ep in node_pods:
        existing.update(_resolved(ep, state).pd)
    for t in range(N_PD_FILTERS):
        if not any(v[0] == t for v in new):
            continue  # this checker quick-returns (predicates.go:471)
        n_existing = sum(1 for e in existing if e[0] == t)
        n_new = sum(1 for v in set(new) if v[0] == t and v not in existing)
        if n_existing + n_new > limits[t]:
            return False
    return True


def csi_max_volume_count(
    pod: Pod, node: Node, node_pods: Sequence[Pod], state
) -> bool:
    """CSIMaxVolumeLimitChecker (csi_volume_predicate.go:54)."""
    from kubernetes_tpu.volumes import CSI_LIMIT_PREFIX

    new = set(_resolved(pod, state).csi)
    if not new:
        return True
    existing: set = set()
    for ep in node_pods:
        existing.update(_resolved(ep, state).csi)
    new -= existing
    drivers = {d for d, _ in new} | {d for d, _ in existing}
    for d in drivers:
        limit = node.allocatable.scalars.get(CSI_LIMIT_PREFIX + d)
        if limit is None:
            continue
        cur = sum(1 for e in existing if e[0] == d)
        add = sum(1 for v in new if v[0] == d)
        if add and cur + add > limit:
            return False
    return True


def volume_zone(pod: Pod, node: Node, state) -> Tuple[bool, bool]:
    """NoVolumeZoneConflict (predicates.go:632). Returns (ok, error)."""
    from kubernetes_tpu.volumes import node_has_zone_label

    rv = _resolved(pod, state)
    if rv.error:
        return False, True
    if not node_has_zone_label(node):
        return True, False
    for key, allowed in rv.zone_rows:
        if node.labels.get(key, "") not in allowed:
            return False, False
    return True, False


def volume_binding(pod: Pod, node: Node, state) -> Tuple[bool, bool, bool]:
    """CheckVolumeBinding (predicates.go:1666 -> FindPodVolumes).
    Returns (bound_satisfied, unbound_satisfied, error)."""
    rv = _resolved(pod, state)
    if rv.error:
        return False, False, True
    bound_ok = True
    for terms in rv.bound_affinity:
        if not any(
            t.match_expressions and _match_expressions(node, t.match_expressions)
            for t in terms
        ):
            bound_ok = False
    unbound_ok = True
    for cands in rv.unbound_clauses:
        satisfied = False
        for terms in cands:
            if not terms or any(
                t.match_expressions and _match_expressions(node, t.match_expressions)
                for t in terms
            ):
                satisfied = True
                break
        if not satisfied:
            unbound_ok = False
    return bound_ok, unbound_ok, False


def volumes_feasible(
    pod: Pod, node: Node, node_pods: Sequence[Pod], state
) -> bool:
    """AND of all five volume predicates (the default-provider volume set,
    defaults.go:40)."""
    vz_ok, vz_err = volume_zone(pod, node, state)
    b_ok, u_ok, vb_err = volume_binding(pod, node, state)
    return (
        not vz_err
        and not vb_err
        and vz_ok
        and b_ok
        and u_ok
        and no_disk_conflict(pod, node_pods, state)
        and max_pd_volume_count(pod, node, node_pods, state)
        and csi_max_volume_count(pod, node, node_pods, state)
    )


# -- RequestedToCapacityRatio / NodeLabel / ResourceLimits priorities --------
# (requested_to_capacity_ratio.go, node_label.go, resource_limits.go)


def _go_div(a: int, b: int) -> int:
    """Go int64 division: truncation toward zero."""
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


def broken_linear(shape) -> "callable":
    """buildBrokenLinearFunction (requested_to_capacity_ratio.go:110)."""
    def f(p: int) -> int:
        n = len(shape)
        for i in range(n):
            if p <= shape[i][0]:
                if i == 0:
                    return shape[0][1]
                x0, y0 = shape[i - 1]
                x1, y1 = shape[i]
                return y0 + _go_div((y1 - y0) * (p - x0), (x1 - x0))
        return shape[n - 1][1]

    return f


def requested_to_capacity_score(
    pod: Pod, node: Node, node_pods: Sequence[Pod],
    shape=((0, 10), (100, 0)),
) -> int:
    """RequestedToCapacityRatioResourceAllocationPriority scorer
    (requested_to_capacity_ratio.go:87-103) on exact integer math."""
    raw = broken_linear(shape)

    def one(req: int, cap: int) -> int:
        if cap == 0 or req > cap:
            return raw(100)
        return raw(100 - _go_div((cap - req) * 100, cap))

    used_cpu, used_mem = _nonzero_used(node_pods)
    p_cpu, p_mem = pod.nonzero_requests()
    cpu = one(int(used_cpu + p_cpu), int(node.allocatable.cpu_milli))
    mem = one(int(used_mem + p_mem), int(node.allocatable.memory))
    return _go_div(cpu + mem, 2)


def node_label_score(node: Node, label: str, presence: bool) -> int:
    """NodeLabelPriority (node_label.go:47)."""
    exists = label in node.labels
    return MAX_PRIORITY if exists == presence else 0


def resource_limits_score(pod: Pod, node: Node) -> int:
    """ResourceLimitsPriority (resource_limits.go:44): 1 when a declared
    cpu OR memory limit fits within allocatable."""
    cpu_ok = 0 < pod.limits.cpu_milli <= node.allocatable.cpu_milli
    mem_ok = 0 < pod.limits.memory <= node.allocatable.memory
    return 1 if (cpu_ok or mem_ok) else 0
