"""Hollow-cluster simulation — the kubemark analog (SURVEY.md §4 item d:
"hollow-node-style simulation for end-to-end queue dynamics: churn,
backoff, preemption").

Where kubemark runs real kubelets with fake runtimes against a real
control plane, this harness runs the real scheduler (queue, cache,
solvers, preemption, volume state) against a simulated hub that owns the
source of truth and feeds the scheduler's event handlers exactly like an
informer pump: pod/node create/delete churn, flaky bindings, node
flapping, replica controllers maintaining workloads. The cache-vs-truth
comparer (``debugger.compare``) is the consistency oracle after every
step."""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.debugger import compare
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import make_node, make_pod


class SimClock:
    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FlakyBinder:
    """Binder whose RPC fails with probability ``fail_rate`` — exercising
    the Forget-and-requeue path (scheduler.go:447)."""

    def __init__(self, hub: "HollowCluster", fail_rate: float, rng) -> None:
        self.hub = hub
        self.fail_rate = fail_rate
        self.rng = rng
        self.failures = 0

    def bind(self, pod: Pod, node_name: str) -> None:
        if self.rng.random() < self.fail_rate:
            self.failures += 1
            raise RuntimeError("simulated bind RPC failure")
        self.hub.confirm_binding(pod, node_name)


@dataclass
class ReplicaSet:
    """A hollow controller: keeps ``replicas`` pods named ``{name}-i``
    alive (recreating deleted ones with fresh uids), the way the
    replicaset controller reconciles."""

    name: str
    replicas: int
    cpu_milli: float = 100
    memory: float = 256 * 2**20
    priority: int = 0
    next_idx: int = 0
    live: Dict[str, Pod] = field(default_factory=dict)


class HollowCluster:
    """Owns the truth (pods/nodes) and pumps watch events at the scheduler.
    All scheduler interaction goes through the event-handler surface, like
    the reference's AddAllEventHandlers wiring."""

    def __init__(
        self,
        seed: int = 0,
        bind_fail_rate: float = 0.0,
        scheduler_kw: Optional[dict] = None,
    ) -> None:
        self.rng = random.Random(seed)
        self.clock = SimClock()
        self.truth_pods: Dict[str, Pod] = {}  # key -> pod (node_name = truth)
        self.truth_nodes: Dict[str, Node] = {}
        self.replicasets: Dict[str, ReplicaSet] = {}
        self.binder = FlakyBinder(self, bind_fail_rate, self.rng)
        self.sched = Scheduler(
            binder=self.binder, clock=self.clock, **(scheduler_kw or {})
        )
        self.bound_total = 0

    # -- truth mutations (each pumps the corresponding watch event) --------

    def add_node(self, node: Node) -> None:
        self.truth_nodes[node.name] = node
        self.sched.on_node_add(node)

    def remove_node(self, name: str) -> None:
        """Node vanishes; its pods are lost and deleted by the hub (the
        node-lifecycle/GC path, heavily simplified)."""
        self.truth_nodes.pop(name, None)
        for key, p in list(self.truth_pods.items()):
            if p.node_name == name:
                self.delete_pod(key)
        self.sched.on_node_delete(name)

    def create_pod(self, pod: Pod) -> None:
        self.truth_pods[pod.key()] = pod
        self.sched.on_pod_add(pod)

    def delete_pod(self, key: str) -> None:
        pod = self.truth_pods.pop(key, None)
        if pod is not None:
            self.sched.on_pod_delete(pod)
            for rs in self.replicasets.values():
                rs.live.pop(key, None)

    def confirm_binding(self, pod: Pod, node_name: str) -> None:
        """The apiserver accepted the binding: truth updates and the watch
        event confirms the scheduler's assumption."""
        old = self.truth_pods[pod.key()]
        import dataclasses

        new = dataclasses.replace(old, node_name=node_name)
        self.truth_pods[pod.key()] = new
        self.bound_total += 1
        self.sched.on_pod_update(old, new)

    # -- controllers / churn ------------------------------------------------

    def add_replicaset(self, rs: ReplicaSet) -> None:
        self.replicasets[rs.name] = rs

    def reconcile_controllers(self) -> None:
        for rs in self.replicasets.values():
            while len(rs.live) < rs.replicas:
                name = f"{rs.name}-{rs.next_idx}"
                rs.next_idx += 1
                pod = make_pod(
                    name,
                    cpu_milli=rs.cpu_milli,
                    memory=rs.memory,
                    priority=rs.priority,
                    labels={"rs": rs.name},
                )
                pod.uid = f"{name}#{rs.next_idx}"
                rs.live[pod.key()] = pod
                self.create_pod(pod)

    def churn(self, kill_pods: int = 0, flap_nodes: int = 0) -> None:
        """Random disruption: delete bound pods, bounce nodes."""
        bound = [k for k, p in self.truth_pods.items() if p.node_name]
        for key in self.rng.sample(bound, min(kill_pods, len(bound))):
            self.delete_pod(key)
        names = list(self.truth_nodes)
        for name in self.rng.sample(names, min(flap_nodes, len(names))):
            self.remove_node(name)

    # -- run ----------------------------------------------------------------

    def step(self, dt: float = 15.0):
        """One sim tick: reconcile controllers, run a scheduling cycle,
        advance time (so backoffs expire across ticks)."""
        self.reconcile_controllers()
        res = self.sched.schedule_cycle()
        self.clock.advance(dt)
        return res

    def check_consistency(self) -> None:
        """Invariants after any step:
        - cache matches truth (comparer),
        - no node over-committed in truth (cpu/memory/pod count),
        - every truth-bound pod landed on a live node."""
        truth = {k: p.node_name for k, p in self.truth_pods.items()}
        node_diffs, pod_diffs = compare(self.sched, truth, list(self.truth_nodes))
        assert not node_diffs, f"cache/truth node diffs: {node_diffs}"
        assert not pod_diffs, f"cache/truth pod diffs: {pod_diffs}"
        by_node: Dict[str, List[Pod]] = {}
        for p in self.truth_pods.values():
            if p.node_name:
                assert p.node_name in self.truth_nodes, (
                    f"{p.key()} bound to dead node {p.node_name}"
                )
                by_node.setdefault(p.node_name, []).append(p)
        for name, pods in by_node.items():
            nd = self.truth_nodes[name]
            cpu = sum(p.requests.cpu_milli for p in pods)
            mem = sum(p.requests.memory for p in pods)
            assert cpu <= nd.allocatable.cpu_milli + 1e-6, f"{name} cpu overcommit"
            assert mem <= nd.allocatable.memory + 1e-6, f"{name} mem overcommit"
            assert len(pods) <= nd.allocatable.pods, f"{name} pod-count overcommit"

    def pending_count(self) -> int:
        return sum(1 for p in self.truth_pods.values() if not p.node_name)
