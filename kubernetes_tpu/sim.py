"""Hollow-cluster simulation — the kubemark analog (SURVEY.md §4 item d:
"hollow-node-style simulation for end-to-end queue dynamics: churn,
backoff, preemption").

Where kubemark runs real kubelets with fake runtimes against a real
control plane, this harness runs the real scheduler (queue, cache,
solvers, preemption, volume state) against a simulated hub that owns the
source of truth and feeds the scheduler's event handlers exactly like an
informer pump: pod/node create/delete churn, flaky bindings, node
flapping, replica controllers maintaining workloads. The cache-vs-truth
comparer (``debugger.compare``) is the consistency oracle after every
step.

The hub is an optimistic-concurrency store, not a plain dict (the single
most important architectural fact of the reference, SURVEY.md §1):

- every object write bumps a global revision and the object's
  resourceVersion (etcd3/store.go:236 GuaranteedUpdate);
- the Binding subresource is a CAS: it fails with :class:`Conflict` if
  the pod is gone, was recreated (uid mismatch), or already has a node
  (registry/core/pod/storage/storage.go:154 BindingREST.Create →
  assignPod);
- watch events can be DELAYED (``event_delay_ticks``): the scheduler then
  acts on stale state and its writes hit conflicts, exactly like a real
  informer lagging etcd — per-object event order is always preserved,
  like a real watch stream;
- a competing writer (``competing_bind_rate``) binds pending pods behind
  the scheduler's back — the HA-peer / external-controller race.
"""

from __future__ import annotations

import bisect
import heapq
import random
import threading
import weakref
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.admission import (
    NS_ACTIVE,
    NS_TERMINATING,
    AdmissionError,
    Namespace,
    QuotaController,
    default_chain,
)
from kubernetes_tpu.api.types import (
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    Node,
    OwnerReference,
    Pod,
    Taint,
    Toleration,
)
from kubernetes_tpu.cloud import CloudNodeController
from kubernetes_tpu.debugger import compare
from kubernetes_tpu.proxy import (
    ClusterIPAllocator,
    EndpointsController,
    NodePortAllocator,
    ServiceProxy,
)
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.testing import (
    make_node,
    make_pod,
    node_affinity_required,
    req,
)


class Conflict(Exception):
    """Optimistic-concurrency write rejection (apierrors.IsConflict)."""


class Compacted(Exception):
    """Watch cursor fell behind the compaction floor — the etcd
    ErrCompacted ("required revision has been compacted") that forces a
    client-go Reflector relist (reflector.go ListAndWatch error path)."""


class SimClock:
    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FlakyBinder:
    """Binder whose RPC fails with probability ``fail_rate`` — exercising
    the Forget-and-requeue path (scheduler.go:447). Hub-side CAS
    rejections (:class:`Conflict`) propagate through the same surface."""

    def __init__(self, hub: "HollowCluster", fail_rate: float, rng) -> None:
        self.hub = hub
        self.fail_rate = fail_rate
        self.rng = rng
        self.failures = 0
        self.conflicts = 0

    def bind(self, pod: Pod, node_name: str) -> None:
        if self.rng.random() < self.fail_rate:
            self.failures += 1
            raise RuntimeError("simulated bind RPC failure")
        try:
            self.hub.confirm_binding(pod, node_name)
        except Conflict:
            self.conflicts += 1
            raise


@dataclass
class ReplicaSet:
    """A hollow controller: keeps ``replicas`` pods named ``{name}-i``
    alive (recreating deleted ones with fresh uids), the way the
    replicaset controller reconciles."""

    name: str
    replicas: int
    cpu_milli: float = 100
    memory: float = 256 * 2**20
    priority: int = 0
    next_idx: int = 0
    live: Dict[str, Pod] = field(default_factory=dict)
    #: owning Deployment name ("" = standalone) — the ownerReference the
    #: GC pass consults (never inferred from the name)
    owner: str = ""
    #: owning Deployment's template revision this RS realizes (the
    #: pod-template-hash analog); orders old RSes during a rollout
    revision: int = 0
    #: "ReplicaSet" or "ReplicationController" — the reference's RC
    #: controller IS the ReplicaSet controller behind conversion
    #: adapters (pkg/controller/replication/replication_controller.go:58
    #: wraps replicaset.NewBaseController); the kind only changes the
    #: ownerReference stamped on pods and the API group it serves under
    kind: str = "ReplicaSet"


@dataclass
class ServiceAccount:
    """v1.ServiceAccount slice: the identity object the serviceaccounts
    controller guarantees per namespace and the tokens controller mints
    credentials for (pkg/controller/serviceaccount)."""

    name: str
    namespace: str = "default"

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Attachment:
    """Attach-detach controller actual-state record
    (volume/attachdetach/cache/actual_state_of_world.go): one volume
    attached to one node; ``detaching`` + ``detach_at`` model the
    grace window before the reconciler issues the detach."""

    volume: str
    node: str
    state: str = "attached"  # "attached" | "detaching"
    detach_at: float = 0.0


@dataclass
class Deployment:
    """Hollow deployment controller (pkg/controller/deployment): one
    ReplicaSet per template revision. A template change (:meth:`rollout`)
    bumps the revision; the sync then surges the new RS up and drains the
    old ones under the maxSurge/maxUnavailable budget — the RollingUpdate
    reconciliation of rolling.go:31 (reconcileNewReplicaSet /
    reconcileOldReplicaSets), with "available" = bound in this hollow
    world. ``max_surge``/``max_unavailable`` take ints or "25%" strings
    (intstr.GetValueFromIntOrPercent: surge rounds up, unavailable
    rounds down)."""

    name: str
    replicas: int
    cpu_milli: float = 100
    memory: float = 256 * 2**20
    priority: int = 0
    #: "RollingUpdate" (default) or "Recreate" (deployment strategy,
    #: apps/v1 DeploymentStrategy: Recreate kills ALL old pods before
    #: any new one exists — downtime traded for never-mixed versions)
    strategy: str = "RollingUpdate"
    max_surge: object = 1
    max_unavailable: object = 1
    template_rev: int = 0

    def __post_init__(self):
        # apps/v1 validation rejects unknown strategy values; a typo'd
        # "recreate" silently rolling (and MIXING versions) would be the
        # exact failure Recreate exists to prevent
        if self.strategy not in ("RollingUpdate", "Recreate"):
            raise ValueError(
                f"Deployment.strategy must be 'RollingUpdate' or "
                f"'Recreate', got {self.strategy!r}"
            )
        # apps/v1 validation also rejects maxSurge=0 AND maxUnavailable=0
        # (validation.go ValidateDeploymentStrategy) — but only as
        # LITERAL values: a percentage that merely rounds to 0 at the
        # current replica count is legal there and coerced at sync time
        # (ResolveFenceposts), so the constructor matches that split
        if self.strategy == "RollingUpdate":
            def _literal_zero(v):
                return v in (0, "0", "0%")

            if _literal_zero(self.max_surge) and _literal_zero(
                    self.max_unavailable):
                raise ValueError(
                    "Deployment maxSurge and maxUnavailable cannot both "
                    "be 0 (the rollout could never progress)"
                )

    def rs_name(self) -> str:
        """Name of the CURRENT revision's ReplicaSet."""
        return f"{self.name}-rs-{self.template_rev}"

    def rollout(self, cpu_milli=None, memory=None, priority=None) -> None:
        """Change the pod template -> new revision (the spec update that
        triggers deployment_controller.go getNewReplicaSet + rolling)."""
        if cpu_milli is not None:
            self.cpu_milli = cpu_milli
        if memory is not None:
            self.memory = memory
        if priority is not None:
            self.priority = priority
        self.template_rev += 1


def _int_or_percent(v, total: int, round_up: bool) -> int:
    """intstr.GetValueFromIntOrPercent (apimachinery util/intstr): "25%"
    resolves against ``total``, surge rounds up, unavailable down."""
    import math

    if isinstance(v, str) and v.endswith("%"):
        f = float(v[:-1]) / 100.0 * total
        return int(math.ceil(f) if round_up else math.floor(f))
    return int(v)


@dataclass
class Job:
    """Hollow job controller (pkg/controller/job): keeps up to
    ``parallelism`` active pods until ``completions`` pods have run for
    ``duration_s`` each (the hollow runtime "finishes" them — the
    run-to-completion lifecycle the scheduler must keep feeding)."""

    name: str
    completions: int
    parallelism: int = 1
    duration_s: float = 30.0
    cpu_milli: float = 100
    memory: float = 256 * 2**20
    next_idx: int = 0
    succeeded: int = 0
    active: Dict[str, Pod] = field(default_factory=dict)
    #: owning CronJob name ("" = standalone) — the ownerReference edge
    #: the GC graph walks (cronjob-spawned jobs cascade on its deletion)
    owner: str = ""
    #: spec.ttlSecondsAfterFinished (batch/v1 JobSpec): when set, the
    #: TTL-after-finished controller deletes the Job this many seconds
    #: after it finishes (ttlafterfinished_controller.go:263 needsCleanup:
    #: finished AND ttl non-nil). None = keep forever (the default).
    ttl_seconds_after_finished: Optional[float] = None
    #: status.completionTime analog — stamped by the job sync on the tick
    #: ``done()`` first becomes true; the TTL clock starts here, not at
    #: the last pod's exit (timeLeft computes expiry from CompletionTime,
    #: ttlafterfinished_controller.go:277).
    finished_at: Optional[float] = None

    def done(self) -> bool:
        return self.succeeded >= self.completions


#: the tolerations the daemonset controller stamps on every daemon pod
#: (pkg/controller/daemon/util AddOrUpdateDaemonPodTolerations): NoExecute
#: outage taints never evict daemons, and the NoSchedule condition taints
#: (TaintNodesByCondition) don't keep them out
DAEMON_TOLERATIONS = (
    Toleration(key="node.kubernetes.io/unreachable", operator="Exists",
               effect=EFFECT_NO_EXECUTE),
    Toleration(key="node.kubernetes.io/not-ready", operator="Exists",
               effect=EFFECT_NO_EXECUTE),
    Toleration(key="node.kubernetes.io/unschedulable", operator="Exists",
               effect=EFFECT_NO_SCHEDULE),
    Toleration(key="node.kubernetes.io/disk-pressure", operator="Exists",
               effect=EFFECT_NO_SCHEDULE),
    Toleration(key="node.kubernetes.io/memory-pressure", operator="Exists",
               effect=EFFECT_NO_SCHEDULE),
    Toleration(key="node.kubernetes.io/pid-pressure", operator="Exists",
               effect=EFFECT_NO_SCHEDULE),
)


@dataclass
class DaemonSet:
    """Hollow daemonset controller (pkg/controller/daemon). v1.16 default
    (ScheduleDaemonSetPods GA'd that cycle, daemon_controller.go): daemon
    pods flow through the DEFAULT scheduler, pinned to their node by
    required node affinity — the reference pins on the metadata.name
    field selector; our columnar packer interns the equivalent
    ``kubernetes.io/hostname`` label every node carries, so the pin is a
    hostname In-term. Pods carry :data:`DAEMON_TOLERATIONS` so the
    node-lifecycle NoExecute taint path leaves them in place."""

    name: str
    cpu_milli: float = 50
    memory: float = 128 * 2**20
    priority: int = 0
    #: node-eligibility selector (spec.template.spec.nodeSelector);
    #: empty = every node
    node_selector: Dict[str, str] = field(default_factory=dict)
    #: pod key -> node name it is pinned to
    live: Dict[str, str] = field(default_factory=dict)
    #: current template revision (the controller-revision-hash analog;
    #: daemon pods carry it as a label) — bumped by :meth:`rollout`
    template_rev: int = 1
    #: RollingUpdate maxUnavailable (update.go:48 — v1.16 default 1):
    #: at most this many nodes may be without a CURRENT-revision daemon
    #: pod due to the update at once
    max_unavailable: int = 1
    #: (revision, template) pairs not yet drained into the hub's
    #: ControllerRevision registry — rollout() records SYNCHRONOUSLY
    #: here so a revision current for zero reconcile passes (two
    #: rollouts between ticks) is never lost from history
    pending_revisions: List[Tuple[int, Dict]] = field(default_factory=list)

    def rollout(self, cpu_milli=None, memory=None, priority=None) -> None:
        """Template update (apps/v1 RollingUpdate updateStrategy): stale
        daemon pods are replaced node by node under max_unavailable; the
        history pass records a ControllerRevision per template."""
        self.pending_revisions.append((self.template_rev, self.template()))
        if cpu_milli is not None:
            self.cpu_milli = cpu_milli
        if memory is not None:
            self.memory = memory
        if priority is not None:
            self.priority = priority
        self.template_rev += 1

    def template(self) -> dict:
        return {"cpu_milli": self.cpu_milli, "memory": self.memory,
                "priority": self.priority}

    def should_keep(self, node: Node) -> bool:
        """v1.16 shouldContinueRunning: an existing daemon pod stays
        unless the node left the selector or carries an untolerated
        NoExecute taint — outage (NotReady) and cordon do NOT evict
        daemons (daemon_controller.go nodeShouldRunDaemonPod)."""
        if not all(node.labels.get(k) == v
                   for k, v in self.node_selector.items()):
            return False
        return not any(
            t.effect == EFFECT_NO_EXECUTE
            and not any(tol.tolerates(t) for tol in DAEMON_TOLERATIONS)
            for t in node.taints
        )

    def can_place(self, node: Node) -> bool:
        """v1.16 shouldSchedule: create a NEW daemon pod only where our
        scheduler would actually place it. Deviation from the reference:
        this hub models cordon/pressure/not-ready as spec+condition bits
        which the predicates enforce regardless of tolerations (the
        reference's TaintNodesByCondition taint form is what the daemon
        tolerations bypass), so such nodes are deferred — the next sync
        after recovery creates the pod — instead of parked-on forever."""
        if not self.should_keep(node):
            return False
        if node.unschedulable or node.conditions.disk_pressure \
                or node.conditions.pid_pressure \
                or not node.conditions.ready \
                or node.conditions.network_unavailable:
            return False
        return not any(
            t.effect == EFFECT_NO_SCHEDULE
            and not any(tol.tolerates(t) for tol in DAEMON_TOLERATIONS)
            for t in node.taints
        )


@dataclass
class StatefulSet:
    """Hollow statefulset controller (pkg/controller/statefulset,
    OrderedReady pod management — stateful_set_control.go): ordinal i is
    created only once 0..i-1 are bound (the hollow Running+Ready);
    scale-down removes the highest ordinal first, one per sync; a deleted
    middle ordinal is recreated under the SAME name (stable identity)
    with a fresh apiserver-assigned uid."""

    name: str
    replicas: int
    cpu_milli: float = 100
    memory: float = 256 * 2**20
    priority: int = 0
    #: current template revision (updateRevision); pods carry it as the
    #: controller-revision-hash label analog
    template_rev: int = 1
    #: status.currentRevision: the revision BELOW-partition pods are
    #: recreated at (the canary boundary's other half — reference
    #: recreates them at currentRevision, not updateRevision); advanced
    #: to template_rev when the rollout completes
    current_rev: int = 1
    #: RollingUpdate partition (stateful_set_control.go: only ordinals
    #: >= partition update; a canary knob — 0 = update everything)
    partition: int = 0
    #: see DaemonSet.pending_revisions
    pending_revisions: List[Tuple[int, Dict]] = field(default_factory=list)

    def pod_name(self, ordinal: int) -> str:
        return f"{self.name}-{ordinal}"

    def rollout(self, cpu_milli=None, memory=None, priority=None) -> None:
        """Template update (apps/v1 RollingUpdate): stale pods with
        ordinal >= partition are replaced highest-first, one per sync,
        each waiting for its successor to run (OrderedReady)."""
        self.pending_revisions.append((self.template_rev, self.template()))
        if cpu_milli is not None:
            self.cpu_milli = cpu_milli
        if memory is not None:
            self.memory = memory
        if priority is not None:
            self.priority = priority
        self.template_rev += 1

    def template(self) -> dict:
        return {"cpu_milli": self.cpu_milli, "memory": self.memory,
                "priority": self.priority}


@dataclass
class ControllerRevision:
    """apps/v1 ControllerRevision (pkg/controller/history): an immutable
    template snapshot DS/STS updates key on — the rollback target
    `kubectl rollout undo` resolves. ``data`` is the hollow template
    (cpu/memory/priority)."""

    owner_kind: str
    owner_name: str
    revision: int
    data: Dict = field(default_factory=dict)

    def key(self) -> str:
        return f"{self.owner_kind}/{self.owner_name}/{self.revision}"


@dataclass
class CronJob:
    """Hollow cronjob controller (pkg/controller/cronjob): spawns a Job
    every ``every_s`` sim-seconds. concurrencyPolicy semantics from
    cronjob_controller.go syncOne: Allow runs jobs side by side, Forbid
    skips a tick while the previous job is active, Replace deletes the
    active job's pods and starts fresh. Finished jobs beyond
    ``history_limit`` are GC'd (successfulJobsHistoryLimit)."""

    name: str
    every_s: float
    completions: int = 1
    parallelism: int = 1
    duration_s: float = 15.0
    concurrency: str = "Allow"  # Allow | Forbid | Replace
    history_limit: int = 3
    cpu_milli: float = 100
    memory: float = 256 * 2**20
    next_run: float = 0.0
    runs: int = 0
    #: job names spawned by this cron, oldest first
    spawned: List[str] = field(default_factory=list)


@dataclass
class HorizontalPodAutoscaler:
    """Hollow HPA (pkg/controller/podautoscaler horizontal.go): scales a
    Deployment between min/max replicas toward
    desired = ceil(current * currentUtilization / target), with the 10%
    tolerance dead-band (GetResourceReplicas, replica_calculator.go:89).
    The hollow metric source is ``load_fn`` — a callable returning the
    current average utilization (the sim's stand-in for the metrics
    pipeline the reference scrapes)."""

    name: str
    deployment: str
    min_replicas: int
    max_replicas: int
    target_utilization: float = 0.5
    load_fn: Optional[Callable[[], float]] = None
    tolerance: float = 0.1


class HollowKubelet:
    """Per-node hollow node agent — the kubemark hollow-node analog
    (pkg/kubemark/hollow_kubelet.go:44: real kubelet logic, fake
    runtime), covering the slice of pkg/kubelet the scheduler's
    correctness depends on:

    - **admission** (lifecycle/predicate.go GeneralPredicates at
      arrival): the apiserver accepts double-booked bindings, so two
      schedulers racing on stale views CAN overcommit a node in truth;
      this kubelet admits bound pods in binding-arrival order
      (resourceVersion) and evicts the over-committed tail (OutOfcpu),
      whose controllers then recreate them;
    - **node-status heartbeats** (kubelet_node_status.go): refreshed
      every sync while alive; the node-lifecycle controller CONSUMES the
      age (it never refreshes — killing this kubelet is how the
      unreachable-taint path is exercised);
    - **pressure conditions** (eviction-manager thresholds): memory
      usage beyond ``mem_pressure_frac`` of allocatable reports
      MemoryPressure in node status (MODIFIED event), which the
      scheduler's CheckNodeMemoryPressure then enforces against
      BestEffort pods.
    """

    def __init__(self, hub: "HollowCluster", node_name: str,
                 mem_pressure_frac: float = 0.95) -> None:
        self.hub = hub
        self.name = node_name
        self.alive = True
        self.mem_pressure_frac = mem_pressure_frac

    def pods(self) -> List[Pod]:
        """Live (non-terminal) pods bound here — a Succeeded pod's
        containers have exited, so it holds no resources and exerts no
        memory pressure (the kubelet's podWorkers have released it)."""
        from kubernetes_tpu.api.types import is_pod_terminated

        return [p for p in self.hub.truth_pods.values()
                if p.node_name == self.name and not is_pod_terminated(p)]

    def heartbeat(self) -> None:
        if self.alive:
            self.hub.heartbeats[self.name] = self.hub.clock.t

    def admit(self, keys: Optional[List[str]] = None) -> None:
        """GeneralPredicates at arrival; evict the over-committed tail in
        binding order (latest bindings lose, like late OutOfcpu arrivals).
        ``keys`` lets the hub pass a pre-grouped pod list (one O(P) pass
        for all nodes instead of one scan per node)."""
        from kubernetes_tpu.api.types import is_pod_terminated

        nd = self.hub.truth_nodes.get(self.name)
        if nd is None:
            return
        if keys is None:
            keys = [k for k, p in self.hub.truth_pods.items()
                    if p.node_name == self.name]
        # terminal pods have released their resources (podWorker done) —
        # they neither consume the budget nor get evicted by it
        keys = [k for k in keys
                if not is_pod_terminated(self.hub.truth_pods[k])]
        keys = sorted(
            keys, key=lambda k: self.hub.resource_version.get(f"pods/{k}", 0))
        cpu = mem = cnt = 0.0
        for k in keys:
            p = self.hub.truth_pods[k]
            cpu += p.requests.cpu_milli
            mem += p.requests.memory
            cnt += 1
            if (
                cpu > nd.allocatable.cpu_milli + 1e-6
                or mem > nd.allocatable.memory + 1e-6
                or cnt > nd.allocatable.pods
            ):
                self.hub.delete_pod(k)
                cpu -= p.requests.cpu_milli
                mem -= p.requests.memory
                cnt -= 1

    def update_conditions(self) -> None:
        """Report MemoryPressure when usage crosses the eviction-manager
        threshold; clear it when usage recedes. Status writes go through
        the hub (a node MODIFIED watch event, like a real status PATCH)."""
        import dataclasses

        nd = self.hub.truth_nodes.get(self.name)
        if nd is None or not self.alive:
            return
        used_mem = sum(p.requests.memory for p in self.pods())
        pressured = used_mem > self.mem_pressure_frac * max(
            nd.allocatable.memory, 1e-9
        )
        if pressured != nd.conditions.memory_pressure:
            self.hub._update_node(dataclasses.replace(
                nd,
                conditions=dataclasses.replace(
                    nd.conditions, memory_pressure=pressured
                ),
            ))

    def sync(self) -> None:
        """One syncLoop iteration (kubelet.go:1816 analog, hollow).
        Admission is NOT repeated here — the hub's kubelet_admission pass
        (run from gc_orphaned every tick) already enforced it with one
        grouped scan."""
        self.heartbeat()
        if self.alive:
            self.update_conditions()


class HollowCluster:
    """Owns the truth (pods/nodes) behind a versioned store and pumps
    watch events at the scheduler. All scheduler interaction goes through
    the event-handler surface, like the reference's AddAllEventHandlers
    wiring; all hub writes go through :meth:`_commit`, the GuaranteedUpdate
    analog."""

    def __init__(
        self,
        seed: int = 0,
        bind_fail_rate: float = 0.0,
        scheduler_kw: Optional[dict] = None,
        event_delay_ticks: int = 0,
        competing_bind_rate: float = 0.0,
        node_grace_s: float = 40.0,
        eviction_wait_s: float = 30.0,
        zone_eviction_rate: int = 1000,
        admission: bool = False,
    ) -> None:
        self.rng = random.Random(seed)
        #: serializes hub mutation against concurrent readers (the REST
        #: facade shares this lock; re-entrant because step() nests hub
        #: calls). The sim itself is single-threaded — the lock exists
        #: for the serving facades.
        self.lock = threading.RLock()
        self.clock = SimClock()
        self.truth_pods: Dict[str, Pod] = {}  # key -> pod (node_name = truth)
        self.truth_nodes: Dict[str, Node] = {}
        #: per-object resourceVersion (etcd mod_revision analog)
        self.resource_version: Dict[str, int] = {}
        self._revision = 0  # global etcd revision
        #: coordination Leases ("ns/name" -> opaque record) — leader
        #: election CASes these through the hub (resourcelock
        #: interface.go:100); see get_lease/cas_lease
        self.leases: Dict[str, object] = {}
        #: volume API truth ("ns/name" -> PVC, name -> PV/StorageClass):
        #: owned by the hub so the PV controller pass (reconcile_volumes,
        #: pv_controller.go:236) and the scheduler's volume binder both
        #: write through the versioned store
        self.pvcs: Dict[str, object] = {}
        self.pvs: Dict[str, object] = {}
        self.storage_classes: Dict[str, object] = {}
        #: service accounts + minted bearer tokens (the serviceaccounts
        #: controller guarantees a "default" SA per Active namespace;
        #: the tokens controller mints one token per SA —
        #: tokens_controller.go:73). Tokens are REVOCABLE: namespace
        #: termination deletes its SAs and their tokens, and the live
        #: lookup (sa_token_user) answers None immediately.
        self.service_accounts: Dict[str, ServiceAccount] = {}
        self.sa_tokens: Dict[str, str] = {}  # token -> "ns/name"
        #: rbac.authorization.k8s.io: ClusterRoles (name -> auth.
        #: ClusterRole) + ClusterRoleBindings; the aggregation
        #: controller pass materializes aggregated roles' rules, and
        #: auth.RBACAuthorizer(self.cluster_roles,
        #: self.cluster_role_bindings) resolves them LIVE
        self.cluster_roles: Dict[str, object] = {}
        self.cluster_role_bindings: List = []
        #: certificates.k8s.io: CSR objects + the live credential
        #: registry the authn chain consults (cert -> (UserInfo,
        #: not_after)); expired certs leave the registry — lookup-time
        #: NotAfter (kubernetes_tpu/certificates.py)
        self.csrs: Dict[str, object] = {}
        self.signed_certs: Dict[str, tuple] = {}
        self.cluster_ca = f"ktpu-ca:{seed}"
        #: ConfigMaps ("ns/name" -> {"data": {...}}) — enough surface
        #: for the root-CA publisher; namespace drain removes them
        self.configmaps: Dict[str, dict] = {}
        #: TTL controller hysteresis step (ttl_controller.go boundaryStep)
        self._ttl_step = 0
        #: nodeipam range allocator: cluster CIDR carved into per-node
        #: blocks ("/8 + /24" covers 65536 nodes — the 50k story fits)
        self.cluster_cidr = "10.0.0.0/8"
        self.node_cidr_prefix = 24
        self._cidr_subnets = None  # lazy (ip_network parse on first use)
        self._cidr_alloc: Dict[str, int] = {}
        self._cidr_next = 0
        self._cidr_free: List[int] = []
        self.cidr_exhausted_total = 0
        #: attach-detach controller actual state (attach_detach_
        #: controller.go:102): volume identity -> Attachment. All
        #: attachable volumes are treated single-attach (the PV model
        #: carries no access modes; RWO is the conservative reading).
        self.attachments: Dict[str, Attachment] = {}
        #: detach grace: how long a no-longer-needed volume stays
        #: attached before the reconciler detaches it (the
        #: maxWaitForUnmount/timer analog, reconciler.go)
        self.detach_grace_s: float = 30.0
        self.attaches_total = 0
        self.detaches_total = 0
        self._last_residue: Dict[str, tuple] = {}
        #: hollow prober targets: pod key -> app health (default True);
        #: the fake runtime's answer to readiness probes
        self.app_health: Dict[str, bool] = {}
        #: pod key -> Running transition time (probe initialDelay clock)
        self._started_at: Dict[str, float] = {}
        #: apps/v1 ControllerRevisions (pkg/controller/history): template
        #: snapshots per DS/STS revision, maintained by reconcile_history
        self.controller_revisions: Dict[str, ControllerRevision] = {}
        self.revision_history_limit = 10
        self.replicasets: Dict[str, ReplicaSet] = {}
        #: v1 ReplicationControllers — same machinery as ReplicaSets
        #: (see ReplicaSet.kind), separate registry so the kinds can't
        #: collide on a name
        self.replication_controllers: Dict[str, ReplicaSet] = {}
        self.deployments: Dict[str, Deployment] = {}
        self.jobs: Dict[str, Job] = {}
        self.daemonsets: Dict[str, DaemonSet] = {}
        self.statefulsets: Dict[str, StatefulSet] = {}
        self.cronjobs: Dict[str, CronJob] = {}
        self.hpas: Dict[str, HorizontalPodAutoscaler] = {}
        #: pod key -> bind commit time (job completion clock; set by
        #: confirm_binding)
        self._bound_at: Dict[str, float] = {}
        #: pod key -> create commit time (metadata.creationTimestamp
        #: analog) — the pod GC's oldest-first ordering key
        #: (gc_controller.go:117 byCreationTimestamp)
        self._created_at: Dict[str, float] = {}
        #: keys whose scheduler-side DELETE was already emitted at the
        #: terminal phase hop (the informer field-selector turns
        #: Running->Succeeded into a delete; the later object delete must
        #: not emit a second one)
        self._terminal_gone: set = set()
        #: pod GC: keep at most this many terminal pods
        #: (--terminated-pod-gc-threshold; 0 disables that half, the
        #: controller-manager default — gc_controller.go:94)
        self.terminated_pod_threshold: int = 0
        self.pods_gced_total = 0
        #: pod key -> graceful-deletion grace seconds (mark_terminating)
        self._term_grace: Dict[str, float] = {}
        #: live PDB objects; the disruption-controller analog maintains
        #: their status and the scheduler's pdb_lister reads them directly
        self.pdbs: List = []
        # node-lifecycle state (heartbeats, unreachable taints, eviction)
        self.dead_kubelets: set = set()
        #: per-node hollow agents (kubemark hollow-node registry)
        self.kubelets: Dict[str, HollowKubelet] = {}
        self.heartbeats: Dict[str, float] = {}
        self._taint_time: Dict[str, float] = {}
        self.node_grace_s = node_grace_s
        self.eviction_wait_s = eviction_wait_s
        self.zone_eviction_rate = zone_eviction_rate
        # service dataplane (kube-proxy analog, kubernetes_tpu/proxy.py):
        # Service/Endpoints truth + per-node hollow proxies
        self.services: Dict[str, object] = {}
        self.endpoints: Dict[str, object] = {}
        self.proxies: Dict[str, object] = {}
        self.ip_alloc = ClusterIPAllocator()
        self.nodeport_alloc = NodePortAllocator()
        self.endpoints_controller = EndpointsController(self)
        # apiserver admission chain (kubernetes_tpu/admission.py) —
        # opt-in like --enable-admission-plugins; when off, creates land
        # unexamined (the legacy hub behavior most sims exercise)
        self.namespaces: Dict[str, Namespace] = {
            "default": Namespace("default", NS_ACTIVE),
            "kube-system": Namespace("kube-system", NS_ACTIVE),
            "kube-public": Namespace("kube-public", NS_ACTIVE),
        }
        #: bootstrap tokens (kubeadm bootstraptoken phase mints; the
        #: token-cleaner controller expires; the bootstrap signer signs
        #: cluster-info with them — kubernetes_tpu/bootstrap.py)
        self.bootstrap_tokens: Dict[str, object] = {}
        self.priority_classes: Dict[str, object] = {}
        self.quotas: List = []
        #: v1 LimitRanges — the LimitRanger admission plugin reads this
        #: container live (add_limit_range appends)
        self.limit_ranges: List = []
        self.admission = (
            default_chain(self.namespaces, self.priority_classes,
                          self.quotas, limit_ranges=self.limit_ranges)
            if admission else None
        )
        self.quota_controller = QuotaController(self)
        from kubernetes_tpu.certificates import (
            CertificateController,
            RootCACertPublisher,
        )

        self.cert_controller = CertificateController(self)
        self.root_ca_publisher = RootCACertPublisher(self)
        #: cloud node controller (kubernetes_tpu/cloud.py) — None until
        #: attach_cloud(); once attached, EVERY node is cloud-managed
        #: (instance gone at the provider ⇒ node object removed)
        self.cloud_controller = None
        self.service_lb_controller = None
        self.route_controller = None
        self.binder = FlakyBinder(self, bind_fail_rate, self.rng)
        # stable signature of the caller's scheduler knobs — compared by
        # the checkpoint config guard (callables repr unstably and never
        # round-trip anyway; they are live wiring, not semantics)
        self._scheduler_kw_sig = tuple(sorted(
            (k, repr(v)) for k, v in (scheduler_kw or {}).items()
            if not callable(v)
        ))
        kw = dict(scheduler_kw or {})
        kw.setdefault("pdb_lister", lambda: list(self.pdbs))
        # the scheduler's events land in the hub as API objects (the
        # reference posts Events via client-go and the apiserver stores
        # them; tools/record aggregation happens recorder-side, so the hub
        # sees count-bumped upserts keyed like the events registry)
        from kubernetes_tpu.events import EventRecorder

        self.events_recorder = EventRecorder(
            clock=self.clock, sinks=[self._store_event]
        )
        #: event-key -> Event, the hub's events registry slice
        self.events_v1: Dict[str, object] = {}
        kw.setdefault("event_sink", self.events_recorder.sink())
        self.sched = Scheduler(binder=self.binder, clock=self.clock, **kw)
        # the scheduler's delayed-binding commits (BindPodVolumes) write
        # through the hub store so PVC/PV mutations get revisions and
        # watch events like every other truth write
        self.sched.volume_binder.writer = self._commit_volume_bind
        self.bound_total = 0
        self.competing_bind_rate = competing_bind_rate
        self.competing_bound = 0
        # watch plumbing: events deliver after 0..event_delay_ticks ticks,
        # per-object order preserved (heap keyed by due-tick then seq)
        self.event_delay_ticks = event_delay_ticks
        self._tick = 0
        self._seq = 0
        self._watch_q: List[tuple] = []  # (due, seq, deliver_fn)
        self._obj_last_due: Dict[str, int] = {}
        #: append-only watch history: (rev, obj_key, type, obj-or-None)
        self._history: List[tuple] = []
        self._compacted_rev = 0
        #: open watch cursors (weak: a dropped Reflector frees its history)
        self._cursors: "weakref.WeakSet" = weakref.WeakSet()

    # -- versioned store core ---------------------------------------------

    def _commit(self, obj_key: str, event_type: str, obj) -> int:
        """Bump the global revision, stamp the object, and append the
        event to the watch HISTORY — every truth write funnels through
        here (etcd3/store.go:236 GuaranteedUpdate; the history log is the
        etcd WAL/watchable-store analog that lets any number of watch
        cursors replay from a revision). ``event_type``/``obj`` are
        REQUIRED: a defaulted ('MODIFIED', None) entry would replay as
        on_node_update(None) in a Reflector far from the buggy call site.

        History is recorded only while watch cursors are open — with no
        watcher it would just pin every historical object (etcd compacts
        periodically for the same reason; see :meth:`step`)."""
        self._revision += 1
        self.resource_version[obj_key] = self._revision
        if self._cursors:
            self._history.append((self._revision, obj_key, event_type, obj))
        else:
            self._compacted_rev = self._revision
        return self._revision

    def record_controller_event(self, reason: str, object_key: str,
                                message: str,
                                type_: str = "Normal",
                                involved_kind: str = "Pod") -> None:
        """Controller-manager event seam (the recorder each reference
        controller carries): aggregate-upsert an Event about any object
        into the hub store — visible via the v1 EventList and
        ``ktpu get events`` like every other event."""
        from kubernetes_tpu.events import Event

        now = self.clock.t
        ev = Event(type=type_, reason=reason, object_key=object_key,
                   message=message, first_timestamp=now,
                   last_timestamp=now, involved_kind=involved_kind)
        # aggregate with the stored series (one shared key derivation
        # with _store_event — two copies would silently skew)
        prior = self.events_v1.get(self._event_series_key(ev))
        if prior is not None:
            ev.count = prior.count + 1
            ev.first_timestamp = prior.first_timestamp
        self._store_event(ev)

    @staticmethod
    def _event_series_key(ev) -> str:
        """The store key of an Event's aggregation series: same
        (object, reason, message) => same key, so recurrences bump
        count/resourceVersion instead of multiplying objects."""
        import hashlib

        series = hashlib.sha1(
            f"{ev.object_key}|{ev.reason}|{ev.message}".encode()
        ).hexdigest()[:10]
        ns, _, name = ev.object_key.partition("/")
        return f"{ns}/{name}.{series}"

    def _store_event(self, ev) -> None:
        """Upsert an (aggregated) Event into the hub store — the
        events-registry write client-go's recorder performs; same key for
        the same (object, reason, message) series so aggregation bumps
        resourceVersion instead of multiplying objects."""
        key = self._event_series_key(ev)
        verb = "MODIFIED" if key in self.events_v1 else "ADDED"
        self.events_v1[key] = ev
        # bounded like the recorder (and like etcd's event TTL): evict the
        # stalest series; a later recurrence restarts its count at 1,
        # matching what TTL'd-out reference events do
        if len(self.events_v1) > 10000:
            oldest = min(self.events_v1,
                         key=lambda k: self.events_v1[k].last_timestamp)
            del self.events_v1[oldest]
        self._commit(f"events/{key}", verb, ev)

    def compact(self, rev: Optional[int] = None) -> None:
        """Drop watch history at or below ``rev`` (etcd compaction,
        mvcc/kvstore_compaction.go). Cursors behind the floor get
        :class:`Compacted` on their next poll and must relist."""
        rev = self._revision if rev is None else rev
        self._compacted_rev = max(self._compacted_rev, rev)
        self._history = [e for e in self._history if e[0] > self._compacted_rev]

    def watch(self, since_rev: int) -> "WatchCursor":
        """Open an independent watch cursor starting AFTER ``since_rev``
        (apiserver watch ?resourceVersion= semantics). Any number of
        cursors may be open — the watch-cacher fan-out (cacher.go: N
        watchers cost one history log)."""
        if since_rev < self._compacted_rev:
            raise Compacted(
                f"required revision {since_rev} has been compacted "
                f"(floor {self._compacted_rev})"
            )
        cur = WatchCursor(self, since_rev)
        self._cursors.add(cur)
        return cur

    def list_state(self):
        """LIST at the current revision: (revision, nodes, pods) snapshots
        — the Reflector's relist source (reflector.go:159)."""
        return (
            self._revision,
            dict(self.truth_nodes),
            dict(self.truth_pods),
        )

    def _emit(self, obj_key: str, deliver: Callable[[], None]) -> None:
        """Queue a watch event. Delivery may lag (``event_delay_ticks``)
        but is never reordered for the same object — a later event for an
        object is due no earlier than its previous one, like a per-object
        watch stream."""
        if self.event_delay_ticks <= 0:
            deliver()
            return
        due = self._tick + self.rng.randint(0, self.event_delay_ticks)
        due = max(due, self._obj_last_due.get(obj_key, 0))
        self._obj_last_due[obj_key] = due
        self._seq += 1
        heapq.heappush(self._watch_q, (due, self._seq, deliver))

    def flush_events(self, up_to: Optional[int] = None) -> int:
        """Deliver all watch events due at or before ``up_to`` (default:
        the current tick). Returns how many were delivered."""
        up_to = self._tick if up_to is None else up_to
        n = 0
        while self._watch_q and self._watch_q[0][0] <= up_to:
            _, _, deliver = heapq.heappop(self._watch_q)
            deliver()
            n += 1
        return n

    def settle(self) -> None:
        """Drain every in-flight watch event and GC orphans — the
        'informers caught up' state the consistency oracle compares."""
        while self._watch_q:
            self.flush_events(up_to=self._watch_q[0][0])
        self.gc_orphaned()
        while self._watch_q:
            self.flush_events(up_to=self._watch_q[0][0])

    # -- truth mutations (each pumps the corresponding watch event) --------

    def add_node(self, node: Node) -> None:
        self.truth_nodes[node.name] = node
        self.kubelets[node.name] = HollowKubelet(self, node.name)
        self.proxies[node.name] = ServiceProxy(node.name, self.clock)
        self.heartbeats[node.name] = self.clock.t
        self._commit(f"nodes/{node.name}", "ADDED", node)
        self._emit(f"nodes/{node.name}", lambda: self.sched.on_node_add(node))

    def remove_node(self, name: str) -> None:
        """Node vanishes; its pods are lost and deleted by the hub (the
        node-lifecycle/GC path, heavily simplified)."""
        if self.truth_nodes.pop(name, None) is None:
            return
        self.heartbeats.pop(name, None)
        self.kubelets.pop(name, None)
        self.proxies.pop(name, None)
        self._taint_time.pop(name, None)
        self.dead_kubelets.discard(name)
        self._commit(f"nodes/{name}", "DELETED", None)
        for key, p in list(self.truth_pods.items()):
            if p.node_name == name:
                self.delete_pod(key)
        self._emit(f"nodes/{name}", lambda: self.sched.on_node_delete(name))

    def create_pod(self, pod: Pod) -> None:
        if self.admission is not None:
            pod = self.admission.run(pod)  # raises AdmissionError on 403
        if not pod.uid:
            # the apiserver assigns metadata.uid at create; an empty uid
            # would break the Binding CAS's recreated-pod check for any
            # consumer that round-trips pods through the JSON seam
            pod.uid = f"{pod.key()}#u{self._revision + 1}"
        self.truth_pods[pod.key()] = pod
        self._created_at[pod.key()] = self.clock.t
        self._terminal_gone.discard(pod.key())  # recreated key: fresh pod
        self._commit(f"pods/{pod.key()}", "ADDED", pod)
        self._emit(f"pods/{pod.key()}", lambda: self.sched.on_pod_add(pod))

    def replace_pod(self, new: "Pod") -> None:
        """Metadata-style update of an existing pod (the PATCH/PUT seam:
        apiserver UpdatePodStatus/label updates). Identity and placement
        are IMMUTABLE here — name/namespace/uid/node_name changes must go
        through delete+create or the Binding subresource; violating that
        would bypass the CAS semantics confirm_binding enforces."""
        key = new.key()
        cur = self.truth_pods.get(key)
        if cur is None:
            raise KeyError(f"pods {key!r} not found")
        if new.uid != cur.uid or new.node_name != cur.node_name:
            raise ValueError(
                "replace_pod cannot change uid or nodeName (use the "
                "Binding subresource / delete+create)"
            )
        self.truth_pods[key] = new
        self._commit(f"pods/{key}", "MODIFIED", new)
        self._emit(f"pods/{key}", lambda: self.sched.on_pod_update(cur, new))

    def delete_pod(self, key: str) -> None:
        pod = self.truth_pods.pop(key, None)
        if pod is not None:
            self._bound_at.pop(key, None)
            self._started_at.pop(key, None)
            self._created_at.pop(key, None)
            self._term_grace.pop(key, None)
            self.app_health.pop(key, None)
            self._commit(f"pods/{key}", "DELETED", None)
            if key in self._terminal_gone:
                # the scheduler's field-selected informer already saw the
                # delete at the terminal phase hop — no second event
                self._terminal_gone.discard(key)
            else:
                self._emit(f"pods/{key}",
                           lambda: self.sched.on_pod_delete(pod))
            for rs in self.replicasets.values():
                rs.live.pop(key, None)
            for rc in self.replication_controllers.values():
                rc.live.pop(key, None)
            for ds in self.daemonsets.values():
                ds.live.pop(key, None)

    def confirm_binding(self, pod: Pod, node_name: str) -> None:
        """The Binding subresource: a CAS write (BindingREST.Create →
        assignPod, storage.go:154,:210). Raises :class:`Conflict` when the
        scheduler's view was stale — pod deleted, pod recreated under the
        same key, or already bound by another writer."""
        key = pod.key()
        cur = self.truth_pods.get(key)
        if cur is None:
            raise Conflict(f'pods "{key}" not found (deleted mid-bind)')
        if cur.uid != pod.uid:
            raise Conflict(f'pods "{key}" uid changed (recreated mid-bind)')
        if cur.node_name:
            raise Conflict(
                f'pods "{key}" is already assigned to node "{cur.node_name}"'
            )
        import dataclasses

        new = dataclasses.replace(cur, node_name=node_name)
        self.truth_pods[key] = new
        self._commit(f"pods/{key}", "MODIFIED", new)
        self._bound_at[key] = self.clock.t
        self.bound_total += 1
        self._emit(f"pods/{key}", lambda: self.sched.on_pod_update(cur, new))

    def get_lease(self, namespace: str, name: str):
        """Read a coordination Lease: ``(record, resourceVersion)`` —
        rv 0 means the Lease does not exist yet (leaselock.go:53 Get)."""
        with self.lock:
            return (self.leases.get(f"{namespace}/{name}"),
                    self.resource_version.get(f"leases/{namespace}/{name}", 0))

    def cas_lease(self, namespace: str, name: str, record,
                  expected_rv: int):
        """Create/update a Lease iff its resourceVersion still equals
        ``expected_rv`` (0 = must-not-exist). Returns the new rv, or None
        on conflict — the apiserver CAS leader election rides on
        (resourcelock/interface.go:100; GuaranteedUpdate semantics). The
        check-and-swap is atomic under the hub lock, which is the whole
        point of hub-mediated HA: two candidates racing the same rv
        cannot both win."""
        with self.lock:
            obj_key = f"leases/{namespace}/{name}"
            cur_rv = self.resource_version.get(obj_key, 0)
            if cur_rv != expected_rv:
                return None
            self.leases[f"{namespace}/{name}"] = record
            return self._commit(obj_key,
                                "MODIFIED" if cur_rv else "ADDED", record)

    # -- checkpoint / restore (etcd snapshot + restore analog) -------------

    #: the attrs a checkpoint carries: the full API-state slice (what
    #: etcd holds — objects, controller specs, dataplane truth) plus the
    #: per-node kubelet clocks (the kubelet checkpointmanager analog:
    #: pod lifecycle/probe state survives an agent restart)
    _CHECKPOINT_ATTRS = (
        "truth_nodes", "truth_pods", "resource_version", "leases",
        "pvcs", "pvs", "storage_classes",
        "replicasets", "deployments", "jobs", "daemonsets",
        "statefulsets", "cronjobs", "hpas", "pdbs",
        "services", "endpoints", "namespaces", "priority_classes",
        "quotas", "ip_alloc", "nodeport_alloc", "events_v1",
        "heartbeats", "dead_kubelets", "_taint_time",
        "_bound_at", "_started_at", "app_health",
        "attachments", "service_accounts", "sa_tokens",
        # round-5 state: identity/config registries an etcd restore
        # preserves (losing signed_certs would orphan every node
        # identity; losing configmaps breaks cluster-info discovery),
        # plus pod-GC bookkeeping
        "replication_controllers", "csrs", "signed_certs", "configmaps",
        "bootstrap_tokens", "cluster_roles", "cluster_role_bindings",
        "cluster_ca", "_created_at", "_term_grace", "_terminal_gone",
        "terminated_pod_threshold", "controller_revisions",
        "limit_ranges",
    )

    def _semantic_config(self) -> dict:
        """The construction knobs that change cluster SEMANTICS — stamped
        into checkpoints so restoring into a differently-configured hub
        fails loudly instead of silently diverging (e.g. a hub saved with
        admission on restored into one without would bypass quota)."""
        return {
            "admission": self.admission is not None,
            "node_grace_s": self.node_grace_s,
            "eviction_wait_s": self.eviction_wait_s,
            "zone_eviction_rate": self.zone_eviction_rate,
            "bind_fail_rate": self.binder.fail_rate,
            "event_delay_ticks": self.event_delay_ticks,
            "competing_bind_rate": self.competing_bind_rate,
            "scheduler_kw": self._scheduler_kw_sig,
            # detach_at timestamps inside checkpointed attachments are
            # absolute and derived from this knob — a mismatched restore
            # would silently change grace semantics mid-window
            "detach_grace_s": self.detach_grace_s,
        }

    def save_checkpoint(self, path: str) -> dict:
        """Write a point-in-time snapshot of the hub's state — the etcd
        backup analog (``etcdctl snapshot save``; etcd's snap files are
        opaque binary and so is this one: pickled, because the faithful
        JSON wire forms are deliberately lossy scheduling slices and a
        checkpoint must round-trip EVERY field exactly or restore
        corrupts constraints silently). Returns a small manifest."""
        import pickle

        import dataclasses

        with self.lock:
            state = {"format": "ktpu-checkpoint/1",
                     "revision": self._revision,
                     "clock_t": self.clock.t,
                     "config": self._semantic_config()}
            for attr in self._CHECKPOINT_ATTRS:
                state[attr] = getattr(self, attr)
            # HPA metric sources are live callables (lambdas in every real
            # usage) — unpicklable and meaningless across processes. They
            # are stripped here; restore documents re-wiring (set load_fn
            # after restore, like any live callback).
            state["hpas"] = {
                k: dataclasses.replace(h, load_fn=None)
                for k, h in self.hpas.items()
            }
            blob = pickle.dumps(state)
        with open(path, "wb") as f:
            f.write(blob)
        return {"revision": state["revision"],
                "nodes": len(state["truth_nodes"]),
                "pods": len(state["truth_pods"]),
                "bytes": len(blob)}

    def restore_checkpoint(self, path: str) -> dict:
        """Restore a checkpoint into THIS (freshly constructed) hub —
        the ``etcdctl snapshot restore`` + cold-start analog:

        - object resourceVersions and the global revision are PRESERVED
          (clients' stored rvs stay meaningful);
        - the watch history is empty and the compaction floor sits at
          the restored revision, so any watcher resuming from an old rv
          gets Compacted and relists — exactly post-restore etcd;
        - the scheduler is re-fed through its event-handler surface
          (the informer relist a restarted control plane performs), so
          its cache/queue rebuild from truth;
        - per-node kubelet clocks (bound/started/probe health) come
          back, so pod lifecycle resumes where it stopped;
        - HPA metric sources (``load_fn``) do NOT round-trip (live
          callables): re-wire them after restore or the HPA holds its
          last size.

        Trust boundary: a checkpoint is a pickle stream, and unpickling
        runs constructors — only restore checkpoints YOU saved (the
        reference's etcd snapshots are data-only; this analog is not).
        As a guard, deserialization goes through a restricted Unpickler
        that only resolves framework/stdlib-container classes, so a
        tampered stream referencing e.g. ``os.system`` fails to load
        instead of executing.
        """
        import pickle

        class _CheckpointUnpickler(pickle.Unpickler):
            _SAFE_BUILTINS = frozenset({
                "set", "frozenset", "list", "dict", "tuple", "bytearray",
                "complex", "range", "slice", "object",
            })

            def find_class(self, module, name):
                # dotted names make find_class getattr-WALK from the
                # module (STACK_GLOBAL), so "kubernetes_tpu.x" + name
                # "os.system" would escape the module allowlist through
                # any module-level import — reject them outright
                if "." not in name:
                    if module.split(".")[0] in ("kubernetes_tpu",
                                                "collections"):
                        return super().find_class(module, name)
                    if module == "builtins" and name in self._SAFE_BUILTINS:
                        return super().find_class(module, name)
                raise pickle.UnpicklingError(
                    f"checkpoint references forbidden global "
                    f"{module}.{name} — refusing to load"
                )

        with open(path, "rb") as f:
            state = _CheckpointUnpickler(f).load()
        if state.get("format") != "ktpu-checkpoint/1":
            raise ValueError(f"not a ktpu checkpoint: {path}")
        want = state.get("config", {})
        have = self._semantic_config()
        if want and want != have:
            diff = {k: (want[k], have.get(k))
                    for k in want if want[k] != have.get(k)}
            raise ValueError(
                f"checkpoint/hub config mismatch (saved, this): {diff} — "
                "construct the hub with the same semantics before restoring"
            )
        if self._revision != 0:
            # a non-fresh hub has objects the scheduler already cached;
            # wholesale truth replacement would leave them dangling there
            # (pods assignable to nodes the checkpoint never had) — the
            # same silent-divergence class the config guard refuses
            raise ValueError(
                "restore_checkpoint requires a freshly constructed hub "
                f"(this one is at revision {self._revision})"
            )
        with self.lock:
            self._revision = state["revision"]
            self._compacted_rev = self._revision
            self._history.clear()
            self.clock.t = state["clock_t"]
            for attr in self._CHECKPOINT_ATTRS:
                if attr not in state:
                    # checkpoint predates this attr (same format tag):
                    # keep the fresh hub's empty default instead of a
                    # raw KeyError on a previously-valid file
                    continue
                cur = getattr(self, attr)
                new = state[attr]
                # the admission chain captured the namespaces/priority-
                # class/quota CONTAINERS at construction (default_chain)
                # — those must be updated IN PLACE or admission keeps
                # enforcing against pre-restore state. Same class:
                # RBACAuthorizer reads the cluster_roles/-bindings dicts
                # LIVE and the bootstrap-token authenticator its dict —
                # an authorizer wired before restore must see post-
                # restore state, not the fresh hub's empty containers.
                if attr in ("namespaces", "priority_classes",
                            "cluster_roles", "bootstrap_tokens"):
                    cur.clear()
                    cur.update(new)
                elif attr in ("quotas", "pdbs", "limit_ranges",
                              "cluster_role_bindings"):
                    cur[:] = new  # captured-at-construction containers
                else:
                    setattr(self, attr, new)
            # rebuild the per-node agents (live objects, not state)
            self.kubelets = {name: HollowKubelet(self, name)
                             for name in self.truth_nodes}
            self.proxies = {name: ServiceProxy(name, self.clock)
                            for name in self.truth_nodes}
            for name in self.dead_kubelets:
                if name in self.kubelets:
                    self.kubelets[name].alive = False
            # informer relist into the scheduler
            for node in self.truth_nodes.values():
                self.sched.on_node_add(node)
            for pod in self.truth_pods.values():
                self.sched.on_pod_add(pod)
            if self.pvcs or self.pvs or self.storage_classes:
                self._sync_volume_state()
        return {"revision": self._revision,
                "nodes": len(self.truth_nodes),
                "pods": len(self.truth_pods)}

    # -- pod lifecycle (hollow kubelet SyncPod + prober) -------------------

    def set_app_health(self, pod_key: str, healthy: bool) -> None:
        """Inject the hollow app's probe answer (the fake runtime seam —
        what kubemark's fake CRI would report)."""
        self.app_health[pod_key] = healthy

    def sync_pod_lifecycle(self) -> None:
        """One SyncPod pass over all bound pods (kuberuntime_manager.go:558
        compressed to phase hops; prober/worker.go for readiness):

        - Pending + bound on a live kubelet -> Running (status MODIFIED);
        - probed pods: Ready once past initialDelay AND the injected app
          health is good; a later health flip flips Ready back — the
          probe-failure path the endpoints controller must observe;
        - probe-less pods never write Ready (they are ready-by-default,
          see proxy.pod_endpoint_ready).

        - run-to-completion pods (``run_duration_s``) hop Running ->
          Succeeded after their duration and STAY in the store — the
          kubelet never deletes API pods; terminal cleanup is the pod
          GC controller's (reconcile_pod_gc). The scheduler observes
          the hop as a DELETE (its informer's
          ``status.phase!=Succeeded,...`` field selector, factory.go
          NewPodInformer) and the node's capacity is released.

        One O(P) scan for all nodes, like kubelet_admission."""
        import dataclasses

        from kubernetes_tpu.api.types import (
            POD_PENDING,
            POD_RUNNING,
            POD_SUCCEEDED,
            is_pod_terminated,
        )

        for key, p in list(self.truth_pods.items()):
            if not p.node_name:
                continue
            if is_pod_terminated(p):
                if p.deletion_timestamp:
                    # ran to completion while a graceful delete was
                    # pending: the kill is already complete — finish the
                    # delete now, independent of kubelet liveness
                    self.delete_pod(key)
                continue
            kl = self.kubelets.get(p.node_name)
            if kl is None or not kl.alive:
                continue
            if (p.deletion_timestamp
                    and self.clock.t - p.deletion_timestamp
                    >= self._term_grace.get(key, 30.0)):
                # graceful kill complete: the kubelet's status sync
                # triggers the final grace-0 delete (status_manager
                # syncPod -> deletePod)
                self.delete_pod(key)
                continue
            if (p.phase == POD_RUNNING and p.run_duration_s is not None
                    and self._started_at.get(key) is not None
                    and self.clock.t - self._started_at[key]
                    >= p.run_duration_s):
                done = dataclasses.replace(p, phase=POD_SUCCEEDED,
                                           ready=False)
                self.truth_pods[key] = done
                self._commit(f"pods/{key}", "MODIFIED", done)
                self._terminal_gone.add(key)
                self._emit(f"pods/{key}",
                           lambda pod=p: self.sched.on_pod_delete(pod))
                continue
            changes = {}
            if p.phase == POD_PENDING:
                changes["phase"] = POD_RUNNING
                self._started_at[key] = self.clock.t
            if p.readiness_probe is not None:
                started = self._started_at.get(key)
                ready = (
                    started is not None
                    and self.clock.t - started >= p.readiness_probe.initial_delay_s
                    and self.app_health.get(key, True)
                )
                if ready != p.ready:
                    changes["ready"] = ready
            if changes:
                new = dataclasses.replace(p, **changes)
                self.truth_pods[key] = new
                self._commit(f"pods/{key}", "MODIFIED", new)
                self._emit(f"pods/{key}",
                           lambda old=p, new=new: self.sched.on_pod_update(
                               old, new))

    # -- volume API + PV controller ----------------------------------------

    def add_storage_class(self, sc) -> None:
        self.storage_classes[sc.name] = sc
        self._commit(f"storageclasses/{sc.name}", "ADDED", sc)
        self._sync_volume_state()

    def add_pv(self, pv) -> None:
        self.pvs[pv.name] = pv
        self._commit(f"persistentvolumes/{pv.name}", "ADDED", pv)
        self._sync_volume_state()

    def add_pvc(self, pvc) -> None:
        self.pvcs[f"{pvc.namespace}/{pvc.name}"] = pvc
        self._commit(f"persistentvolumeclaims/{pvc.namespace}/{pvc.name}",
                     "ADDED", pvc)
        self._sync_volume_state()

    def _sync_volume_state(self) -> None:
        """Push the hub's volume truth into the scheduler's listers (the
        PV/PVC/StorageClass informer feed) — invalidates the snapshot and
        resweeps unschedulables, scheduler.set_volume_state semantics."""
        self.sched.set_volume_state(
            list(self.pvcs.values()), list(self.pvs.values()),
            list(self.storage_classes.values()),
        )

    def delete_pvc(self, key: str) -> bool:
        """DELETE of a PVC under the pvc-protection finalizer
        (pvc_protection_controller.go): an in-use claim (some live,
        non-terminal pod references it by name) is marked terminating
        and kept; the protection pass finalizes the removal when the
        last user is gone. Returns True when the object was removed
        NOW, False when protection deferred it."""
        pvc = self.pvcs.get(key)
        if pvc is None:
            return False
        if self._pvc_in_use(key):
            if not pvc.deletion_timestamp:
                pvc.deletion_timestamp = self.clock.t or 1e-6
                self._commit(f"persistentvolumeclaims/{key}",
                             "MODIFIED", pvc)
            return False
        self._finalize_pvc_delete(key)
        return True

    def delete_pv(self, name: str) -> bool:
        """DELETE of a PV under the pv-protection finalizer
        (pv_protection_controller.go): a claimed PV stays terminating
        until its claim releases it."""
        pv = self.pvs.get(name)
        if pv is None:
            return False
        if pv.claim_ref:
            if not pv.deletion_timestamp:
                pv.deletion_timestamp = self.clock.t or 1e-6
                self._commit(f"persistentvolumes/{name}", "MODIFIED", pv)
            return False
        del self.pvs[name]
        self._commit(f"persistentvolumes/{name}", "DELETED", None)
        self._sync_volume_state()
        return True

    def _pvc_in_use(self, key: str) -> bool:
        from kubernetes_tpu.api.types import is_pod_terminated

        ns, name = key.split("/", 1)
        return any(
            p.namespace == ns and not is_pod_terminated(p)
            and any(v.pvc == name for v in p.volumes)
            for p in self.truth_pods.values()
        )

    def _finalize_pvc_delete(self, key: str) -> None:
        pvc = self.pvcs.pop(key)
        if pvc.volume_name and pvc.volume_name in self.pvs:
            # Released -> Available (the hollow reclaim policy); a PV
            # waiting on pv-protection may now finalize too
            self.pvs[pvc.volume_name].claim_ref = ""
            self._commit(f"persistentvolumes/{pvc.volume_name}",
                         "MODIFIED", self.pvs[pvc.volume_name])
        self._commit(f"persistentvolumeclaims/{key}", "DELETED", None)
        self._sync_volume_state()

    def reconcile_volume_protection(self) -> None:
        """The two protection controllers' finalizer passes: terminating
        PVCs whose last pod user is gone are removed (releasing their
        PV); terminating PVs whose claim released them are removed."""
        for key in [k for k, c in self.pvcs.items()
                    if c.deletion_timestamp and not self._pvc_in_use(k)]:
            self._finalize_pvc_delete(key)
        for name in [n for n, pv in self.pvs.items()
                     if pv.deletion_timestamp and not pv.claim_ref]:
            del self.pvs[name]
            self._commit(f"persistentvolumes/{name}", "DELETED", None)
            self._sync_volume_state()

    def _commit_volume_bind(self, pvc, pv) -> None:
        """The scheduler's BindPodVolumes write, routed through the hub
        store: same in-place object mutation as the default writer plus
        revision bumps + watch events for both objects."""
        pv.claim_ref = f"{pvc.namespace}/{pvc.name}"
        pvc.volume_name = pv.name
        self._commit(f"persistentvolumes/{pv.name}", "MODIFIED", pv)
        self._commit(f"persistentvolumeclaims/{pvc.namespace}/{pvc.name}",
                     "MODIFIED", pvc)

    #: TTL controller boundary table (pkg/controller/ttl/ttl_controller
    #: .go:102 ttlBoundaries): (size_min, size_max, ttl_seconds) with
    #: overlapping min/max = the reference's hysteresis — the step only
    #: moves when the count leaves the CURRENT band, so oscillation at a
    #: boundary doesn't thrash every node's annotation
    TTL_BOUNDARIES = ((0, 100, 0), (90, 500, 15), (450, 1000, 30),
                      (900, 2000, 60), (1800, 10000, 300),
                      (9000, 1 << 31, 600))
    TTL_ANNOTATION = "node.alpha.kubernetes.io/ttl"

    def reconcile_ttl(self) -> None:
        """The TTL controller: annotate every node with the secret/
        configmap cache TTL kubelets should use, scaled to cluster size
        with hysteresis (ttl_controller.go:141,:182)."""
        import dataclasses

        count = len(self.truth_nodes)
        while (self._ttl_step + 1 < len(self.TTL_BOUNDARIES)
               and count > self.TTL_BOUNDARIES[self._ttl_step][1]):
            self._ttl_step += 1
        while (self._ttl_step > 0
               and count < self.TTL_BOUNDARIES[self._ttl_step][0]):
            self._ttl_step -= 1
        want = str(self.TTL_BOUNDARIES[self._ttl_step][2])
        for node in list(self.truth_nodes.values()):
            if node.annotations.get(self.TTL_ANNOTATION) != want:
                new = dataclasses.replace(
                    node, annotations={**node.annotations,
                                       self.TTL_ANNOTATION: want})
                self._update_node(new)

    def reconcile_node_ipam(self) -> None:
        """The nodeipam range allocator (ipam/range_allocator.go): carve
        one per-node podCIDR from the cluster CIDR; release a deleted
        node's block back to the set; exhaustion surfaces as a counter
        (the reference emits CIDRNotAvailable), never a crash."""
        import dataclasses
        import ipaddress

        if self._cidr_subnets is None:
            net = ipaddress.ip_network(self.cluster_cidr)
            self._cidr_subnets = list(
                net.subnets(new_prefix=self.node_cidr_prefix))
            self._cidr_index = {str(s): i
                                for i, s in enumerate(self._cidr_subnets)}
            self._cidr_next = 0
            self._cidr_free: List[int] = []
        live = set(self.truth_nodes)
        for name in [n for n in self._cidr_alloc if n not in live]:
            self._cidr_free.append(self._cidr_alloc.pop(name))
        used = set(self._cidr_alloc.values())
        for name, node in list(self.truth_nodes.items()):
            if node.pod_cidr:
                # OCCUPY a pre-set CIDR (range_allocator occupyCIDRs): a
                # node ingested with spec.podCIDR already assigned must
                # claim its block or the allocator would hand the same
                # subnet to the next CIDR-less node
                idx = self._cidr_index.get(node.pod_cidr)
                if idx is not None and name not in self._cidr_alloc:
                    self._cidr_alloc[name] = idx
                    used.add(idx)
                continue
            if name in self._cidr_alloc:
                # same-name delete+re-add (or a write that dropped the
                # field): re-stamp the held block instead of leaking it
                idx = self._cidr_alloc[name]
            else:
                idx = None
                while self._cidr_free:
                    cand = self._cidr_free.pop()
                    if cand not in used:
                        idx = cand
                        break
                if idx is None:
                    while (self._cidr_next < len(self._cidr_subnets)
                           and self._cidr_next in used):
                        self._cidr_next += 1
                    if self._cidr_next < len(self._cidr_subnets):
                        idx = self._cidr_next
                        self._cidr_next += 1
                    else:
                        self.cidr_exhausted_total += 1
                        continue
                self._cidr_alloc[name] = idx
                used.add(idx)
            self._update_node(dataclasses.replace(
                node, pod_cidr=str(self._cidr_subnets[idx])))

    def reconcile_service_accounts(self) -> None:
        """The serviceaccounts + tokens controller pair
        (pkg/controller/serviceaccount/serviceaccounts_controller.go:46,
        tokens_controller.go:73): every ACTIVE namespace carries a
        "default" ServiceAccount, every ServiceAccount carries exactly
        one minted bearer token, and a namespace leaving Active revokes
        both — committed through the versioned store so identity churn
        is watchable like any other object."""
        active = {name for name, ns in self.namespaces.items()
                  if ns.phase == NS_ACTIVE}
        for ns in active:
            sa = ServiceAccount("default", namespace=ns)
            if sa.key() not in self.service_accounts:
                self.service_accounts[sa.key()] = sa
                self._commit(f"serviceaccounts/{sa.key()}", "ADDED", sa)
        # revoke: SAs of gone/terminating namespaces
        for key, sa in list(self.service_accounts.items()):
            if sa.namespace not in active:
                del self.service_accounts[key]
                self._commit(f"serviceaccounts/{key}", "DELETED", None)
        live_keys = set(self.service_accounts)
        for token, key in list(self.sa_tokens.items()):
            if key not in live_keys:
                del self.sa_tokens[token]
        minted = set(self.sa_tokens.values())
        for key in live_keys - minted:
            # opaque, unguessable-enough for the hollow plane; the mint
            # revision makes a re-created namespace's token DIFFERENT
            # from its predecessor's (revocation must stick)
            token = f"sa-token-{key.replace('/', '-')}-{self._revision}"
            self.sa_tokens[token] = key

    def service_account_token(self, namespace: str,
                              name: str = "default") -> str:
        """The minted token for one SA (what a pod's projected token
        volume would carry). KeyError when the controller hasn't minted
        it (namespace missing/terminating)."""
        key = f"{namespace}/{name}"
        for token, k in self.sa_tokens.items():
            if k == key:
                return token
        raise KeyError(f"no token minted for serviceaccount {key!r}")

    def sa_token_user(self, token: str):
        """Live lookup for the authenticators (REST:
        auth.ServiceAccountAuthenticator; gRPC: serve_grpc's callable
        token): UserInfo for a valid token, None for unknown/revoked."""
        key = self.sa_tokens.get(token)
        if key is None:
            return None
        ns, name = key.split("/", 1)
        from kubernetes_tpu.auth import service_account_user

        return service_account_user(ns, name)

    def bootstrap_token_user(self, credential: str):
        """The bootstrap-token authenticator
        (plugin/pkg/auth/authenticator/token/bootstrap): a live,
        authentication-usage, unexpired ``id.secret`` token
        authenticates as ``system:bootstrap:<id>`` in the
        system:bootstrappers group — the identity whose CSRs the
        approver's nodeclient binding admits."""
        tid, dot, secret = credential.partition(".")
        if not dot:
            return None
        tok = self.bootstrap_tokens.get(tid)
        if (tok is None or tok.secret != secret
                or "authentication" not in tok.usages
                or tok.expired(self.clock.t)):
            return None
        from kubernetes_tpu.auth import UserInfo
        from kubernetes_tpu.certificates import BOOTSTRAPPERS_GROUP

        return UserInfo(name=f"system:bootstrap:{tid}",
                        groups=(BOOTSTRAPPERS_GROUP,))

    def credential_user(self, credential: str):
        """One lookup over EVERY live hub-minted identity — SA tokens
        (tokens controller), signed node certificates (CSR signer), and
        bootstrap tokens. Plug into auth.ServiceAccountAuthenticator as
        ``lookup`` to accept all three on one seam."""
        return (self.sa_token_user(credential)
                or self.cert_user(credential)
                or self.bootstrap_token_user(credential))

    # -- certificates.k8s.io (kubernetes_tpu/certificates.py) --------------

    def create_csr(self, csr) -> None:
        """CSR create (the apiserver stamps spec.username from the
        authenticated requestor; callers of this seam have already
        authenticated — node_bootstrap_csr builds the right shape)."""
        if csr.name in self.csrs:
            raise ValueError(
                f'certificatesigningrequests "{csr.name}" already exists')
        csr.created_at = self.clock.t
        self.csrs[csr.name] = csr
        self._commit(f"certificatesigningrequests/{csr.name}", "ADDED", csr)

    def cert_user(self, credential: str):
        """Live lookup for the authn chain: UserInfo for a valid signed
        node credential, None for unknown/expired — the client-cert
        verification path, modeled as a bearer credential (see
        kubernetes_tpu/certificates.py module docstring)."""
        entry = self.signed_certs.get(credential)
        if entry is None:
            return None
        user, not_after = entry
        if self.clock.t >= not_after:
            return None
        return user

    def put_configmap(self, namespace: str, name: str, data: dict) -> None:
        key = f"{namespace}/{name}"
        etype = "MODIFIED" if key in self.configmaps else "ADDED"
        self.configmaps[key] = {"data": dict(data)}
        self._commit(f"configmaps/{key}", etype, self.configmaps[key])

    def delete_configmap(self, key: str) -> None:
        if self.configmaps.pop(key, None) is not None:
            self._commit(f"configmaps/{key}", "DELETED", None)

    def _desired_attachments(self) -> Dict[str, set]:
        """Desired state: volume identity -> set of nodes with bound pods
        whose volumes resolve to an attachable backend (in-tree PD kinds
        or CSI) — the desired_state_of_world populator
        (attach_detach_controller.go podAdd/Update -> desiredStateOfWorld).
        A SET, not last-writer-wins: several live claimants of one PV on
        different nodes are a real state the reconciler must refuse to
        flap on (keep the existing attachment, never steal it). Inline
        attachable volumes count too (identity "inline:kind:handle");
        PVC-backed ones use the PV name so residue can re-resolve."""
        from kubernetes_tpu.volumes import (
            PD_FILTER_INDEX,
            attachable_tokens,
        )

        want: Dict[str, set] = {}
        for p in self.truth_pods.values():
            if not p.node_name or not p.volumes:
                continue
            for v in p.volumes:
                if v.pvc:
                    pvc = self.pvcs.get(f"{p.namespace}/{v.pvc}")
                    pv = (self.pvs.get(pvc.volume_name)
                          if pvc is not None and pvc.volume_name else None)
                    if pv is None:
                        continue  # unbound/missing: nothing to attach yet
                    if attachable_tokens(pv):
                        want.setdefault(pv.name, set()).add(p.node_name)
                elif v.kind in PD_FILTER_INDEX:
                    want.setdefault(f"inline:{v.kind}:{v.handle}",
                                    set()).add(p.node_name)
        return want

    def reconcile_attachments(self) -> None:
        """The attach-detach reconciler (reconciler/reconciler.go):
        converge actual attachments toward desired.

        - attach when desired and unattached;
        - a volume desired on a NEW node while still attached elsewhere
          waits for the old attachment to detach first (the single-
          attach / multi-attach guard — the reference refuses to attach
          an RWO volume to a second node and surfaces FailedAttachVolume
          until the detach completes);
        - a no-longer-desired attachment enters ``detaching`` and is
          removed only after ``detach_grace_s`` (maxWaitForUnmount
          analog) — during the grace it still occupies an attach-limit
          slot, which the scheduler sees via the residue feed;
        - a volume that becomes desired again mid-grace on the SAME node
          re-attaches in place (the reconciler cancels the detach).
        """
        want = self._desired_attachments()
        t = self.clock.t
        # expiry/detach FIRST: a grace window that ends this pass frees
        # the volume for the attach loop below (one-pass convergence;
        # attach-after-expiry ordering also keeps the oracle honest)
        for vol, rec in list(self.attachments.items()):
            desired_here = rec.node in want.get(vol, ())
            if not desired_here:
                if rec.state == "attached":
                    rec.state = "detaching"
                    rec.detach_at = t + self.detach_grace_s
                elif t >= rec.detach_at:
                    del self.attachments[vol]
                    self.detaches_total += 1
        for vol, nodes in want.items():
            rec = self.attachments.get(vol)
            if rec is None:
                # deterministic choice among claimant nodes (several
                # claimants on one unattached volume: lowest name wins,
                # the rest wait on the multi-attach guard)
                self.attachments[vol] = Attachment(volume=vol,
                                                   node=min(nodes),
                                                   state="attached")
                self.attaches_total += 1
            elif rec.node in nodes:
                if rec.state == "detaching":
                    rec.state = "attached"  # needed again: cancel detach
                    rec.detach_at = 0.0
            # rec.node not in nodes: multi-attach guard — the existing
            # attachment is never stolen; it detaches via the loop above
            # (not desired there) and a later pass attaches the claimant
        # residue = attachments the scheduler cannot derive from live
        # bound pods; push only on change (each push invalidates the
        # snapshot and resweeps unschedulables)
        residue: Dict[str, tuple] = {}
        for vol, rec in self.attachments.items():
            if (rec.node not in want.get(vol, ())
                    and not vol.startswith("inline:")):
                residue[rec.node] = residue.get(rec.node, ()) + (vol,)
        if residue != self._last_residue:
            self._last_residue = residue
            self.sched.set_attached_residue(residue)

    def check_attachment_invariants(self) -> None:
        """Fuzz oracle: (a) single-attach — by construction one record
        per volume, asserted against desired duplication; (b) every
        bound pod's attachable volumes are attached to ITS node unless
        blocked by a grace-period detach elsewhere (the multi-attach
        wait); (c) no attachment without a desiring pod outlives the
        grace window.

        Converge-then-check (check_consistency's settle analog): binds
        land at the END of a step, after that step's reconcile pass, so
        the reconciler runs once more here — the invariants are about
        the CONVERGED reconciler, not its one-tick lag."""
        self.reconcile_attachments()
        want = self._desired_attachments()
        t = self.clock.t
        for vol, nodes in want.items():
            rec = self.attachments.get(vol)
            assert rec is not None, f"desired volume {vol} never attached"
            if rec.node not in nodes:
                assert rec.state == "detaching", (
                    f"{vol} attached to {rec.node} but desired on {nodes} "
                    "without a pending detach (multi-attach guard broken)")
        for vol, rec in self.attachments.items():
            if rec.node not in want.get(vol, ()):
                assert rec.state == "detaching", (
                    f"stale attachment {vol}@{rec.node} not detaching")
                assert rec.detach_at <= t + self.detach_grace_s + 1e-6, (
                    f"{vol} grace window exceeds detach_grace_s")

    def reconcile_volumes(self) -> None:
        """The persistent-volume binder controller pass
        (pv_controller.go:236 syncUnboundClaim): bind each pending
        IMMEDIATE-mode PVC to an available compatible PV now; a
        WaitForFirstConsumer claim waits for the scheduler (delayed
        binding — its syncUnboundClaim branch checks the selected-node
        annotation and defers). Newly-satisfiable pods wake via the
        volume-state resweep."""
        from kubernetes_tpu.api.types import BINDING_WAIT_FOR_FIRST_CONSUMER

        bound_any = False
        for key, pvc in self.pvcs.items():
            if pvc.volume_name or pvc.deletion_timestamp:
                continue  # bound, or terminating under pvc-protection
            sc = self.storage_classes.get(pvc.storage_class)
            if (sc is not None
                    and sc.binding_mode == BINDING_WAIT_FOR_FIRST_CONSUMER):
                continue  # the scheduler owns delayed binding
            assumed = self.sched.cache.packer.vol_state.assumed_claims
            pick = None
            for pv in self.pvs.values():
                if (not pv.claim_ref and not pv.deletion_timestamp
                        and pv.name not in assumed
                        and pv.storage_class == pvc.storage_class):
                    pick = pv
                    break
            if pick is not None:
                self._commit_volume_bind(pvc, pick)
                bound_any = True
        if bound_any:
            self._sync_volume_state()

    def gc_owner_graph(self) -> None:
        """The ownerReference dependency-graph GC
        (pkg/controller/garbagecollector/garbagecollector.go:65),
        compressed to the hub's kind registry: an object whose every
        controller owner no longer exists is background-deleted. Edges:
        Pod -> ReplicaSet/Job/DaemonSet/StatefulSet (pod.owner_refs),
        Job -> CronJob (job.owner), ReplicaSet -> Deployment (the
        rs.owner cascade lives in reconcile_controllers). Adoption of
        matching orphans is a deliberate non-goal."""
        for name in [n for n, j in self.jobs.items()
                     if j.owner and j.owner not in self.cronjobs]:
            j = self.jobs.pop(name)
            for key in list(j.active):
                self.delete_pod(key)
        kinds = self._owner_kinds()
        for key, p in list(self.truth_pods.items()):
            refs = p.owner_refs
            if refs and not any(r.name in kinds.get(r.kind, {})
                                for r in refs):
                self.delete_pod(key)

    def _owner_kinds(self) -> Dict[str, dict]:
        return {
            "Deployment": self.deployments,
            "ReplicaSet": self.replicasets,
            "ReplicationController": self.replication_controllers,
            "Job": self.jobs,
            "DaemonSet": self.daemonsets,
            "StatefulSet": self.statefulsets,
            "CronJob": self.cronjobs,
        }

    def gc_orphaned(self) -> None:
        """Delete truth pods bound to nodes that no longer exist — the
        node-lifecycle-controller/GC eviction a real cluster runs when a
        binding lands on a node that died meanwhile (the apiserver accepts
        such bindings; assignPod does not check node existence)."""
        for key, p in list(self.truth_pods.items()):
            if p.node_name and p.node_name not in self.truth_nodes:
                self.delete_pod(key)
        self.kubelet_admission()

    def kubelet_admission(self) -> None:
        """Run every node's kubelet admission pass (the per-node logic
        lives in :class:`HollowKubelet.admit`, lifecycle/predicate.go
        analog). Called from gc_orphaned so consistency holds even
        between sync ticks; runs for dead kubelets too — the truth
        invariant (no over-committed node) predates the agent split."""
        by_node: Dict[str, List[str]] = {}
        for key, p in self.truth_pods.items():
            if p.node_name:
                by_node.setdefault(p.node_name, []).append(key)
        for name, kl in list(self.kubelets.items()):
            kl.admit(by_node.get(name, []))

    def mark_terminating(self, key: str, grace_s: float = 30.0) -> None:
        """Graceful DELETE: stamp metadata.deletionTimestamp and let the
        owning kubelet finish the kill after ``grace_s`` (the apiserver's
        graceful-deletion path, registry/core/pod/strategy.go
        CheckGracefulDelete). An UNBOUND pod has no kubelet to confirm
        termination — that leak is exactly what the pod GC's
        gcUnscheduledTerminating half collects (gc_controller.go:172)."""
        import dataclasses

        from kubernetes_tpu.api.types import is_pod_terminated

        pod = self.truth_pods.get(key)
        if pod is None or pod.deletion_timestamp:
            return
        if is_pod_terminated(pod):
            # registry CheckGracefulDelete (pod/strategy.go): a pod whose
            # containers have exited deletes immediately — grace is for
            # running workloads, and no kubelet kill is pending
            self.delete_pod(key)
            return
        terminating = dataclasses.replace(
            pod, deletion_timestamp=self.clock.t or 1e-6)
        self.truth_pods[key] = terminating
        self._term_grace[key] = grace_s
        self._commit(f"pods/{key}", "MODIFIED", terminating)
        self._emit(f"pods/{key}",
                   lambda old=pod, new=terminating:
                   self.sched.on_pod_update(old, new))

    def reconcile_pod_gc(self) -> None:
        """The pod GC controller (podgc/gc_controller.go:94 gc), minus
        the orphan half which lives in :meth:`gc_orphaned` (it doubles
        as the consistency oracle's precondition so it runs more often):

        - ``terminated_pod_threshold`` > 0: keep at most that many
          terminal (Succeeded/Failed) pods, deleting oldest-by-creation
          first (gc_controller.go:108 gcTerminated sorts
          byCreationTimestamp and deletes count-threshold);
        - unscheduled terminating pods (deletionTimestamp set, no node)
          are force-deleted — no kubelet will ever confirm their
          termination (gc_controller.go:172 gcUnscheduledTerminating).
        """
        from kubernetes_tpu.api.types import is_pod_terminated

        if self.terminated_pod_threshold > 0:
            terminated = [k for k, p in self.truth_pods.items()
                          if is_pod_terminated(p)]
            excess = len(terminated) - self.terminated_pod_threshold
            if excess > 0:
                terminated.sort(
                    key=lambda k: (self._created_at.get(k, 0.0), k))
                for k in terminated[:excess]:
                    self.delete_pod(k)
                    self.pods_gced_total += 1
        for key, p in list(self.truth_pods.items()):
            if p.deletion_timestamp and not p.node_name:
                self.delete_pod(key)
                self.pods_gced_total += 1

    def reconcile_ttl_after_finished(self) -> None:
        """The TTL-after-finished controller
        (ttlafterfinished_controller.go:186 processJob): delete a
        finished Job once ``ttl_seconds_after_finished`` has elapsed
        since its completion time. The Job's leftover pods cascade
        through the ownerRef GC graph (their Job owner is gone); a
        spawning CronJob's bookkeeping entry is dropped so its
        concurrency accounting can't see a ghost."""
        for name in list(self.jobs):
            j = self.jobs[name]
            if (j.ttl_seconds_after_finished is None or not j.done()
                    or j.finished_at is None):
                continue
            if self.clock.t - j.finished_at < j.ttl_seconds_after_finished:
                continue
            del self.jobs[name]
            for cj in self.cronjobs.values():
                if name in cj.spawned:
                    cj.spawned.remove(name)
            self.record_controller_event(
                "SuccessfulDelete", f"default/{name}",
                f"Deleted job {name} past its "
                f"ttlSecondsAfterFinished={j.ttl_seconds_after_finished:g}",
                involved_kind="Job")

    def attach_cloud(self, cloud) -> None:
        """Run the cluster under an external cloud provider: the cloud
        node controller initializes uninitialized-tainted nodes and
        removes nodes whose instance died; the service controller
        provisions LoadBalancer services; the route controller installs
        per-podCIDR cloud routes (kubernetes_tpu/cloud.py)."""
        from kubernetes_tpu.cloud import RouteController, ServiceLBController

        self.cloud_controller = CloudNodeController(self, cloud)
        self.service_lb_controller = ServiceLBController(self, cloud)
        self.route_controller = RouteController(self, cloud)

    # -- namespaces / priority classes / quotas (admission seam) -------------

    def add_namespace(self, name: str) -> None:
        self.namespaces[name] = Namespace(name, NS_ACTIVE)
        self._commit(f"namespaces/{name}", "ADDED", self.namespaces[name])

    #: namespaces every entry point refuses to delete (the apiserver
    #: protects these; one guard here so no seam can bypass it)
    PROTECTED_NAMESPACES = ("default", "kube-system", "kube-public")

    def terminate_namespace(self, name: str) -> None:
        """Mark Terminating; the namespace-controller pass in step() then
        drains its content and removes it (pkg/controller/namespace).
        Raises ValueError for protected system namespaces."""
        if name in self.PROTECTED_NAMESPACES:
            raise ValueError(f'namespaces "{name}" is protected')
        ns = self.namespaces.get(name)
        if ns is not None:
            ns.phase = NS_TERMINATING
            self._commit(f"namespaces/{name}", "MODIFIED", ns)

    def add_priority_class(self, cls) -> None:
        self.priority_classes[cls.name] = cls

    def add_quota(self, quota) -> None:
        self.quotas.append(quota)
        self.quota_controller.reconcile()

    def add_limit_range(self, lr) -> None:
        """Install a LimitRange; the admission chain's LimitRanger reads
        the live container (defaults/bounds apply to the NEXT create)."""
        self.limit_ranges.append(lr)

    def reconcile_namespaces(self) -> None:
        """The namespace controller's deletion pass: drain EVERY
        namespaced resource (pods, services+endpoints, events, leases,
        PVCs — pkg/controller/namespace deletes all namespaced content
        via discovery), then remove the namespace once empty."""
        for name, ns in list(self.namespaces.items()):
            if ns.phase != NS_TERMINATING:
                continue
            prefix = f"{name}/"
            remaining = [k for k, p in self.truth_pods.items()
                         if p.namespace == name]
            for key in remaining:
                self.delete_pod(key)
            for key in [k for k in self.services if k.startswith(prefix)]:
                self.delete_service(key)
            for key in [k for k in self.endpoints if k.startswith(prefix)]:
                self.delete_endpoints(key)
            for key in [k for k in self.events_v1 if k.startswith(prefix)]:
                del self.events_v1[key]
                self._commit(f"events/{key}", "DELETED", None)
            for key in [k for k in self.leases if k.startswith(prefix)]:
                del self.leases[key]
                self._commit(f"leases/{key}", "DELETED", None)
            for key in [k for k in self.configmaps if k.startswith(prefix)]:
                self.delete_configmap(key)
            # namespace pods were deleted above, so no pvc-protection
            # deferral applies — finalize through the one teardown path
            # (release PV claimRef, commit both deletes, volume resync)
            for key in [k for k in self.pvcs if k.startswith(prefix)]:
                self._finalize_pvc_delete(key)
            if not remaining:
                del self.namespaces[name]
                self._commit(f"namespaces/{name}", "DELETED", None)

    # -- services / endpoints (kube-proxy seam) ------------------------------

    def add_service(self, svc) -> None:
        """Create a Service; the hub assigns the ClusterIP like the
        apiserver's service-ip allocator (pkg/registry/core/service),
        and NodePort/LoadBalancer services get node ports from the
        port allocator for every port that didn't pick its own."""
        import dataclasses

        wants_node_ports = getattr(svc, "type", "ClusterIP") in (
            "NodePort", "LoadBalancer")
        # Every allocation this create performs is tracked and rolled
        # back if ANY later step rejects it (ROADMAP bug (c): explicit
        # node-port reservations used to leak when the ClusterIP reserve
        # or a later allocator exhaustion raised) — the reference
        # apiserver releases allocations on failed create the same way.
        reserved_ports = []  # explicit + auto node ports taken here
        allocated_ip = ""  # ClusterIP WE allocated (cleared on rollback)
        reserved_ip = ""  # caller VIP WE reserved (released, field kept)
        try:
            if wants_node_ports:
                # validate explicit picks FIRST (a duplicate raises the
                # apiserver's 'already allocated' 422 analog); a port
                # repeated WITHIN the service is the same 422 (it would
                # double-release on delete otherwise)
                seen = set()
                for p in svc.ports:
                    if p.node_port:
                        if p.node_port in seen:
                            raise ValueError(
                                f"provided node-port range {p.node_port} "
                                "is already allocated (duplicated within "
                                "the service)")
                        seen.add(p.node_port)
                        self.nodeport_alloc.reserve(p.node_port)
                        reserved_ports.append(p.node_port)
            if not svc.cluster_ip:
                svc.cluster_ip = self.ip_alloc.allocate()
                allocated_ip = svc.cluster_ip
            else:
                self.ip_alloc.reserve(svc.cluster_ip)
                reserved_ip = svc.cluster_ip
            if wants_node_ports:
                ports = []
                for p in svc.ports:
                    if not p.node_port:
                        auto = self.nodeport_alloc.allocate()
                        reserved_ports.append(auto)
                        p = dataclasses.replace(p, node_port=auto)
                    ports.append(p)
                svc.ports = tuple(ports)
        except Exception:
            for n in reserved_ports:
                self.nodeport_alloc.release(n)
            if allocated_ip:
                self.ip_alloc.release(allocated_ip)
                svc.cluster_ip = ""
            elif reserved_ip:
                # caller-specified VIP: release OUR reservation (the CIDR
                # slot must not leak) but keep the field — it is the
                # caller's requested spec, not something we minted
                self.ip_alloc.release(reserved_ip)
            raise
        self.services[svc.key()] = svc
        self._commit(f"services/{svc.key()}", "ADDED", svc)

    def delete_service(self, key: str) -> None:
        svc = self.services.pop(key, None)
        if svc is not None:
            if svc.cluster_ip:
                self.ip_alloc.release(svc.cluster_ip)
            for p in svc.ports:
                if p.node_port:
                    self.nodeport_alloc.release(p.node_port)
            self._commit(f"services/{key}", "DELETED", None)

    def put_endpoints(self, ep) -> None:
        verb = "MODIFIED" if ep.key() in self.endpoints else "ADDED"
        self.endpoints[ep.key()] = ep
        self._commit(f"endpoints/{ep.key()}", verb, ep)

    def delete_endpoints(self, key: str) -> None:
        if self.endpoints.pop(key, None) is not None:
            self._commit(f"endpoints/{key}", "DELETED", None)

    def sync_proxies(self) -> None:
        """Every node's proxy recompiles its rule table from the current
        (services, endpoints) snapshot — the per-node syncProxyRules pass
        kubemark's hollow-proxy runs against fake iptables."""
        for pr in self.proxies.values():
            pr.sync(self.services, self.endpoints)

    # -- controllers / churn ------------------------------------------------

    def add_replicaset(self, rs: ReplicaSet) -> None:
        self.replicasets[rs.name] = rs

    def add_deployment(self, d: Deployment) -> None:
        self.deployments[d.name] = d

    def scale_deployment(self, name: str, replicas: int) -> None:
        d = self.deployments.get(name)
        if d is None:
            raise KeyError(f"deployment {name!r} not found")
        d.replicas = replicas

    def delete_deployment(self, name: str) -> None:
        """Cascading delete: the GC pass removes the orphaned ReplicaSet
        and its pods (ownerReference chain, pkg/controller/garbagecollector
        foreground deletion)."""
        self.deployments.pop(name, None)

    def add_job(self, j: Job) -> None:
        self.jobs[j.name] = j

    def add_daemonset(self, ds: DaemonSet) -> None:
        self.daemonsets[ds.name] = ds

    def delete_daemonset(self, name: str) -> None:
        """Foreground cascade: removing the DaemonSet deletes its pods
        (the GC the ownerReference chain drives in the reference)."""
        ds = self.daemonsets.pop(name, None)
        if ds is not None:
            for key in list(ds.live):
                self.delete_pod(key)

    def add_cronjob(self, cj: CronJob) -> None:
        cj.next_run = self.clock.t
        self.cronjobs[cj.name] = cj

    def add_hpa(self, hpa: HorizontalPodAutoscaler) -> None:
        self.hpas[hpa.name] = hpa

    def add_statefulset(self, ss: StatefulSet) -> None:
        self.statefulsets[ss.name] = ss

    def scale_statefulset(self, name: str, replicas: int) -> None:
        self.statefulsets[name].replicas = replicas

    def delete_statefulset(self, name: str) -> None:
        if self.statefulsets.pop(name, None) is not None:
            for key, p in list(self.truth_pods.items()):
                if p.labels.get("ss") == name:
                    self.delete_pod(key)

    def reconcile_history(self) -> None:
        """The history controller (pkg/controller/history
        ControllerRevisions): snapshot every DS/STS template revision,
        GC beyond revisionHistoryLimit (oldest first, never the live
        revision), drop revisions of deleted owners."""
        owners = (
            [("DaemonSet", n, d) for n, d in self.daemonsets.items()]
            + [("StatefulSet", n, s) for n, s in self.statefulsets.items()]
        )
        live = set()
        for kind, name, obj in owners:
            # drain revisions recorded synchronously at rollout() time —
            # a revision current for zero passes is still history
            for rev, data in obj.pending_revisions:
                pkey = f"{kind}/{name}/{rev}"
                if pkey not in self.controller_revisions:
                    self.controller_revisions[pkey] = ControllerRevision(
                        kind, name, rev, data)
            obj.pending_revisions.clear()
            key = f"{kind}/{name}/{obj.template_rev}"
            if key not in self.controller_revisions:
                self.controller_revisions[key] = ControllerRevision(
                    kind, name, obj.template_rev, obj.template())
            per_owner = sorted(
                (cr for cr in self.controller_revisions.values()
                 if cr.owner_kind == kind and cr.owner_name == name),
                key=lambda cr: cr.revision)
            # never GC a revision pods can still be created AT: the
            # update revision, and (STS) the currentRevision a canary
            # partition recreates below-boundary pods from
            keep = {obj.template_rev,
                    getattr(obj, "current_rev", obj.template_rev)}
            while (len(per_owner) > self.revision_history_limit
                   and per_owner[0].revision not in keep):
                del self.controller_revisions[per_owner.pop(0).key()]
            live.update(cr.key() for cr in per_owner)
        for key in [k for k in self.controller_revisions if k not in live]:
            del self.controller_revisions[key]

    def rollback(self, kind: str, name: str, to_revision: int) -> None:
        """``kubectl rollout undo --to-revision`` for DS/STS: re-apply
        the stored revision's template. Like the reference, undo creates
        a NEW revision carrying the old template (history is
        append-only), and the rolling machinery replaces pods."""
        cr = self.controller_revisions.get(f"{kind}/{name}/{to_revision}")
        if cr is None:
            raise KeyError(
                f"{kind.lower()}s {name!r} has no revision {to_revision}")
        obj = (self.daemonsets if kind == "DaemonSet"
               else self.statefulsets)[name]
        if cr.data == obj.template():
            # undo to the template already running: the reference
            # short-circuits ("skipped rollback") — bumping anyway would
            # roll-restart every pod for zero change
            return
        obj.rollout(**cr.data)

    def reconcile_controllers(self) -> None:
        import math

        self.reconcile_history()

        # hpa: scale the target deployment toward the metric target
        # (podautoscaler/horizontal.go; desired = ceil(current * ratio),
        # 10% tolerance dead-band per replica_calculator.go:89) — runs
        # before the deployment sync so the new size propagates this tick
        for hpa in self.hpas.values():
            d = self.deployments.get(hpa.deployment)
            if d is None or hpa.load_fn is None:
                continue
            current = max(1, d.replicas)
            target = hpa.target_utilization
            ratio = (float(hpa.load_fn()) / target) if target > 0 else 1.0
            desired = current if abs(ratio - 1.0) <= hpa.tolerance \
                else math.ceil(current * ratio)
            d.replicas = min(hpa.max_replicas,
                             max(hpa.min_replicas, desired))

        # cronjobs: spawn Jobs on schedule (cronjob_controller.go syncOne);
        # a multi-period clock jump still launches one run per sync — the
        # reference's missed-start accounting compressed to its effect
        for cj in self.cronjobs.values():
            if self.clock.t < cj.next_run:
                continue
            active = [jn for jn in cj.spawned
                      if jn in self.jobs and not self.jobs[jn].done()]
            if active and cj.concurrency == "Forbid":
                # skipped runs are dropped, never queued: catch the
                # schedule up past NOW or a long-running job would be
                # followed by a burst of back-to-back make-up runs
                while cj.next_run <= self.clock.t:
                    cj.next_run += cj.every_s
                continue
            if active and cj.concurrency == "Replace":
                for jn in active:
                    j = self.jobs.pop(jn)
                    for key in list(j.active):
                        self.delete_pod(key)
                    cj.spawned.remove(jn)
            cj.runs += 1
            jn = f"{cj.name}-{cj.runs}"
            while jn in self.jobs:
                # a foreign job already owns this name: the apiserver
                # would reject the duplicate create — never overwrite it
                cj.runs += 1
                jn = f"{cj.name}-{cj.runs}"
            self.jobs[jn] = Job(jn, completions=cj.completions,
                                parallelism=cj.parallelism,
                                duration_s=cj.duration_s,
                                cpu_milli=cj.cpu_milli, memory=cj.memory,
                                owner=cj.name)
            cj.spawned.append(jn)
            cj.next_run += cj.every_s

        # deployment -> replicasets (create/scale/rolling update)
        for d in self.deployments.values():
            new_rs = self.replicasets.get(d.rs_name())
            olds = [rs for rs in self.replicasets.values()
                    if rs.owner == d.name and rs.name != d.rs_name()]
            if new_rs is None:
                # getNewReplicaSet: the new revision's RS starts at 0 when
                # an old RS exists (the rolling path scales it), else at
                # full size (first sync of a fresh deployment)
                new_rs = ReplicaSet(d.rs_name(), 0 if olds else d.replicas,
                                    d.cpu_milli, d.memory, d.priority,
                                    owner=d.name, revision=d.template_rev)
                self.replicasets[new_rs.name] = new_rs
            if not olds:
                new_rs.replicas = d.replicas
                continue
            if d.strategy == "Recreate":
                # recreate.go: scale every old RS to 0 first; the new RS
                # only grows once NO old pod remains (never-mixed
                # versions, at the cost of downtime)
                for rs in olds:
                    rs.replicas = 0
                new_rs.replicas = (
                    d.replicas
                    if not any(rs.live for rs in olds) else 0
                )
                continue
            # ---- RollingUpdate reconciliation (rolling.go:31) ----
            # a mid-rollout SCALE-DOWN must bite immediately: the new RS
            # never holds more than the (new) desired size, even while
            # old RSes are still draining (review: without this clamp a
            # shrink waits for the old RS to empty, holding quota)
            new_rs.replicas = min(new_rs.replicas, d.replicas)
            surge = _int_or_percent(d.max_surge, d.replicas, round_up=True)
            max_unavail = _int_or_percent(d.max_unavailable, d.replicas,
                                          round_up=False)
            if surge == 0 and max_unavail == 0:
                # a percentage budget that rounds to 0 at this replica
                # count (literal 0/0 is rejected at construction) — the
                # reference coerces unavailable to 1 here so the rollout
                # still progresses (intstr ResolveFenceposts)
                max_unavail = 1
            # old RSes never grow and never replace lost pods mid-rollout
            # (the reference only ever scales them down; a dead old pod
            # is rollout progress, not something to recreate)
            for rs in olds:
                rs.replicas = min(rs.replicas, len(rs.live))
            # reconcileNewReplicaSet: grow the new RS within the surge
            # budget (NewRSNewReplicas: total may reach replicas+surge)
            total = new_rs.replicas + sum(rs.replicas for rs in olds)
            if total < d.replicas + surge:
                new_rs.replicas = min(
                    d.replicas, new_rs.replicas + (d.replicas + surge - total)
                )
            # reconcileOldReplicaSets: unavailable (unbound) old pods are
            # free to delete (cleanupUnhealthyReplicas), then drain down
            # to the availability floor replicas-maxUnavailable
            def available(rs):
                return sum(
                    1 for k in rs.live
                    if k in self.truth_pods and self.truth_pods[k].node_name
                )

            for rs in olds:
                rs.replicas -= min(rs.replicas, len(rs.live) - available(rs))
            avail_total = available(new_rs) + sum(available(rs) for rs in olds)
            can_kill = max(0, avail_total - (d.replicas - max_unavail))
            for rs in sorted(olds, key=lambda r: r.revision):
                if can_kill <= 0:
                    break
                down = min(rs.replicas, can_kill)
                rs.replicas -= down
                can_kill -= down
        # garbage collector: deployment gone -> cascade its RS + pods;
        # drained old-revision RSes are removed once empty (the hollow
        # form of revisionHistoryLimit cleanup)
        for name in list(self.replicasets):
            rs = self.replicasets[name]
            if rs.owner and rs.owner not in self.deployments:
                for key in list(rs.live):
                    self.delete_pod(key)
                del self.replicasets[name]
            elif (rs.owner and rs.replicas == 0 and not rs.live
                  and rs.owner in self.deployments
                  and name != self.deployments[rs.owner].rs_name()):
                del self.replicasets[name]
        # replicaset/RC scale-down (deployment shrink, rolling drain, or
        # direct resize) — unassigned pods are deleted first, the
        # ActivePods ranking of controller_utils.go:722, which is what
        # keeps the rolling availability budget honest
        for rs in (list(self.replicasets.values())
                   + list(self.replication_controllers.values())):
            extra = len(rs.live) - rs.replicas
            if extra > 0:
                victims = sorted(rs.live, key=lambda k: bool(
                    k in self.truth_pods and self.truth_pods[k].node_name))
                for key in victims[:extra]:
                    self.delete_pod(key)
        def spawn(prefix: str, idx: int, labels: dict, cpu, mem, pri=0,
                  owner: "OwnerReference | None" = None):
            pod = make_pod(f"{prefix}-{idx}", cpu_milli=cpu, memory=mem,
                           priority=pri, labels=labels,
                           owner_refs=(owner,) if owner else ())
            pod.uid = f"{prefix}-{idx}#{idx}"
            try:
                self.create_pod(pod)
            except AdmissionError:
                # a real controller gets the 403 and retries next sync
                # (quota may free up as pods finish)
                return None
            return pod

        # jobs: finish pods that ran their duration; keep parallelism fed
        for j in self.jobs.values():
            for key in list(j.active):
                if key not in self.truth_pods:
                    j.active.pop(key)  # evicted/killed: controller re-adds
                    continue
                t0 = self._bound_at.get(key)
                if t0 is not None and self.clock.t - t0 >= j.duration_s:
                    j.succeeded += 1
                    j.active.pop(key)
                    # terminal phase hop is observable in the watch
                    # history BEFORE the delete (Running -> Succeeded ->
                    # DELETED, the full lifecycle chain)
                    import dataclasses

                    from kubernetes_tpu.api.types import POD_SUCCEEDED

                    done = dataclasses.replace(
                        self.truth_pods[key], phase=POD_SUCCEEDED,
                        ready=False)
                    self.truth_pods[key] = done
                    self._commit(f"pods/{key}", "MODIFIED", done)
                    self.delete_pod(key)  # Succeeded -> cleaned up
            if j.done() and j.finished_at is None:
                # status.completionTime — the TTL-after-finished clock
                j.finished_at = self.clock.t
            while (not j.done()
                   and len(j.active) < j.parallelism
                   and j.succeeded + len(j.active) < j.completions):
                j.next_idx += 1
                pod = spawn(j.name, j.next_idx, {"job": j.name},
                            j.cpu_milli, j.memory,
                            owner=OwnerReference("Job", j.name))
                if pod is None:
                    break
                j.active[pod.key()] = pod
        for rs in (list(self.replicasets.values())
                   + list(self.replication_controllers.values())):
            while len(rs.live) < rs.replicas:
                rs.next_idx += 1
                # the owner label is revision-stable: a Service selecting
                # {"deploy": name} spans old and new RSes mid-rollout
                is_rc = rs.kind == "ReplicationController"
                labels = {"rc": rs.name} if is_rc else {"rs": rs.name}
                if rs.owner:
                    labels["deploy"] = rs.owner
                # the reference's generateName random suffix is what
                # keeps same-name RC and RS pods from colliding; the
                # hollow deterministic naming needs a kind discriminator
                # instead
                pod = spawn(f"{rs.name}-rc" if is_rc else rs.name,
                            rs.next_idx, labels,
                            rs.cpu_milli, rs.memory, rs.priority,
                            owner=OwnerReference(rs.kind, rs.name))
                if pod is None:
                    break
                rs.live[pod.key()] = pod

        # daemonsets: exactly one pod per eligible node, pinned by
        # required node affinity and pushed through the regular scheduler
        # (v1.16 ScheduleDaemonSetPods); pods whose node vanished, fell
        # out of the selector, or got bound somewhere other than their pin
        # (a competing writer ignoring affinity — the apiserver accepts
        # such bindings) are deleted — the controller's per-node
        # expectations pass (daemon_controller.go manage())
        for ds in self.daemonsets.values():
            keep = {n.name for n in self.truth_nodes.values()
                    if ds.should_keep(n)}
            for key, node_name in list(ds.live.items()):
                p = self.truth_pods.get(key)
                mispinned = (p is not None and p.node_name
                             and p.node_name != node_name)
                if node_name not in keep or mispinned:
                    self.delete_pod(key)
            # RollingUpdate (daemon/update.go rollingUpdate): delete
            # stale-revision daemon pods while at most max_unavailable
            # nodes lack a RUNNING current-revision pod — the normal
            # create loop below recreates with the new template (one
            # node at a time at the default maxUnavailable=1)
            want_rev = str(ds.template_rev)
            # unavailable = daemon pods not RUNNING (any revision): a
            # stale-but-running pod still serves — it does not charge
            # the budget, it's what the budget lets us kill
            unavail = sum(
                1 for key in ds.live
                if (p := self.truth_pods.get(key)) is None
                or not p.node_name
            )
            budget = ds.max_unavailable - unavail
            for key in sorted(ds.live):
                if budget <= 0:
                    break
                p = self.truth_pods.get(key)
                if p is not None and p.labels.get("rev") != want_rev:
                    self.delete_pod(key)
                    budget -= 1
            have = set(ds.live.values())
            for node_name in sorted(
                    n.name for n in self.truth_nodes.values()
                    if ds.can_place(n) and n.name not in have):
                pod = make_pod(
                    f"{ds.name}-{node_name}",
                    cpu_milli=ds.cpu_milli, memory=ds.memory,
                    priority=ds.priority,
                    labels={"ds": ds.name, "rev": want_rev},
                    affinity=node_affinity_required(
                        [req("kubernetes.io/hostname", "In", node_name)]
                    ),
                    tolerations=DAEMON_TOLERATIONS,
                    owner_refs=(OwnerReference("DaemonSet", ds.name),),
                )
                try:
                    self.create_pod(pod)
                except AdmissionError:
                    continue
                ds.live[pod.key()] = node_name

        # statefulsets: OrderedReady — scale down highest ordinal first
        # (one per sync), otherwise create the lowest missing ordinal only
        # once every predecessor is bound (stateful_set_control.go)
        for ss in self.statefulsets.values():
            by_ord: Dict[int, Pod] = {}
            for p in self.truth_pods.values():
                if p.labels.get("ss") != ss.name:
                    continue
                try:
                    by_ord[int(p.name.rsplit("-", 1)[1])] = p
                except (IndexError, ValueError):
                    continue
            over = [o for o in by_ord if o >= ss.replicas]
            if over:
                self.delete_pod(by_ord[max(over)].key())
                continue  # one termination per sync; creation waits
            # RollingUpdate (stateful_set_control.go updateStatefulSet):
            # ordinals >= partition whose revision is stale are deleted
            # HIGHEST-first, one per sync, only while every pod is bound
            # (OrderedReady never tears down into an unsettled set); the
            # missing-ordinal create below recreates with the new
            # template. Ordinals below the partition keep the old
            # revision — the canary boundary.
            want_rev = str(ss.template_rev)
            if all(p.node_name for p in by_ord.values()):
                stale = [o for o, p in by_ord.items()
                         if o >= ss.partition
                         and p.labels.get("rev") != want_rev]
                if stale:
                    self.delete_pod(by_ord[max(stale)].key())
                    continue
                if (not stale and len(by_ord) == ss.replicas
                        and ss.current_rev != ss.template_rev
                        and ss.partition == 0):
                    # rollout complete: status.currentRevision catches
                    # up to updateRevision (updateStatefulSetStatus)
                    ss.current_rev = ss.template_rev
            for o in range(ss.replicas):
                p = by_ord.get(o)
                if p is None:
                    if o < ss.partition:
                        # below the canary boundary: recreate at the
                        # CURRENT revision's template, not the update's
                        # (the reference recreates at currentRevision)
                        cur = self.controller_revisions.get(
                            f"StatefulSet/{ss.name}/{ss.current_rev}")
                        tpl = cur.data if cur is not None else ss.template()
                        rev_label = str(ss.current_rev)
                    else:
                        tpl = ss.template()
                        rev_label = want_rev
                    pod = make_pod(ss.pod_name(o),
                                   cpu_milli=tpl["cpu_milli"],
                                   memory=tpl["memory"],
                                   priority=tpl["priority"],
                                   labels={"ss": ss.name,
                                           "rev": rev_label},
                                   owner_refs=(OwnerReference(
                                       "StatefulSet", ss.name),))
                    try:
                        self.create_pod(pod)
                    except AdmissionError:
                        pass
                    break
                if not p.node_name:
                    break  # predecessor not Running yet: hold the line

        # cronjob history GC — after the jobs pass above so jobs that
        # finished THIS sync count against successfulJobsHistoryLimit
        for cj in self.cronjobs.values():
            finished = [jn for jn in cj.spawned
                        if jn in self.jobs and self.jobs[jn].done()]
            while len(finished) > cj.history_limit:
                jn = finished.pop(0)
                cj.spawned.remove(jn)
                del self.jobs[jn]

    def churn(self, kill_pods: int = 0, flap_nodes: int = 0) -> None:
        """Random disruption: delete bound pods, bounce nodes."""
        bound = [k for k, p in self.truth_pods.items() if p.node_name]
        for key in self.rng.sample(bound, min(kill_pods, len(bound))):
            self.delete_pod(key)
        names = list(self.truth_nodes)
        for name in self.rng.sample(names, min(flap_nodes, len(names))):
            self.remove_node(name)

    # -- disruption controller (pkg/controller/disruption) ------------------

    def add_replication_controller(self, name: str, replicas: int,
                                   cpu_milli: float = 100,
                                   memory: float = 256 * 2**20,
                                   priority: int = 0) -> "ReplicaSet":
        """v1 ReplicationController create — reconciled by the exact
        ReplicaSet machinery (the reference's RC controller is the RS
        controller behind conversion adapters, replication_controller
        .go:58); pods carry kind=ReplicationController ownerReferences
        so the GC graph keys on the right kind."""
        rc = ReplicaSet(name, replicas, cpu_milli, memory, priority,
                        kind="ReplicationController")
        self.replication_controllers[name] = rc
        return rc

    def add_pdb(self, pdb) -> None:
        self.pdbs.append(pdb)

    def evict_pod(self, key: str):
        """The Eviction subresource's storage half (policy/v1beta1
        Eviction; registry/core/pod/storage/eviction.go:147 checks every
        covering PDB and PATCHes disruptionsAllowed down atomically):
        returns (True, "") and deletes the pod, or (False, message) when
        any covering budget is exhausted — the 429 the apiserver sends.
        The disruption is charged IMMEDIATELY (all covering PDBs
        decrement) so a burst of evictions cannot overshoot the budget
        between disruption-controller passes."""
        import dataclasses

        pod = self.truth_pods.get(key)
        if pod is None:
            return False, f'pods "{key}" not found'
        covering = [pdb for pdb in self.pdbs if pdb.matches(pod)]
        if any(pdb.disruptions_allowed <= 0 for pdb in covering):
            return False, (
                "Cannot evict pod as it would violate the pod's "
                "disruption budget."
            )
        for pdb in covering:
            pdb.disruptions_allowed -= 1
        # observable terminating hop (deletionTimestamp) before the
        # delete — endpoints/watchers see the pod leave rotation first.
        # clock.t can be 0.0 at sim start and deletionTimestamp's unset
        # value is also 0.0, so floor at a positive epsilon or the hop
        # would be invisible to every `not deletion_timestamp` consumer
        terminating = dataclasses.replace(
            pod, deletion_timestamp=self.clock.t or 1e-6)
        self.truth_pods[key] = terminating
        self._commit(f"pods/{key}", "MODIFIED", terminating)
        self.delete_pod(key)
        return True, ""

    def reconcile_pdbs(self) -> None:
        """Maintain PDB status the way the disruption controller does:
        disruptionsAllowed = max(0, currentHealthy - minAvailable), where
        healthy = bound, non-terminating matching pods (updatePdbStatus,
        pkg/controller/disruption/disruption.go)."""
        for pdb in self.pdbs:
            if pdb.min_available is None:
                continue
            healthy = sum(
                1
                for p in self.truth_pods.values()
                if p.node_name and not p.deletion_timestamp and pdb.matches(p)
            )
            pdb.disruptions_allowed = max(0, healthy - pdb.min_available)

    # -- node lifecycle controller (node_lifecycle_controller.go) -----------

    TAINT_UNREACHABLE = "node.kubernetes.io/unreachable"

    def kill_kubelet(self, name: str) -> None:
        """The node's kubelet stops heartbeating — the node object remains
        (unlike :meth:`remove_node`); the lifecycle controller must notice
        via heartbeat age, not via a delete event."""
        self.dead_kubelets.add(name)
        if name in self.kubelets:
            self.kubelets[name].alive = False

    def heal_kubelet(self, name: str) -> None:
        self.dead_kubelets.discard(name)
        if name in self.kubelets:
            self.kubelets[name].alive = True
            self.kubelets[name].heartbeat()

    def _update_node(self, node: Node) -> None:
        self.truth_nodes[node.name] = node
        self._commit(f"nodes/{node.name}", "MODIFIED", node)
        self._emit(f"nodes/{node.name}", lambda: self.sched.on_node_update(node))

    def monitor_node_health(self) -> None:
        """monitorNodeHealth (:660): heartbeat older than the grace period
        ⇒ Ready=Unknown + NoExecute unreachable taint; a fresh heartbeat
        ⇒ restore. Then NoExecute eviction (:579): pods on a tainted node
        that don't tolerate it are evicted once their toleration window
        (here: ``eviction_wait_s``) passes — rate-limited per zone
        (handleDisruption/setLimiterInZone, :998,:1096)."""
        import dataclasses

        now = self.clock.t
        for name, nd in list(self.truth_nodes.items()):
            age = now - self.heartbeats.get(name, now)
            tainted = any(t.key == self.TAINT_UNREACHABLE for t in nd.taints)
            if age > self.node_grace_s and not tainted:
                new = dataclasses.replace(
                    nd,
                    conditions=dataclasses.replace(nd.conditions, ready=False),
                    taints=nd.taints
                    + (Taint(self.TAINT_UNREACHABLE, effect=EFFECT_NO_EXECUTE),),
                )
                self._taint_time[name] = now
                self._update_node(new)
            elif age <= self.node_grace_s and tainted:
                new = dataclasses.replace(
                    nd,
                    conditions=dataclasses.replace(nd.conditions, ready=True),
                    taints=tuple(
                        t for t in nd.taints if t.key != self.TAINT_UNREACHABLE
                    ),
                )
                self._taint_time.pop(name, None)
                self._update_node(new)
        # NoExecute eviction, zone-rate-limited
        evicted_in_zone: Dict[str, int] = {}
        for key, p in list(self.truth_pods.items()):
            if not p.node_name:
                continue
            nd = self.truth_nodes.get(p.node_name)
            if nd is None:
                continue
            t0 = self._taint_time.get(nd.name)
            if t0 is None or now - t0 <= self.eviction_wait_s:
                continue
            # NoExecute taint-manager semantics (taint_manager.go):
            # tolerating without tolerationSeconds = never evicted;
            # with tolerationSeconds = evicted once the window passes
            # (DefaultTolerationSeconds admission stamps 300 s on pods
            # that declare nothing)
            tols = [
                tol for tol in p.tolerations
                if tol.tolerates(Taint(self.TAINT_UNREACHABLE,
                                       effect=EFFECT_NO_EXECUTE))
            ]
            if tols:
                secs = [t.toleration_seconds for t in tols]
                if any(s is None for s in secs):
                    continue
                # getMinTolerationTime (taint_manager.go): the SHORTEST
                # matching window bounds how long the pod may stay
                if now - t0 <= min(secs):
                    continue
            zone = nd.zone() or ""
            if evicted_in_zone.get(zone, 0) >= self.zone_eviction_rate:
                continue
            evicted_in_zone[zone] = evicted_in_zone.get(zone, 0) + 1
            self.delete_pod(key)

    def competing_writer(self) -> None:
        """An HA peer / external controller binding pending pods behind the
        scheduler's back. Every such bind is a legal hub write (capacity
        checked against truth), so any later scheduler bind for the same
        pod MUST hit the CAS conflict and Forget+requeue."""
        if self.competing_bind_rate <= 0:
            return
        free: Dict[str, List[float]] = {}
        for name, nd in self.truth_nodes.items():
            free[name] = [nd.allocatable.cpu_milli, nd.allocatable.memory,
                          nd.allocatable.pods]
        for p in self.truth_pods.values():
            if p.node_name and p.node_name in free:
                f = free[p.node_name]
                f[0] -= p.requests.cpu_milli
                f[1] -= p.requests.memory
                f[2] -= 1
        for key, p in list(self.truth_pods.items()):
            if p.node_name or self.rng.random() >= self.competing_bind_rate:
                continue
            fits = [
                n for n, f in free.items()
                if f[0] >= p.requests.cpu_milli and f[1] >= p.requests.memory
                and f[2] >= 1
            ]
            if not fits:
                continue
            target = self.rng.choice(fits)
            try:
                self.confirm_binding(p, target)
            except Conflict:
                continue
            f = free[target]
            f[0] -= p.requests.cpu_milli
            f[1] -= p.requests.memory
            f[2] -= 1
            self.competing_bound += 1

    # -- run ----------------------------------------------------------------

    def step(self, dt: float = 15.0):
        """One sim tick: deliver due watch events, GC orphans, let the
        competing writer race, reconcile controllers, run a scheduling
        cycle, advance time (so backoffs expire across ticks)."""
        with self.lock:
            return self._step_locked(dt)

    def _step_locked(self, dt: float):
        self._tick += 1
        self.flush_events()
        self.gc_orphaned()
        for kl in list(self.kubelets.values()):  # syncLoop ticks
            kl.sync()
        self.sync_pod_lifecycle()
        self.monitor_node_health()
        self.reconcile_pdbs()
        if self.cloud_controller is not None:
            self.cloud_controller.reconcile()
            self.service_lb_controller.reconcile()
            self.route_controller.reconcile()
        if self.admission is not None:
            self.reconcile_namespaces()
            self.quota_controller.reconcile()
        elif any(ns.phase == NS_TERMINATING
                 for ns in self.namespaces.values()):
            # without the admission chain nothing STOPS creates into a
            # terminating namespace, but a deletion must still drain —
            # a REST DELETE namespace on an admission-less hub would
            # otherwise terminate forever
            self.reconcile_namespaces()
        # unconditional: an (impossible today) empty namespaces dict must
        # still REVOKE — gating here would freeze dead tokens alive
        self.reconcile_service_accounts()
        if any(getattr(r, "aggregation_selectors", ())
               for r in self.cluster_roles.values()):
            from kubernetes_tpu.auth import aggregate_cluster_roles

            aggregate_cluster_roles(self.cluster_roles)
        self.cert_controller.reconcile()
        self.root_ca_publisher.reconcile()
        if self.bootstrap_tokens or (
                f"kube-public/cluster-info" in self.configmaps):
            # bootstrap-token controllers (kubernetes_tpu/bootstrap.py):
            # cleaner expires tokens, signer keeps cluster-info's
            # signature set in lockstep with the live token set
            from kubernetes_tpu.bootstrap import (
                bootstrap_signer,
                token_cleaner,
            )

            token_cleaner(self)
            bootstrap_signer(self)
        self.reconcile_ttl()
        self.reconcile_node_ipam()
        self.reconcile_ttl_after_finished()
        self.reconcile_controllers()
        self.gc_owner_graph()
        self.reconcile_pod_gc()
        if self.pvcs or self.pvs:
            self.reconcile_volume_protection()
            self.reconcile_volumes()
        if (self.pvs or self.attachments
                or any(p.volumes for p in self.truth_pods.values())):
            # the any() covers INLINE attachable volumes (no PV objects
            # in the cluster) — without it that half of the controller
            # would never run
            self.reconcile_attachments()
        if self.services or self.endpoints:
            self.endpoints_controller.reconcile()
            self.sync_proxies()
        # the competing writer races AFTER new pods exist but BEFORE the
        # scheduler's cycle — the window where the scheduler's view goes
        # stale and its binds must CAS-fail
        self.competing_writer()
        res = self.sched.schedule_cycle()
        # periodic compaction to the slowest open cursor (etcd's
        # auto-compaction): history stays bounded by watcher lag, not by
        # sim length
        floor = min((c.rev for c in self._cursors), default=self._revision)
        self.compact(floor)
        self.clock.advance(dt)
        return res

    def check_consistency(self) -> None:
        """Invariants at the settled state (all watch events delivered —
        the comparer in the reference also reads the synced informer view):
        - cache matches truth (comparer),
        - no node over-committed in truth (cpu/memory/pod count),
        - every truth-bound pod landed on a live node."""
        self.settle()
        from kubernetes_tpu.api.types import is_pod_terminated

        # terminal pods are deliberately absent from the scheduler cache
        # (their phase hop reached it as a DELETE — the informer field
        # selector); the comparer sees the same filtered view
        truth = {k: p.node_name for k, p in self.truth_pods.items()
                 if not is_pod_terminated(p)}
        node_diffs, pod_diffs = compare(self.sched, truth, list(self.truth_nodes))
        assert not node_diffs, f"cache/truth node diffs: {node_diffs}"
        assert not pod_diffs, f"cache/truth pod diffs: {pod_diffs}"
        by_node: Dict[str, List[Pod]] = {}
        for p in self.truth_pods.values():
            if p.node_name:
                assert p.node_name in self.truth_nodes, (
                    f"{p.key()} bound to dead node {p.node_name}"
                )
                if is_pod_terminated(p):
                    continue  # exited containers hold no resources
                by_node.setdefault(p.node_name, []).append(p)
        for name, pods in by_node.items():
            nd = self.truth_nodes[name]
            cpu = sum(p.requests.cpu_milli for p in pods)
            mem = sum(p.requests.memory for p in pods)
            assert cpu <= nd.allocatable.cpu_milli + 1e-6, f"{name} cpu overcommit"
            assert mem <= nd.allocatable.memory + 1e-6, f"{name} mem overcommit"
            assert len(pods) <= nd.allocatable.pods, f"{name} pod-count overcommit"
        # service dataplane: endpoints/proxies agree with (services, pods)
        if self.services:
            self.endpoints_controller.reconcile()
            self.sync_proxies()
            for key, svc in self.services.items():
                ep = self.endpoints.get(key)
                assert ep is not None, f"service {key} has no Endpoints"
                from kubernetes_tpu.proxy import pod_endpoint_ready

                want = sorted(
                    p.key() for p in self.truth_pods.values()
                    if svc.selects(p) and pod_endpoint_ready(p)
                )
                got = sorted(a.pod_key for a in ep.ready)
                assert got == want, f"{key} endpoints drift: {got} != {want}"
                for a in ep.ready:
                    assert self.truth_pods[a.pod_key].node_name == a.node_name
        # volume truth: PVC<->PV binding is mutual and exclusive (the
        # pv_controller's own invariant: a bound pair references each
        # other; no PV serves two claims)
        claimants: Dict[str, str] = {}
        for key, pvc in self.pvcs.items():
            if not pvc.volume_name:
                continue
            pv = self.pvs.get(pvc.volume_name)
            assert pv is not None, f"pvc {key} bound to unknown pv"
            assert pv.claim_ref == key, (
                f"pv {pv.name} claimRef {pv.claim_ref!r} != {key!r}"
            )
            assert claimants.setdefault(pvc.volume_name, key) == key, (
                f"pv {pvc.volume_name} double-claimed"
            )
        for pv in self.pvs.values():
            if pv.claim_ref:
                pvc = self.pvcs.get(pv.claim_ref)
                assert pvc is not None and pvc.volume_name == pv.name, (
                    f"pv {pv.name} claimRef not reciprocated"
                )
        # ownerRef graph: at the settled state no object may outlive its
        # every controller owner (the GC pass must have converged)
        kinds = self._owner_kinds()
        for p in self.truth_pods.values():
            if p.owner_refs:
                assert any(r.name in kinds.get(r.kind, {})
                           for r in p.owner_refs), (
                    f"{p.key()} outlives its owners {p.owner_refs}"
                )
        for name, j in self.jobs.items():
            assert not j.owner or j.owner in self.cronjobs, (
                f"job {name} outlives CronJob {j.owner}"
            )

    def pending_count(self) -> int:
        return sum(1 for p in self.truth_pods.values() if not p.node_name)


class WatchCursor:
    """One watcher's position in the hub's history — the apiserver watch
    stream a client holds. Independent cursors = watch fan-out
    (storage/cacher/cacher.go: many watchers, one event source)."""

    def __init__(self, hub: HollowCluster, since_rev: int) -> None:
        self.hub = hub
        self.rev = since_rev

    def poll(self) -> List[tuple]:
        """Events after this cursor's revision, advancing it. Raises
        :class:`Compacted` when the cursor fell behind the compaction
        floor (the relist trigger)."""
        if self.rev < self.hub._compacted_rev:
            raise Compacted(
                f"required revision {self.rev} has been compacted "
                f"(floor {self.hub._compacted_rev})"
            )
        h = self.hub._history
        i = bisect.bisect_right(h, self.rev, key=lambda e: e[0])
        out = h[i:]
        self.rev = max(self.rev, self.hub._revision)
        return out


class Reflector:
    """client-go Reflector.ListAndWatch (tools/cache/reflector.go:159)
    over the hub's versioned store, feeding a scheduler's event-handler
    surface (the SharedInformer seam):

    - LIST at a revision, deliver the snapshot as adds/updates/deletes
      RELATIVE to what this reflector already delivered (DeltaFIFO.Replace
      semantics — a relist must emit deletes for objects that vanished
      while the watch was down);
    - WATCH from that revision, translating history events into
      on_pod_add/on_pod_update/on_pod_delete/on_node_* calls;
    - a :class:`Compacted` watch error relists (reflector.go's
      "too old resource version" path);
    - resync() re-delivers every known object as a no-op update (the
      SharedInformer resync period);
    - ``pod_label_selector``/``pod_field_selector`` scope the POD feed
      the way the reference's ListWatch options do (a kubelet's pod
      informer lists with ``spec.nodeName=<self>``, kubelet/config/
      apiserver.go:32): selection happens at the feed layer before any
      sink delivery, and a MODIFIED pod that leaves the selector is
      delivered as a DELETE (watch-cache selector semantics), never
      silently retained.

    Network-fault hardening (PR 15):

    - **resourceVersion-monotonic dedupe** — every delivered event
      carries the hub revision; an event at or below the object's last
      delivered revision is a NO-OP (``deduped`` counts them). This is
      what makes duplicated and reordered watch frames harmless: a
      stale MODIFIED reordered after its object's DELETE can never
      resurrect the object (the reference informer's resourceVersion
      comparison in the DeltaFIFO/store seam).
    - **progress deadline** — a watch that delivers NOTHING for
      ``progress_deadline_s`` while the hub has advanced revisions is
      treated as silently stalled (half-open connection class) and
      forced to relist instead of idling forever; forced relists (and
      Compacted storms) back off with FULL JITTER per replica
      (``relist_backoff``) so a fleet can't stampede a recovering hub.
      Both need an injected ``clock``; without one the behavior is
      exactly the pre-hardening Reflector.
    - ``cursor_wrap`` — chaos seam: wraps the watch cursor at relist
      time (chaos.FuzzedCursor injects drop/duplicate/reorder/410).
    """

    def __init__(self, hub: HollowCluster, sink,
                 pod_label_selector: str = "",
                 pod_field_selector: str = "",
                 clock: Optional[Callable[[], float]] = None,
                 progress_deadline_s: Optional[float] = None,
                 relist_backoff=None,
                 cursor_wrap=None) -> None:
        from kubernetes_tpu.api.selectors import (
            match_fields,
            match_labels,
            parse_field_selector,
            parse_label_selector,
            pod_fields,
            validate_field_keys,
        )

        self.hub = hub
        self.sink = sink
        self.pods: Dict[str, Pod] = {}
        self.nodes: Dict[str, Node] = {}
        self.relists = 0
        self._cursor: Optional[WatchCursor] = None
        # -- network-fault hardening state --------------------------------
        self.clock = clock
        if progress_deadline_s is None:
            # robustness.watchProgressDeadline: a Scheduler sink carries
            # its config — the knob governs every reflector built on it
            # unless the caller pins a deadline explicitly (0 = off);
            # sinks without a robustness block keep detection off
            progress_deadline_s = getattr(
                getattr(sink, "robustness", None),
                "watch_progress_deadline_s", 0.0)
        self.progress_deadline_s = float(progress_deadline_s or 0.0)
        progress_deadline_s = self.progress_deadline_s
        if relist_backoff is None and progress_deadline_s > 0:
            # full jitter on a PER-REPLICA stream (SystemRandom seed):
            # two replicas stalling together must not relist in lockstep
            from kubernetes_tpu.faults import RetryPolicy

            relist_backoff = RetryPolicy(
                base_s=1.0, max_s=30.0, jitter=0.5,
                seed=random.SystemRandom().randrange(1 << 30))
        self._relist_backoff = relist_backoff
        self._cursor_wrap = cursor_wrap
        #: per-object last DELIVERED revision (the dedupe floor) —
        #: LIVE objects only; deleted objects move to the tombstone LRU
        self._obj_rev: Dict[str, int] = {}
        #: dedupe floors for objects DELETED since the last relist,
        #: kept apart from the live map and LRU-bounded: between
        #: relists every churned-away pod would otherwise keep a floor
        #: entry forever (growth ∝ total churn — the soak sentinel's
        #: original finding). The floor cannot simply be dropped at the
        #: DELETE: a reordered stale MODIFIED arriving after it would
        #: resurrect the object. Evicting the OLDEST tombstone only
        #: narrows that reorder-protection window to the most recent
        #: ``tombstone_capacity`` deletions — the same bounded-window
        #: trade the jaxtel signature LRU makes.
        self._gone_rev: "OrderedDict[str, int]" = OrderedDict()
        self.tombstone_capacity = 4096
        #: duplicated / reordered-stale events dropped as no-ops
        self.deduped = 0
        #: relists forced by the progress deadline (stalled watch)
        self.stalled_relists = 0
        #: highest revision actually RECEIVED from the stream (the
        #: stall detector compares the hub's head against it — the
        #: cursor position alone can lie when frames are being eaten)
        self._delivered_rev = 0
        #: a 410 observed DURING the relist cool-down: the relist is
        #: owed once the window opens — a real compacted cursor would
        #: re-raise every poll, but an injected one-shot 410
        #: (chaos.FuzzedCursor) fires exactly once and must not be lost
        self._pending_compacted = False
        self._last_progress = clock() if clock is not None else 0.0
        self._next_relist_ok = 0.0
        self._stall_attempts = 0
        self._lsel = parse_label_selector(pod_label_selector)
        self._fsel = parse_field_selector(pod_field_selector)
        validate_field_keys(self._fsel, "pods")
        self._match_labels, self._match_fields = match_labels, match_fields
        self._pod_fields = pod_fields

    def _selects(self, p: Pod) -> bool:
        if not self._lsel and not self._fsel:
            return True
        return (self._match_labels(self._lsel, p.labels)
                and self._match_fields(self._fsel, self._pod_fields(p)))

    # -- list+watch --------------------------------------------------------

    def list_and_watch(self) -> None:
        rev, nodes, pods = self.hub.list_state()
        pods = {k: p for k, p in pods.items() if self._selects(p)}
        # Replace(): adds for new, updates for changed, deletes for gone
        for name, nd in nodes.items():
            if name not in self.nodes:
                self.sink.on_node_add(nd)
            elif self.nodes[name] is not nd:
                self.sink.on_node_update(nd)
        for name in list(self.nodes):
            if name not in nodes:
                self.sink.on_node_delete(name)
        for key, p in pods.items():
            old = self.pods.get(key)
            if old is None:
                self.sink.on_pod_add(p)
            elif old is not p:
                if old.uid != p.uid or (old.node_name and not p.node_name):
                    # deleted-and-recreated while the watch was down: a
                    # single update would leave the stale bound pod in the
                    # sink's cache (scheduler on_pod_update's unassigned
                    # branch never removes) — replay as delete+add
                    self.sink.on_pod_delete(old)
                    self.sink.on_pod_add(p)
                else:
                    self.sink.on_pod_update(old, p)
        for key, old in list(self.pods.items()):
            if key not in pods:
                self.sink.on_pod_delete(old)
        self.nodes, self.pods = nodes, pods
        # the dedupe floor COMPACTS at every relist: the fresh cursor
        # starts AT rev, so no frame at or below rev can ever arrive
        # again — live objects keep a floor of rev and entries for
        # objects gone from the listing (every deleted pod ever seen)
        # are dropped, bounding the map to the live set instead of
        # growing with total objects ever delivered (a reflector
        # under sustained create/delete churn would otherwise leak)
        self._obj_rev = {f"nodes/{n}": rev for n in nodes}
        self._obj_rev.update({f"pods/{k}": rev for k in pods})
        # tombstones compact with the floor: the fresh cursor starts AT
        # rev, so no stale frame for a dead object can arrive either
        self._gone_rev.clear()
        cur = self.hub.watch(rev)
        if self._cursor_wrap is not None:
            cur = self._cursor_wrap(cur)
        self._cursor = cur
        self._delivered_rev = max(self._delivered_rev, rev)
        if self.clock is not None:
            self._last_progress = self.clock()

    def _arm_relist_backoff(self, now) -> None:
        """Jittered cool-down before the NEXT forced relist — the
        anti-stampede half of the stall/storm handling."""
        if now is None or self._relist_backoff is None:
            return
        self._next_relist_ok = now + self._relist_backoff.backoff_s(
            self._stall_attempts)
        self._stall_attempts += 1

    def pump(self) -> int:
        """Deliver pending watch events; relist on compaction or on a
        detected silent stall. Returns the number of events received
        (relist counts as one). Duplicated / reordered-stale events are
        dropped by the per-object resourceVersion dedupe (``deduped``)
        but still count as stream liveness."""
        if self._cursor is None:
            self.list_and_watch()
            return 1
        now = self.clock() if self.clock is not None else None
        if self._pending_compacted:
            if now is not None and now < self._next_relist_ok:
                return 0  # still cooling down; the relist stays owed
            self._pending_compacted = False
            self.relists += 1
            self._arm_relist_backoff(now)
            self.list_and_watch()
            return 1
        try:
            events = self._cursor.poll()
        except Compacted:
            if now is not None and now < self._next_relist_ok:
                # a 410 storm already forced a relist inside this
                # jittered cool-down; wait it out instead of joining
                # the stampede — but REMEMBER the compaction (a one-
                # shot injected 410 will not re-raise next poll)
                self._pending_compacted = True
                return 0
            self.relists += 1
            self._arm_relist_backoff(now)
            self.list_and_watch()
            return 1
        if events:
            self._delivered_rev = max(
                self._delivered_rev, max(e[0] for e in events))
            if now is not None:
                self._last_progress = now
            self._stall_attempts = 0
        elif now is not None:
            if self.hub._revision <= self._delivered_rev:
                # genuinely idle: nothing new exists to deliver
                self._last_progress = now
            elif (self.progress_deadline_s > 0
                    and now - self._last_progress
                    >= self.progress_deadline_s
                    and now >= self._next_relist_ok):
                # SILENT STALL: the hub advanced revisions but this
                # stream delivered nothing past the deadline (half-open
                # connection / event-eating middlebox class). Force a
                # relist with jittered backoff instead of idling forever.
                self.stalled_relists += 1
                self.relists += 1
                self._arm_relist_backoff(now)
                self.list_and_watch()
                return 1
        for rev, obj_key, etype, obj in events:
            floor = self._obj_rev.get(obj_key)
            if floor is None:
                floor = self._gone_rev.get(obj_key, 0)
            if rev <= floor:
                # duplicate or reordered-stale frame: the object already
                # reflects a revision at/after this one — a no-op by the
                # resourceVersion-monotonic rule (NEVER re-applied: a
                # stale MODIFIED after a DELETE would resurrect)
                self.deduped += 1
                continue
            if etype == "DELETED":
                # the floor migrates to the bounded tombstone LRU: live
                # map stays sized to the live set, yet a reordered
                # stale MODIFIED still dedupes against the delete's rev
                self._obj_rev.pop(obj_key, None)
                self._gone_rev.pop(obj_key, None)
                self._gone_rev[obj_key] = rev
                while len(self._gone_rev) > self.tombstone_capacity:
                    self._gone_rev.popitem(last=False)
            else:
                # a frame PAST the tombstone is a recreation (the hub
                # mints monotonic revs): the object is live again
                self._gone_rev.pop(obj_key, None)
                self._obj_rev[obj_key] = rev
            kind, _, ident = obj_key.partition("/")
            if kind not in ("nodes", "pods"):
                # the history is shared across kinds (events, services,
                # endpoints, ...); this reflector only syncs the two kinds
                # the scheduler's informers watch — anything else would
                # otherwise be fed into the pod handlers and crash
                # (reflector filtering = the ListWatch's resource scoping)
                continue
            if kind == "nodes":
                if etype == "ADDED":
                    self.nodes[ident] = obj
                    self.sink.on_node_add(obj)
                elif etype == "MODIFIED":
                    self.nodes[ident] = obj
                    self.sink.on_node_update(obj)
                else:
                    self.nodes.pop(ident, None)
                    self.sink.on_node_delete(ident)
            else:
                if etype == "ADDED":
                    if self._selects(obj):
                        self.pods[ident] = obj
                        self.sink.on_pod_add(obj)
                elif etype == "MODIFIED":
                    was = ident in self.pods
                    now = self._selects(obj)
                    if was and now:
                        old = self.pods[ident]
                        self.pods[ident] = obj
                        self.sink.on_pod_update(old, obj)
                    elif was:  # left the selector → DELETE, never retain
                        self.sink.on_pod_delete(self.pods.pop(ident))
                    elif now:  # entered the selector → ADD
                        self.pods[ident] = obj
                        self.sink.on_pod_add(obj)
                else:
                    old = self.pods.pop(ident, None)
                    if old is not None:
                        self.sink.on_pod_delete(old)
        return len(events)

    def resync(self) -> None:
        """Re-deliver every known object as an update — the SharedInformer
        resync loop (shared_informer.go resyncPeriod); handlers must treat
        it as a no-op when nothing changed."""
        for nd in self.nodes.values():
            self.sink.on_node_update(nd)
        for key, p in self.pods.items():
            self.sink.on_pod_update(p, p)
