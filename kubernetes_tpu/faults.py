"""Fault injection + resilience primitives for the batched solve path.

The paper's premise — a Go control plane trusting an out-of-process TPU
solver across the extender/gRPC seam — only holds if the scheduler
survives that solver timing out, crashing, or returning garbage. This
module supplies both halves of proving that:

- :class:`FaultInjector` — a **deterministic, seeded** harness that arms
  fault rules against named sites ("solve:batch", "extender:filter",
  "grpc:Filter") and fires them from a private RNG stream, so a chaos
  run replays bit-identically under ``-p no:randomly``. It plugs into
  the solver entry (``ops/assign.py`` ``fault_hook``), the HTTP extender
  transport, and the gRPC shim client.

- :class:`CircuitBreaker` — closed → open → half-open per solver tier /
  extender endpoint. While open the tier is skipped outright (no latency
  burned on a wedged TPU); after ``open_duration_s`` a bounded number of
  half-open probes retry the real call — the health probe IS a solve —
  and a success closes the breaker again.

- :class:`RetryPolicy` — bounded retry with exponential backoff + full
  jitter for the transport seams (and the in-process solver tiers, where
  the backoff sleep is injectable so fake-clock tests never block).

The injected fault classes map one-to-one onto the validation /
exception paths of the degradation ladder (scheduler.py
``_solve_ladder`` + ops/assign.py ``validate_solution``):

========== ============================================================
kind        what it simulates → what catches it
========== ============================================================
timeout     solver/transport deadline blown → SolverTimeout / socket.timeout
connection  TPU service crash / conn refused → SolverCrash / ConnectionError
partial     truncated response (half the rows) → shape check
stale       snapshot race: rows from a dead snapshot → range check
garbage     corrupt assignment indices → range/invalid-node check
nan         NaN/Inf cost or usage tensors → finiteness check
infeasible  lying solver overpacking node 0 → capacity re-check
truncated   torn wire frame → ValueError from the transport
error-field remote verb error → extender error-result path
corrupt     mistyped payload → response-parse hardening (ExtenderError)
========== ============================================================

The NETWORK fault kinds (the hub/REST/watch seam, PR 15) ride the same
injector through :meth:`FaultInjector.rpc_hook`:

=========== ===========================================================
rpc_error    the RPC definitely failed before the server acted →
             :class:`RPCError`; a blind retry is safe
rpc_timeout  the RPC timed out AMBIGUOUSLY — the server may or may not
             have committed → :class:`RPCTimeout`; the scheduler's bind
             protocol resolves it by read-your-write verification (GET
             the pod, compare uid+nodeName, adopt or requeue — never a
             blind re-bind that could double-place)
latency      the call succeeds after an injected delay (rule.latency_s)
drop /       watch-stream faults (chaos.FuzzedCursor at "watch:event" /
duplicate /  "watch:batch"): events vanish, repeat, or arrive out of
reorder      order — the Reflector's resourceVersion-monotonic dedupe
             must make them no-ops
compacted    a forced 410/Compacted on the watch — the relist-storm
             trigger
=========== ===========================================================
"""

from __future__ import annotations

import fnmatch
import socket
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple


class SolverFault(Exception):
    """Base of the injected/derived solver failures the ladder catches."""


class RPCError(Exception):
    """A hub RPC failed DEFINITELY before the server acted (connection
    refused, 5xx before the handler ran). The operation did not commit;
    retrying through the normal requeue path is safe."""


class RPCTimeout(Exception):
    """A hub RPC timed out with an AMBIGUOUS outcome: the server may or
    may not have committed the operation before the response was lost.
    For a bind this is the dangerous class — a blind retry could bind a
    pod that IS already bound (a hub CAS conflict at best, a double
    placement with a less careful store). The scheduler resolves it by
    read-your-write verification (GET the pod, compare uid + nodeName,
    then adopt or requeue — scheduler._resolve_ambiguous_bind)."""


class SolverTimeout(SolverFault):
    """The solve blew its deadline (injected, or a transport timeout)."""


class SolverCrash(SolverFault):
    """The solver process/connection died mid-solve."""


class SolverResultInvalid(SolverFault):
    """The solver answered, but validation rejected the result."""


class DeviceLost(SolverFault):
    """The accelerator went away under us (XLA "device lost" class):
    resident buffers — the device snapshot, in-flight solves — are gone.
    Recovery rebuilds the resident table from the host mirror
    (cache.drop_device_snapshot) and the ladder absorbs the solve
    outage (batch -> batch-cpu -> greedy) until the device heals."""


class DeviceOOM(SolverFault):
    """Device allocation failure (RESOURCE_EXHAUSTED class). Same
    recovery path as :class:`DeviceLost`: drop residents, rebuild from
    host, degrade to the CPU tiers meanwhile."""


class ShardLost(DeviceLost):
    """ONE device of a mesh went away (a single-chip loss on a multi-
    chip slice). Sharded resident buffers have a shard on every mesh
    device, so losing any one of them poisons every collective — the
    recovery path is the DeviceLost path (drop residents, host-mode
    snapshots through the cooloff), and the heal probe re-places
    SHARDED once the mesh answers again. ``shard`` carries the lost
    device's mesh index for the chaos reports."""

    def __init__(self, message: str, shard: int = 0) -> None:
        super().__init__(message)
        self.shard = int(shard)


# ---------------------------------------------------------------------------
# Circuit breaker (closed -> open -> half-open)
# ---------------------------------------------------------------------------

CLOSED, HALF_OPEN, OPEN = "closed", "half-open", "open"

#: numeric encoding for the scheduler_circuit_breaker_state gauge
STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Per-target breaker: ``failure_threshold`` consecutive failures
    open it; after ``open_duration_s`` it half-opens and admits up to
    ``half_open_probes`` trial calls (the health probes — real calls,
    not pings); a probe success closes it, a probe failure re-opens."""

    def __init__(
        self,
        failure_threshold: int = 3,
        open_duration_s: float = 30.0,
        half_open_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str, str], None]] = None,
    ) -> None:
        self.failure_threshold = max(1, int(failure_threshold))
        self.open_duration_s = open_duration_s
        self.half_open_probes = max(1, int(half_open_probes))
        self.clock = clock
        self.on_transition = on_transition
        self.state = CLOSED
        self.consecutive_failures = 0
        self.opened_at = 0.0
        self._probes_used = 0
        #: lifetime transition count (observability/tests)
        self.opens = 0

    def _transition(self, new: str) -> None:
        old, self.state = self.state, new
        if new == OPEN:
            self.opens += 1
            self.opened_at = self.clock()
        if new == HALF_OPEN:
            self._probes_used = 0
        if self.on_transition is not None and old != new:
            self.on_transition(old, new)

    def allow(self) -> bool:
        """May the next call go through? Half-open admits a bounded
        number of probes per open->half-open episode."""
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if self.clock() - self.opened_at < self.open_duration_s:
                return False
            self._transition(HALF_OPEN)
        # HALF_OPEN
        if self._probes_used < self.half_open_probes:
            self._probes_used += 1
            return True
        return False

    def record_success(self) -> None:
        self.consecutive_failures = 0
        if self.state != CLOSED:
            self._transition(CLOSED)

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.state == HALF_OPEN or (
            self.state == CLOSED
            and self.consecutive_failures >= self.failure_threshold
        ):
            self._transition(OPEN)

    def state_code(self) -> int:
        return STATE_CODE[self.state]


# ---------------------------------------------------------------------------
# Bounded retry with exponential backoff + jitter
# ---------------------------------------------------------------------------


class RetryPolicy:
    """``call(fn)`` retries on the configured exception classes with
    exponential backoff and full jitter (AWS-style: sleep uniform in
    [0, min(max, base·2^attempt)·(1+jitter)]). ``sleep`` is injectable
    so fake-clock tests and the in-cycle solver retries never block."""

    def __init__(
        self,
        max_retries: int = 2,
        base_s: float = 0.05,
        max_s: float = 2.0,
        jitter: float = 0.2,
        seed: int = 0,
        sleep: Callable[[float], None] = time.sleep,
        retry_on: Tuple[type, ...] = (Exception,),
    ) -> None:
        import random

        self.max_retries = max(0, int(max_retries))
        self.base_s = base_s
        self.max_s = max_s
        self.jitter = jitter
        self.sleep = sleep
        self.retry_on = retry_on
        self._rng = random.Random(seed)
        #: lifetime retry count (tests/metrics read this)
        self.retries = 0

    def backoff_s(self, attempt: int) -> float:
        cap = min(self.max_s, self.base_s * (2.0 ** attempt))
        # clamp: a jitter > 1 (or negative base) must never produce a
        # negative delay — time.sleep(negative) raises
        return max(0.0, cap * (1.0 + self.jitter
                               * (self._rng.random() * 2.0 - 1.0)))

    def call(self, fn, deadline_s: Optional[float] = None,
             clock: Callable[[], float] = time.monotonic,
             on_retry: Optional[Callable[[int, Exception], None]] = None):
        """Run ``fn`` with bounded retries. ``deadline_s`` (absolute, on
        ``clock``) stops retrying when the next backoff would cross it —
        the last error propagates rather than blowing the cycle budget."""
        attempt = 0
        while True:
            try:
                return fn()
            except self.retry_on as e:
                if attempt >= self.max_retries:
                    raise
                delay = self.backoff_s(attempt)
                if deadline_s is not None and clock() + delay >= deadline_s:
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                self.retries += 1
                self.sleep(delay)
                attempt += 1


# ---------------------------------------------------------------------------
# Fault injection
# ---------------------------------------------------------------------------

#: kinds that raise at the call site instead of corrupting a payload
_RAISING = {
    "timeout": lambda site: socket.timeout(f"injected timeout at {site}"),
    "connection": lambda site: ConnectionError(
        f"injected connection reset at {site}"),
    "truncated": lambda site: ValueError(
        f"injected truncated frame at {site}"),
}

#: solver-side raising kinds (typed for the ladder's except clauses)
_SOLVER_RAISING = {
    "timeout": lambda site: SolverTimeout(f"injected solver timeout at {site}"),
    "connection": lambda site: SolverCrash(
        f"injected solver connection loss at {site}"),
    "crash": lambda site: SolverCrash(f"injected solver crash at {site}"),
    "device_lost": lambda site: DeviceLost(
        f"injected device loss at {site}"),
    "device_oom": lambda site: DeviceOOM(
        f"injected device OOM at {site}"),
    "shard_lost": lambda site: ShardLost(
        f"injected mesh shard loss at {site}"),
}

#: kinds the device-site hook (snapshot scatter / warmup compile) raises —
#: the accelerator-loss class, distinct from solver-result corruption
_DEVICE_RAISING = {
    "device_lost": _SOLVER_RAISING["device_lost"],
    "device_oom": _SOLVER_RAISING["device_oom"],
    "shard_lost": _SOLVER_RAISING["shard_lost"],
}


@dataclass
class FaultRule:
    """One armed fault: fnmatch ``site`` pattern, fault ``kind``, firing
    probability ``rate``, optional bounded ``remaining`` shot count.
    ``shard`` rides along for ``shard_lost`` rules so the raised
    :class:`ShardLost` names the lost mesh device; ``latency_s`` is the
    injected delay of a ``latency`` rule; ``commit_rate`` is the
    probability an ambiguous ``rpc_timeout`` DID commit server-side
    before the response was lost."""

    site: str
    kind: str
    rate: float = 1.0
    remaining: Optional[int] = None
    shard: Optional[int] = None
    latency_s: float = 0.0
    commit_rate: float = 0.5


class FaultInjector:
    """Deterministic seeded fault source shared by every hook site.

    Arm rules with :meth:`arm`; each hook consults :meth:`pick` with its
    site name. Rules match by ``fnmatch`` (so ``"solve:batch*"`` poisons
    both the TPU and CPU batch tiers but not the greedy oracle), fire
    from one private RNG stream (replayable), and may be shot-limited.
    """

    def __init__(self, seed: int = 0) -> None:
        import random

        self.rng = random.Random(seed)
        self.rules: List[FaultRule] = []
        #: (site, kind) -> times fired (assertable by chaos tests)
        self.fired: Dict[Tuple[str, str], int] = {}

    def arm(self, site: str, kind: str, rate: float = 1.0,
            count: Optional[int] = None,
            shard: Optional[int] = None,
            latency_s: float = 0.0,
            commit_rate: float = 0.5) -> "FaultInjector":
        self.rules.append(FaultRule(site, kind, rate, count, shard,
                                    latency_s, commit_rate))
        return self

    def fired_total(self, site_pattern: str = "*") -> int:
        return sum(n for (s, _), n in self.fired.items()
                   if fnmatch.fnmatch(s, site_pattern))

    def disarm(self, site_pattern: str = "*",
               kind: Optional[str] = None) -> int:
        """Remove armed rules matching ``site_pattern`` (and ``kind``,
        when given); returns how many were removed. The phase-scoped
        fault window: a soak phase arms its rules at entry and disarms
        exactly its own at exit, leaving any longer-lived rules (a
        whole-soak background latency rule) in place — clearing
        ``rules`` wholesale would close those too. Firing counters
        survive disarm: per-phase deltas stay attributable."""
        keep = [r for r in self.rules
                if not (fnmatch.fnmatch(r.site, site_pattern)
                        and (kind is None or r.kind == kind))]
        removed = len(self.rules) - len(keep)
        self.rules[:] = keep
        return removed

    def window(self, site: str, kind: str, **kw):
        """Context manager: arm one rule on entry, disarm THAT rule on
        exit (even on error) — the bracket a :class:`soak.SoakPhase`'s
        arm/disarm hooks are built from."""
        import contextlib

        @contextlib.contextmanager
        def _window():
            self.arm(site, kind, **kw)
            rule = self.rules[-1]
            try:
                yield rule
            finally:
                with contextlib.suppress(ValueError):
                    self.rules.remove(rule)

        return _window()

    def pick_rule(self, site: str,
                  kinds: Optional[Tuple[str, ...]] = None
                  ) -> Optional[FaultRule]:
        """First armed, matching, non-exhausted rule that passes its
        rate roll; records the firing and decrements bounded shots.
        ``kinds`` restricts the roll to rules of those kinds — callers
        whose site hosts several kinds with different applicability
        (watch:batch: a 410 fires on any poll, a reorder only when
        there are >= 2 frames to shuffle) roll each separately so an
        inapplicable pick never burns a bounded rule's shot or records
        a firing that did nothing."""
        for rule in self.rules:
            if rule.remaining == 0 or not fnmatch.fnmatch(site, rule.site):
                continue
            if kinds is not None and rule.kind not in kinds:
                continue
            if rule.rate < 1.0 and self.rng.random() >= rule.rate:
                continue
            if rule.remaining is not None:
                rule.remaining -= 1
            key = (site, rule.kind)
            self.fired[key] = self.fired.get(key, 0) + 1
            return rule
        return None

    def pick(self, site: str,
             kinds: Optional[Tuple[str, ...]] = None) -> Optional[str]:
        """Kind-only view of :meth:`pick_rule` (the original surface)."""
        rule = self.pick_rule(site, kinds)
        return rule.kind if rule is not None else None

    # -- transport seam (HTTP extender / gRPC shim) ------------------------

    def transport_fault(self, site: str) -> Optional[str]:
        """Raise for raising kinds; return corruption kinds ("corrupt",
        "error-field", "partial") for the caller to apply to its
        response; None = no fault."""
        kind = self.pick(site)
        if kind in _RAISING:
            raise _RAISING[kind](site)
        return kind

    @staticmethod
    def corrupt_response(kind: Optional[str], resp: dict) -> dict:
        """Apply a non-raising transport fault to a decoded response."""
        if kind == "error-field":
            return {"error": "injected remote failure"}
        if kind == "corrupt":
            # mistyped payload: exercises the parse hardening, which must
            # convert it into ExtenderError instead of crashing the cycle
            return {"nodenames": 12345, "failedNodes": "not-a-map"}
        if kind == "partial":
            # keys missing entirely — a half-written frame that still
            # decoded as JSON
            return {}
        return resp

    # -- device seam (snapshot scatter / warmup compile) -------------------

    def device_hook(self, site: str) -> Optional[str]:
        """Raise for the accelerator-loss kinds (``device_lost``,
        ``device_oom``, ``shard_lost``) armed at a device site — the
        snapshot scatter ("snapshot:device") and the AOT warmup
        ("warmup:compile") call this before touching the device; other
        kinds are returned for the caller to interpret (usually
        ignored). A ``shard_lost`` rule's ``shard`` index rides the
        raised exception."""
        rule = self.pick_rule(site)
        if rule is None:
            return None
        if rule.kind == "shard_lost":
            raise ShardLost(f"injected mesh shard loss at {site}",
                            shard=rule.shard or 0)
        if rule.kind in _DEVICE_RAISING:
            raise _DEVICE_RAISING[rule.kind](site)
        return rule.kind

    # -- hub RPC seam (binder / REST facade / pod-reader GET) --------------

    def rpc_hook(self, site: str):
        """Network-fault decision for one hub RPC (the bind commit, a
        verification GET, a REST verb). Returns ``None`` (no fault) or a
        triple ``(kind, rule, committed)``:

        - ``("rpc_error", rule, False)`` — the caller must raise
          :class:`RPCError` WITHOUT performing the server-side effect;
        - ``("rpc_timeout", rule, committed)`` — the AMBIGUOUS kind: the
          caller performs the server-side effect iff ``committed`` (the
          rule's ``commit_rate`` coin, rolled on the injector's private
          stream so runs replay), then raises :class:`RPCTimeout` either
          way — the client can never tell the two apart;
        - ``("latency", rule, True)`` — delay ``rule.latency_s`` then
          proceed normally.

        Other kinds armed at an rpc site are returned verbatim for the
        caller to interpret (site-specific extensions)."""
        rule = self.pick_rule(site)
        if rule is None:
            return None
        if rule.kind == "rpc_timeout":
            return (rule.kind, rule, self.rng.random() < rule.commit_rate)
        if rule.kind == "rpc_error":
            return (rule.kind, rule, False)
        return (rule.kind, rule, True)

    # -- solver seam (ops/assign.py fault_hook) ----------------------------

    def solver_hook(self, site: str, assigned, usage, rounds, n_nodes: int):
        """The ``fault_hook`` contract of batch_assign/greedy_assign:
        called after the solve with the would-be result; may raise a
        :class:`SolverFault` or return a poisoned (assigned, usage,
        rounds) triple."""
        kind = self.pick(site)
        if kind is None:
            return assigned, usage, rounds
        if kind in _SOLVER_RAISING:
            raise _SOLVER_RAISING[kind](site)
        return poison_solution(kind, assigned, usage, rounds, n_nodes,
                               self.rng)


def poison_solution(kind: str, assigned, usage, rounds, n_nodes: int, rng):
    """Corrupt a solver result the way a specific failure class would —
    each mapping to exactly one validate_solution rejection reason."""
    import jax.numpy as jnp
    import numpy as np

    a = np.array(assigned)  # graftlint: disable=R7 -- chaos harness: materializes the result to poison it
    if kind == "partial":
        # truncated response: half the rows never arrived
        return a[: max(1, a.shape[0] // 2)], usage, rounds
    if kind == "stale":
        # stale-snapshot race: node rows that only existed in a previous
        # snapshot generation (indices past the live table)
        a = np.where(a >= 0, a + n_nodes + 3, a)
        return a, usage, rounds
    if kind == "garbage":
        a = np.asarray(
            [rng.randrange(-3, n_nodes + 5) for _ in range(a.shape[0])],
            dtype=np.int32,
        )
        return a, usage, rounds
    if kind == "nan":
        usage = usage._replace(
            requested=jnp.full_like(usage.requested, jnp.nan))
        return a, usage, rounds
    if kind == "infeasible":
        # the lying solver: every pod "fits" on node 0
        a = np.where(a >= 0, 0, a)
        return a, usage, rounds
    raise ValueError(f"unknown fault kind {kind!r}")
