"""gRPC streaming shim — the BASELINE-named integration seam: a gRPC
service speaking extender-shaped messages (api/types.go:284-330) with a
bidirectional snapshot-delta stream so the node cache stays resident
service-side (nodeCacheCapable semantics, ExtenderConfig api/types.go:203).

Transport layering vs the reference: where the HTTP webhook seam
(extender.py / server.py ExtenderServer) re-sends state per request, this
seam is level-triggered like the control plane itself — the client streams
watch deltas (SyncState), the service applies them to the scheduler's
cache and acks with the applied revision (the resume point, mirroring
watch bookmarks), and Filter/Prioritize then travel with node NAMES only.

The service stubs are hand-wired over ``grpc.method_handlers_generic_
handler`` with the protoc-generated message classes
(``proto/extender_pb2.py``) — the environment ships protoc + grpcio but
not the grpc_tools codegen plugin, and the generic-handler API is exactly
what generated ``*_pb2_grpc.py`` code calls underneath.
"""

from __future__ import annotations

import json
import threading
from concurrent import futures
from typing import Iterator, Optional

import grpc

from kubernetes_tpu.api.types import Node, NodeCondition, Resources, Taint
from kubernetes_tpu.api.protobuf import (
    node_from_pb,
    node_to_pb,
    pod_from_pb,
    pod_to_pb,
)
from kubernetes_tpu.extender import node_to_json, pod_to_json
from kubernetes_tpu.proto import corev1_pb2
from kubernetes_tpu.proto import extender_pb2 as pb
from kubernetes_tpu.server import ExtenderServer, parse_quantity, pod_from_json

SERVICE_NAME = "ktpu.TpuScheduler"


def node_from_json(d: dict) -> Node:
    """Inverse of extender.node_to_json for the fields the kernels read."""
    meta = d.get("metadata", {})
    status = d.get("status", {})
    alloc = status.get("allocatable") or {}
    res = Resources(
        cpu_milli=parse_quantity(alloc.get("cpu", "0"), is_cpu=True),
        memory=parse_quantity(alloc.get("memory", "0")),
        pods=parse_quantity(alloc.get("pods", "110")),
    )
    for name, q in alloc.items():
        if name not in ("cpu", "memory", "pods", "ephemeral-storage"):
            res.scalars[name] = parse_quantity(q)
    if "ephemeral-storage" in alloc:
        res.ephemeral_storage = parse_quantity(alloc["ephemeral-storage"])
    spec = d.get("spec") or {}
    taints = tuple(
        Taint(key=t.get("key", ""), value=t.get("value", ""),
              effect=t.get("effect", ""))
        for t in (spec.get("taints") or [])
    )
    # conditions: the two mandatory-predicate inputs plus the pressure
    # flags (CheckNodeConditionPredicate reads Ready/NetworkUnavailable;
    # absent Ready stays True — node_to_json always emits it)
    flags = {
        c.get("type"): c.get("status") == "True"
        for c in (status.get("conditions") or [])
    }
    cond = NodeCondition(
        ready=flags.get("Ready", True),
        memory_pressure=flags.get("MemoryPressure", False),
        disk_pressure=flags.get("DiskPressure", False),
        pid_pressure=flags.get("PIDPressure", False),
        network_unavailable=flags.get("NetworkUnavailable", False),
    )
    images = {}
    for img in status.get("images") or []:
        for name in img.get("names") or []:
            images[name] = float(img.get("sizeBytes", 0))
    avoid = ()
    ann = (meta.get("annotations") or {}).get(
        "scheduler.alpha.kubernetes.io/preferAvoidPods"
    )
    if ann:
        try:
            avoid = tuple(
                e["podSignature"]["podController"]["uid"]
                for e in json.loads(ann).get("preferAvoidPods", [])
                if e.get("podSignature", {}).get("podController", {}).get("uid")
            )
        except (ValueError, TypeError, KeyError, AttributeError):
            avoid = ()  # malformed annotation ignored, like the reference
    labels = dict(meta.get("labels") or {})
    # the kubelet self-labels every node with kubernetes.io/hostname
    # (pkg/kubelet well-known labels); nodes ingested without it would
    # break hostname-pinned placement (DaemonSet affinity)
    if meta.get("name"):
        labels.setdefault("kubernetes.io/hostname", meta["name"])
    # annotations round-trip EXCEPT preferAvoidPods, which parses into
    # the dedicated field (node_to_json re-emits it from there — keeping
    # both would double it on the next serialization)
    annotations = {k: v for k, v in (meta.get("annotations") or {}).items()
                   if k != "scheduler.alpha.kubernetes.io/preferAvoidPods"}
    return Node(
        name=meta.get("name", ""),
        labels=labels,
        allocatable=res,
        taints=taints,
        conditions=cond,
        unschedulable=bool(spec.get("unschedulable", False)),
        images=images,
        prefer_avoid_owner_uids=avoid,
        annotations=annotations,
        pod_cidr=spec.get("podCIDR", ""),
    )


class TpuSchedulerService:
    """Service implementation over a live Scheduler (its cache is the
    resident snapshot the deltas feed)."""

    def __init__(self, scheduler, fault_injector=None) -> None:
        self.scheduler = scheduler
        self.extender = ExtenderServer(scheduler)
        #: deltas serialize against verbs; a service-side cycle loop must
        #: hold this too (sync_state mutates the same cache/queue)
        self.lock = threading.Lock()
        self.revision = 0
        #: chaos seam (kubernetes_tpu/faults.py): fires per served verb
        #: ("grpc-service:filter", ...) — a raising fault rides the
        #: verb's error-result path, simulating a crashing service
        self.fault_injector = fault_injector

    # -- SyncState (bidi stream) -------------------------------------------

    def sync_state(self, request_iterator: Iterator[pb.SnapshotDelta],
                   context) -> Iterator[pb.SyncAck]:
        s = self.scheduler
        for delta in request_iterator:
            with self.lock:
                for nd in delta.nodes:
                    if nd.op == pb.NodeDelta.REMOVE:
                        s.on_node_delete(nd.name)
                    else:
                        if nd.node_pb:
                            msg = corev1_pb2.NodeMsg()
                            msg.ParseFromString(nd.node_pb)
                            node = node_from_pb(msg)
                        else:
                            node = node_from_json(json.loads(nd.node_json))
                        if nd.op == pb.NodeDelta.ADD:
                            s.on_node_add(node)
                        else:
                            s.on_node_update(node)
                for pd in delta.pods:
                    if pd.op == pb.PodDelta.REMOVE:
                        known = s.cache.pod(pd.key) or s.queue.pod(pd.key)
                        if known is None:  # unseen key: synthesize for cleanup
                            ns, _, name = pd.key.partition("/")
                            from kubernetes_tpu.api.types import Pod as _Pod

                            known = _Pod(name=name, namespace=ns)
                        s.on_pod_delete(known)
                    else:
                        if pd.pod_pb:
                            msg = corev1_pb2.PodMsg()
                            msg.ParseFromString(pd.pod_pb)
                            pod = pod_from_pb(msg)
                        else:
                            pod = pod_from_json(json.loads(pd.pod_json))
                        known = s.cache.pod(pd.key) or s.queue.pod(pd.key)
                        if known is not None:
                            # the UPDATE path owns the queue-removal /
                            # assumption-confirm / Permit-wait invariants
                            # (scheduler.py on_pod_update) — routing
                            # updates through on_pod_add would double-book
                            # a bound pod's capacity
                            s.on_pod_update(known, pod)
                        else:
                            s.on_pod_add(pod)
                self.revision = max(self.revision, delta.revision)
                n_nodes = s.cache.node_count()
                # snapshot while still locked: acking a revision some
                # OTHER stream advanced to would claim deltas this
                # stream never applied
                ack_rev = self.revision
            yield pb.SyncAck(revision=ack_rev,
                            nodes_in_snapshot=n_nodes)

    # -- unary verbs --------------------------------------------------------

    def filter(self, request: pb.ExtenderArgs, context) -> pb.ExtenderFilterResult:
        with self.lock:
            payload = {"pod": json.loads(request.pod_json)}
            if request.node_names:
                payload["nodenames"] = list(request.node_names)
            try:
                kind = None
                if self.fault_injector is not None:
                    kind = self.fault_injector.transport_fault(
                        "grpc-service:filter")
                r = self.extender.handle("filter", payload)
                if kind is not None:
                    # ROADMAP bug (d): the armed corruption must actually
                    # poison the response (a discarded kind was a no-op
                    # that still consumed shots); a corrupted shape then
                    # fails result construction below and rides the
                    # error-result path like any remote failure
                    r = self.fault_injector.corrupt_response(kind, r)
                result = pb.ExtenderFilterResult(
                    node_names=r.get("nodenames") or [],
                    failed_nodes=r.get("failedNodes") or {},
                    error=r.get("error", ""),
                )
            except Exception as e:  # verb errors ride the result message
                return pb.ExtenderFilterResult(error=str(e))
        return result

    def prioritize(self, request: pb.ExtenderArgs, context) -> pb.HostPriorityList:
        with self.lock:
            payload = {"pod": json.loads(request.pod_json)}
            if request.node_names:
                payload["nodenames"] = list(request.node_names)
            try:
                kind = None
                if self.fault_injector is not None:
                    kind = self.fault_injector.transport_fault(
                        "grpc-service:prioritize")
                r = self.extender.handle("prioritize", payload)
                if kind is not None:
                    # bug (d) as above: apply the corruption; a mistyped
                    # payload fails the item loop and becomes the verb's
                    # error result
                    r = self.fault_injector.corrupt_response(kind, r)
                out = pb.HostPriorityList()
                for item in r:
                    out.items.add(host=item["host"], score=item["score"])
            except Exception as e:
                return pb.HostPriorityList(error=str(e))
        return out

    def get_state(self, request: pb.StateRequest, context) -> pb.StateSnapshot:
        """Read-only snapshot dump for tooling (the ktpu CLI's 'get'
        source): cache nodes, bound/assumed pods, queued pods."""
        s = self.scheduler
        with self.lock:
            out = pb.StateSnapshot(revision=self.revision)
            if request.kind in ("", "nodes"):
                for nd in s.cache.nodes():
                    out.node_json.append(json.dumps(node_to_json(nd)))
            if request.kind in ("", "pods"):
                for nd in s.cache.nodes():
                    for p in s.cache.pods_on(nd.name):
                        out.pod_json.append(json.dumps(pod_to_json(p)))
                for qname, pods in s.queue.pending_pods().items():
                    for p in pods:
                        out.pending_json.append(json.dumps(
                            {"queue": qname, "pod": pod_to_json(p)}
                        ))
        return out

    def bind(self, request: pb.Binding, context) -> pb.BindResult:
        """The Binding-subresource write (BindingREST.Create → assignPod,
        registry/core/pod/storage/storage.go:154): a pending pod moves
        from the queue into the cache bound to the target node."""
        s = self.scheduler
        with self.lock:
            key = request.pod_key
            if s.cache.pod(key) is not None:
                return pb.BindResult(ok=False,
                                     error=f"pod {key!r} already bound")
            pod = s.queue.pod(key)
            if pod is None:
                return pb.BindResult(ok=False,
                                     error=f"pod {key!r} not in snapshot")
            try:
                s.queue.delete(key)
                s.cache.assume_pod(pod, request.node)
            except Exception as e:
                s.queue.add(pod)
                return pb.BindResult(ok=False, error=str(e))
        # the binder may be a real network hop (the chaos harness wraps
        # it in injected latency/timeouts) — holding the service lock
        # across it would stall every other verb for the round trip.
        # The ASSUME above already reserves the pod optimistically
        # (scheduler.go's assume-then-bind design), so concurrent binds
        # of the same key fail the cache.pod() check either way.
        try:
            s.binder.bind(pod, request.node)
            with self.lock:
                s.cache.finish_binding(key)
        except Exception as e:
            with self.lock:
                try:
                    s.cache.forget_pod(key)
                except Exception:
                    pass
                # bind failure re-queues (scheduler.go:447 error path) —
                # dropping the pod from both queue and cache would strand
                # it until the client re-sends an ADD delta
                s.queue.add(pod)
            return pb.BindResult(ok=False, error=str(e))
        return pb.BindResult(ok=True, error="")


def _authed(fn, token):
    """Bearer-token gate for one RPC behavior — the wire seam's analog of
    the REST facade's WithAuthentication filter (the reference secures
    this hop with TLS/token auth on the apiserver connection). The check
    runs eagerly at call time, BEFORE any stream generator is returned,
    so streaming RPCs reject as early as unary ones. A falsy token
    (None or "") keeps the seam open on BOTH sides — an unset env var
    must not produce a server demanding the empty bearer string.

    ``token`` may also be a CALLABLE ``raw_token -> bool`` — a live
    validator (e.g. the hub's service-account token registry), so the
    gRPC seam consumes the same revocable identities the REST chain
    does (tokens_controller analog)."""
    import hmac

    if not token:
        return fn

    if callable(token):
        def check(request_or_iterator, context):
            md = dict(context.invocation_metadata())
            raw = md.get("authorization", "")
            ok = raw.startswith("Bearer ") and token(raw[len("Bearer "):])
            if not ok:
                context.abort(grpc.StatusCode.UNAUTHENTICATED,
                              "invalid bearer token")
            return fn(request_or_iterator, context)

        return check

    want = f"Bearer {token}"

    def check(request_or_iterator, context):
        md = dict(context.invocation_metadata())
        # constant-time compare: this IS the authentication filter
        if not hmac.compare_digest(md.get("authorization", ""), want):
            context.abort(grpc.StatusCode.UNAUTHENTICATED,
                          "invalid bearer token")
        return fn(request_or_iterator, context)

    return check


def _handlers(svc: TpuSchedulerService,
              token: "str | None" = None) -> grpc.GenericRpcHandler:
    rpcs = {
        "SyncState": grpc.stream_stream_rpc_method_handler(
            _authed(svc.sync_state, token),
            request_deserializer=pb.SnapshotDelta.FromString,
            response_serializer=pb.SyncAck.SerializeToString,
        ),
        "Filter": grpc.unary_unary_rpc_method_handler(
            _authed(svc.filter, token),
            request_deserializer=pb.ExtenderArgs.FromString,
            response_serializer=pb.ExtenderFilterResult.SerializeToString,
        ),
        "Prioritize": grpc.unary_unary_rpc_method_handler(
            _authed(svc.prioritize, token),
            request_deserializer=pb.ExtenderArgs.FromString,
            response_serializer=pb.HostPriorityList.SerializeToString,
        ),
        "Bind": grpc.unary_unary_rpc_method_handler(
            _authed(svc.bind, token),
            request_deserializer=pb.Binding.FromString,
            response_serializer=pb.BindResult.SerializeToString,
        ),
        "GetState": grpc.unary_unary_rpc_method_handler(
            _authed(svc.get_state, token),
            request_deserializer=pb.StateRequest.FromString,
            response_serializer=pb.StateSnapshot.SerializeToString,
        ),
    }
    return grpc.method_handlers_generic_handler(SERVICE_NAME, rpcs)


def serve_grpc(scheduler, address: str = "127.0.0.1:0",
               max_workers: int = 8, service=None, token=None):
    """Start the gRPC service; returns (server, bound_port). Pass an
    existing ``service`` to share it with a service-side cycle loop (which
    must hold ``service.lock`` around schedule_cycle). ``token`` gates
    every RPC behind `authorization: Bearer <token>` metadata (the wire
    seam's authentication filter); None/"" keeps the seam open."""
    if service is not None and service.scheduler is not scheduler:
        raise ValueError(
            "serve_grpc: `service` wraps a different Scheduler than the one "
            "passed — RPCs would act on service.scheduler while the caller "
            "drives the other"
        )
    svc = service or TpuSchedulerService(scheduler)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((_handlers(svc, token),))
    port = server.add_insecure_port(address)
    server.start()
    return server, port


class SnapshotDeltaBridge:
    """The control-plane shim: pumps a hub's watch events to the service
    as SnapshotDelta messages, preserving cross-kind event order (one
    delta per contiguous same-kind run — a node delete must not reorder
    around a pod bind). The deployment shape BASELINE targets: control
    plane streaming deltas to the TPU VM service.

    ``lock`` (pass the hub's own lock for a threaded driver) is held
    around list/poll so reads never race hub mutations; the wire send
    happens OUTSIDE it — a slow stream must not wedge the hub."""

    def __init__(self, hub, client: "GrpcSchedulerClient",
                 lock=None) -> None:
        import contextlib
        import os

        self.hub = hub
        self.client = client
        self._node_json = node_to_json
        self._pod_json = pod_to_json
        #: typed corev1 delta payloads (VERDICT r4 missing #5: proto
        #: codecs for the snapshot-feed wire) — on by default, both ends
        #: in-repo; KTPU_PROTO_FEED=0 falls back to JSON strings
        self.proto_feed = os.environ.get("KTPU_PROTO_FEED", "1") == "1"
        self._lock = lock if lock is not None else contextlib.nullcontext()
        # LIST and cursor registration must be ONE atomic step: the hub
        # only appends history while a cursor is open (sim._commit), so
        # an event committed between list_state(rev) and watch(rev) —
        # with no other cursor alive — would vanish without ever raising
        # Compacted. The wire send happens after, outside the lock.
        # list_state's dicts hold LIVE object references the hub mutates
        # in place, so serialization must happen inside the lock too —
        # same hazard pump() documents; only the wire send stays outside
        with self._lock:
            rev, nodes, pods = hub.list_state()
            self.cursor = hub.watch(rev)
            d = pb.SnapshotDelta(revision=rev)
            for nd in nodes.values():
                d.nodes.add(op=pb.NodeDelta.ADD, name=nd.name,
                            **self._payload(nd, node_to_pb, node_to_json,
                                            "node_pb", "node_json"))
            for p in pods.values():
                d.pods.add(op=pb.PodDelta.ADD, key=p.key(),
                           **self._payload(p, pod_to_pb, pod_to_json,
                                           "pod_pb", "pod_json"))
        list(client.sync_state(iter([d])))

    def _payload(self, obj, to_pb, to_json, pb_field, json_field) -> dict:
        """The ONE proto-vs-JSON payload choice for every delta site:
        kwargs for the delta's add() — typed bytes when the proto feed
        is on and an object exists, else the JSON string ("" on REMOVE
        frames, which carry no object either way)."""
        if obj is None:
            return {json_field: ""}
        if self.proto_feed:
            return {pb_field: to_pb(obj).SerializeToString()}
        return {json_field: json.dumps(to_json(obj))}

    NODE_OPS = {"ADDED": pb.NodeDelta.ADD,
                "MODIFIED": pb.NodeDelta.UPDATE,
                "DELETED": pb.NodeDelta.REMOVE}
    POD_OPS = {"ADDED": pb.PodDelta.ADD,
               "MODIFIED": pb.PodDelta.UPDATE,
               "DELETED": pb.PodDelta.REMOVE}

    def pump(self) -> int:
        node_ops, pod_ops = self.NODE_OPS, self.POD_OPS
        # poll AND serialize under the lock: the hub commits live object
        # references into watch history and mutates them in place, so a
        # threaded driver racing this loop could tear the JSON (dict
        # changed size mid-iteration) or stamp a delta whose body
        # reflects a later revision than it claims. Only the wire send
        # stays outside — a slow stream must not wedge the hub.
        with self._lock:
            events = self.cursor.poll()
            if not events:
                return 0
            deltas = []
            cur_kind = None
            d = None
            for rev, obj_key, etype, obj in events:
                kind, _, ident = obj_key.partition("/")
                if kind not in ("nodes", "pods"):
                    continue  # leases/volumes/events aren't scheduler feed
                if d is None or kind != cur_kind:
                    d = pb.SnapshotDelta(revision=rev)
                    deltas.append(d)
                    cur_kind = kind
                d.revision = rev
                if kind == "nodes":
                    d.nodes.add(op=node_ops[etype], name=ident,
                                **self._payload(obj, node_to_pb,
                                                node_to_json,
                                                "node_pb", "node_json"))
                else:
                    d.pods.add(op=pod_ops[etype], key=ident,
                               **self._payload(obj, pod_to_pb, pod_to_json,
                                               "pod_pb", "pod_json"))
        if deltas:
            list(self.client.sync_state(iter(deltas)))
        return len(events)


class GrpcSchedulerClient:
    """The Go-side shim's view: typed stubs over a channel (what a
    generated *_pb2_grpc.Stub provides). ``token`` attaches
    `authorization: Bearer <token>` metadata to every call (the client
    half of the seam's authentication).

    Robustness seams (kubernetes_tpu/faults.py): ``retry`` — a
    RetryPolicy applying bounded exponential backoff + jitter around
    each unary call (transient UNAVAILABLE/DEADLINE_EXCEEDED survive a
    retry; the stream is NOT retried here — reconnect-and-resume is the
    bridge's job via acked revisions); ``fault_injector`` — the chaos
    harness hook, firing per-verb before the wire call ("grpc:Filter",
    "grpc:Bind", ...)."""

    def __init__(self, target: str, token: "str | None" = None,
                 retry=None, fault_injector=None, obs=None):
        self.target = target
        self.channel = grpc.insecure_channel(target)
        self.retry = retry
        self.fault_injector = fault_injector
        #: observability facade (kubernetes_tpu/obs): per-verb transport
        #: spans on the caller's in-flight cycle trace (None = silent)
        self.obs = obs
        self._md = ([("authorization", f"Bearer {token}")]
                    if token else None)

        def with_md(callable_, verb: str = "", unary: bool = False):
            inj, md = self.fault_injector, self._md
            plain = (inj is None and obs is None
                     and not (unary and retry is not None))
            if md is None and plain:
                return callable_

            def call(*a, **kw):
                from contextlib import nullcontext

                if md is not None:
                    kw.setdefault("metadata", md)

                def once():
                    if inj is not None:
                        # raising kinds only on this typed seam: a
                        # corrupt frame fails protobuf decode, which
                        # grpc surfaces as an RpcError anyway
                        inj.transport_fault(f"grpc:{verb}")
                    return callable_(*a, **kw)

                span = (self.obs.span(f"grpc:{verb}")
                        if self.obs is not None else nullcontext())
                with span:
                    if unary and self.retry is not None:
                        return self.retry.call(once)
                    return once()

            return call

        base = f"/{SERVICE_NAME}/"
        self.sync_state = with_md(self.channel.stream_stream(
            base + "SyncState",
            request_serializer=pb.SnapshotDelta.SerializeToString,
            response_deserializer=pb.SyncAck.FromString,
        ), "SyncState")
        self.filter = with_md(self.channel.unary_unary(
            base + "Filter",
            request_serializer=pb.ExtenderArgs.SerializeToString,
            response_deserializer=pb.ExtenderFilterResult.FromString,
        ), "Filter", unary=True)
        self.prioritize = with_md(self.channel.unary_unary(
            base + "Prioritize",
            request_serializer=pb.ExtenderArgs.SerializeToString,
            response_deserializer=pb.HostPriorityList.FromString,
        ), "Prioritize", unary=True)
        self.bind = with_md(self.channel.unary_unary(
            base + "Bind",
            request_serializer=pb.Binding.SerializeToString,
            response_deserializer=pb.BindResult.FromString,
        ), "Bind", unary=True)
        self.get_state = with_md(self.channel.unary_unary(
            base + "GetState",
            request_serializer=pb.StateRequest.SerializeToString,
            response_deserializer=pb.StateSnapshot.FromString,
        ), "GetState", unary=True)

    def close(self) -> None:
        self.channel.close()
