"""HTTP serving shim: healthz + metrics + the extender-protocol server.

Two serving roles, mirroring the reference's two integration surfaces:

- :func:`serve_scheduler` — the component's own ``/healthz`` + ``/metrics``
  endpoints (app/server.go:214-234 installs these on every scheduler).
- :class:`ExtenderServer` — the *reverse* integration seam from
  BASELINE: this framework served AS a scheduler extender. A stock Go
  kube-scheduler configured with an HTTPExtender pointing here (verbs
  ``filter``/``prioritize``, ``nodeCacheCapable: true``) offloads
  filtering/scoring to the TPU batch kernels while keeping its own
  control loop; wire shapes follow pkg/scheduler/api/types.go:284-345.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from kubernetes_tpu.api.types import OwnerReference, Pod, Resources

def parse_quantity(s, is_cpu: bool = False) -> float:
    """Wire-seam quantity decode: cpu strings → milli-CPU, everything
    else → base units. Full suffix grammar lives in
    :mod:`kubernetes_tpu.api.quantity` (apimachinery ParseQuantity
    analog)."""
    from kubernetes_tpu.api import quantity

    return quantity.parse_cpu(s) if is_cpu else quantity.parse_quantity(s)


def _parse_deletion_ts(v) -> float:
    if not v:
        return 0.0
    from kubernetes_tpu.extender import rfc3339_to_epoch

    return rfc3339_to_epoch(v)


def pod_from_json(d: dict) -> Pod:
    """Inverse of extender.pod_to_json for the fields the kernels read."""
    from kubernetes_tpu.api.types import POD_PENDING, ReadinessProbe

    meta = d.get("metadata", {})
    spec = d.get("spec", {})
    status = d.get("status") or {}
    requests = Resources()
    probe = None
    for c in spec.get("containers", []):
        req = (c.get("resources") or {}).get("requests") or {}
        for name, q in req.items():
            if name == "cpu":
                requests.cpu_milli += parse_quantity(q, is_cpu=True)
            elif name == "memory":
                requests.memory += parse_quantity(q)
            elif name == "ephemeral-storage":
                requests.ephemeral_storage += parse_quantity(q)
            else:
                requests.scalars[name] = requests.scalars.get(name, 0) + parse_quantity(q)
        rp = c.get("readinessProbe")
        if probe is None and rp is not None:
            probe = ReadinessProbe(
                initial_delay_s=float(rp.get("initialDelaySeconds", 0)))
    ready = any(
        c.get("type") == "Ready" and c.get("status") == "True"
        for c in (status.get("conditions") or [])
    )
    return Pod(
        phase=status.get("phase", POD_PENDING),
        ready=ready,
        readiness_probe=probe,
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid", ""),
        labels=dict(meta.get("labels") or {}),
        owner_refs=tuple(
            OwnerReference(kind=r.get("kind", ""), name=r.get("name", ""),
                           uid=r.get("uid", ""))
            for r in (meta.get("ownerReferences") or [])
        ),
        node_name=spec.get("nodeName", ""),
        node_selector=dict(spec.get("nodeSelector") or {}),
        priority=int(spec.get("priority") or 0),
        scheduler_name=spec.get("schedulerName") or "default-scheduler",
        requests=requests,
        nominated_node_name=(d.get("status") or {}).get("nominatedNodeName", ""),
        preemption_policy=spec.get("preemptionPolicy")
        or "PreemptLowerPriority",
        deletion_timestamp=_parse_deletion_ts(meta.get("deletionTimestamp")),
    )


class ExtenderServer:
    """Serves filter/prioritize over the scheduler's cache snapshot using
    the device kernels — one pod per request (the extender protocol is
    per-pod), but filtering/scoring the whole node axis in one fused pass.
    """

    def __init__(self, scheduler) -> None:
        self.scheduler = scheduler

    # -- request handling --------------------------------------------------

    def handle(self, verb: str, payload: dict) -> dict:
        if verb == "filter":
            return self._filter(payload)
        if verb == "prioritize":
            return self._prioritize(payload)
        return {"error": f"unknown verb {verb!r}"}

    def _evaluate(self, payload: dict):
        from kubernetes_tpu.ops.arrays import (
            nodes_to_device,
            pods_to_device,
            selectors_to_device,
        )
        from kubernetes_tpu.ops.predicates import decode_reasons, run_predicates
        from kubernetes_tpu.ops.priorities import run_priorities

        s = self.scheduler
        pod = pod_from_json(payload["pod"])
        requested = payload.get("nodenames")
        pk = s.cache.packer
        pk.intern_pod(pod)
        nt = s.cache.snapshot()
        node_order = s.cache.node_order()
        dn = nodes_to_device(nt)
        dp = pods_to_device(pk.pack_pods([pod]))
        ds = selectors_to_device(pk.pack_selector_tables())
        fr = run_predicates(dp, dn, ds, None, None, None, s.pred_mask)
        score = run_priorities(dp, dn, ds, fr.mask, s.weights)
        mask = np.asarray(fr.mask)[0]
        reasons = np.asarray(fr.reasons)[0]
        scores = np.asarray(score)[0]
        rows: Dict[str, int] = {n: i for i, n in enumerate(node_order)}
        names = requested if requested is not None else node_order
        return pod, names, rows, mask, reasons, scores

    def _filter(self, payload: dict) -> dict:
        from kubernetes_tpu.ops.predicates import decode_reasons

        _, names, rows, mask, reasons, _ = self._evaluate(payload)
        ok, failed = [], {}
        for n in names:
            i = rows.get(n)
            if i is None:
                failed[n] = "node not in snapshot"
            elif mask[i]:
                ok.append(n)
            else:
                failed[n] = ",".join(decode_reasons(int(reasons[i]))) or "infeasible"
        return {"nodenames": ok, "failedNodes": failed, "error": ""}

    def _prioritize(self, payload: dict) -> dict:
        _, names, rows, mask, _, scores = self._evaluate(payload)
        # extender scores ride a 0-10 scale like in-tree priorities. The
        # fused kernel total is a weighted SUM of 0-10 terms (routinely
        # >10), so normalize per request — max feasible score maps to 10,
        # the reference's reduce-style normalization (_normalize_reduce /
        # NormalizeReduce, priorities/reduce.go) — before the clamp;
        # clamping raw totals would saturate every node at 10 and erase
        # the ranking signal this seam exists to carry.
        vals = {
            n: float(scores[rows[n]])
            for n in names
            if rows.get(n) is not None and mask[rows[n]]
        }
        top = max(vals.values(), default=0.0)
        scale = 10.0 / top if top > 0 else 0.0
        out = []
        for n in names:
            val = vals.get(n, 0.0) * scale
            # integer floor like the Go reduce (score*MaxPriority/maxCount
            # in int64 arithmetic), so near-ties stay distinguishable
            out.append({"host": n, "score": int(max(0.0, min(10.0, val)))})
        return out


def why_payload(sched, path: str):
    """The ``/debug/why`` body (schedulability explainer surface,
    obs/explain.py): ``?pod=<ns/name or name>`` returns that pod's
    latest explanation — per-predicate node exclusion counts, scheduling
    attempts, queue residency, and the top one-bit-away relaxations;
    without an argument, the latest cycle's cluster summary. Returns
    ``(status, json-able dict)``."""
    import heapq
    from urllib.parse import parse_qs, urlparse

    q = parse_qs(urlparse(path).query)
    pod = (q.get("pod") or [""])[0]
    why = getattr(sched, "why_pending", None)
    if why is None:
        return 404, {"error": "no explain surface on this scheduler"}
    # the handler runs on the HTTP thread while the scheduling loop
    # mutates why_pending: dict() is a GIL-atomic C-level copy (str
    # keys, no callbacks), so iteration below can't race the scheduler
    why = dict(why)
    if pod:
        pe = why.get(pod)
        if pe is None and "/" not in pod:
            # bare names resolve like kubectl's default namespace, then
            # by suffix across namespaces
            pe = why.get(f"default/{pod}")
            if pe is None:
                hits = [k for k in why if k.endswith(f"/{pod}")]
                pe = why[hits[0]] if len(hits) == 1 else None
        if pe is None:
            return 404, {
                "error": f"no pending-pod explanation for {pod!r}",
                "known": heapq.nsmallest(50, why),
            }
        return 200, pe.to_json()
    rep = getattr(sched, "last_explain", None)
    # cap the key listing like the 404 path — at bench scale the
    # residual queue is tens of thousands of pods and a poll must not
    # serialize a multi-MB document; pending_total carries the real size
    if rep is None:
        return 200, {"unschedulable": 0, "pending_total": len(why),
                     "pending_known": heapq.nsmallest(50, why),
                     "note": "no unschedulable pods analyzed yet"}
    from kubernetes_tpu.obs.explain import summarize_breakdown

    doc = rep.to_json()
    # same 50-key cap as pending_known: "unschedulable" carries the real
    # per-cycle count, so the sample is informational only
    doc["pods"] = heapq.nsmallest(50, rep.pods)
    doc["summary"] = summarize_breakdown(rep.reason_pods, rep.n_nodes)
    doc["pending_total"] = len(why)
    doc["pending_known"] = heapq.nsmallest(50, why)
    return 200, doc


def journeys_payload(sched, path: str):
    """The ``/debug/journeys`` body (per-pod journey tracer,
    obs/journey.py): ``?pod=<ns/name or name>`` returns that pod's full
    timeline — phase decomposition, attempt rows, raw events; without
    an argument, the slowest-K completed table plus the oldest
    in-flight journeys. Returns ``(status, json-able dict)``."""
    import heapq
    from urllib.parse import parse_qs, urlparse

    q = parse_qs(urlparse(path).query)
    pod = (q.get("pod") or [""])[0]
    obs = getattr(sched, "obs", None)
    journeys = getattr(obs, "journeys", None)
    if journeys is None or not getattr(journeys, "enabled", False):
        return 404, {"error": "no journey tracker on this scheduler"}
    if not pod:
        return 200, journeys.snapshot()
    doc = journeys.timeline(pod)
    if doc is None and "/" not in pod:
        # bare names resolve like /debug/why: default namespace first,
        # then a unique suffix match across namespaces
        doc = journeys.timeline(f"default/{pod}")
        if doc is None:
            known = journeys.keys()
            hits = [k for k in known if k.endswith(f"/{pod}")]
            doc = journeys.timeline(hits[0]) if len(hits) == 1 else None
    if doc is None:
        return 404, {
            "error": f"no journey retained for {pod!r}",
            "known": heapq.nsmallest(50, journeys.keys()),
        }
    return 200, doc


def profile_payload(sched, path: str):
    """The ``/debug/profile`` body: arm an on-demand
    ``jax.profiler`` capture of the next ``?cycles=N`` cycle closes
    (obs/incidents.py — bounded by the incidents config's profile_dir
    and max_profiles). Returns ``(status, json-able dict)``."""
    from urllib.parse import parse_qs, urlparse

    q = parse_qs(urlparse(path).query)
    obs = getattr(sched, "obs", None)
    incidents = getattr(obs, "incidents", None)
    if incidents is None:
        return 404, {"error": "no incident recorder on this scheduler"}
    try:
        cycles = int((q.get("cycles") or ["8"])[0])
    except ValueError:
        return 400, {"error": "cycles must be an integer"}
    started = incidents.arm_profile(cycles, tag="debug")
    return (200 if started else 409), {
        "started": started,
        "cycles": cycles,
        "profile_dir": str(getattr(incidents.config, "profile_dir", "")),
        "profiles_taken": incidents.profiles_taken,
        "note": ("" if started else
                 "not started: profiling disabled (empty profile_dir), "
                 "a capture is already active, or max_profiles reached"),
    }


def serve_scheduler(
    scheduler,
    host: str = "127.0.0.1",
    port: int = 0,
    extender: Optional[ExtenderServer] = None,
    fairness=None,
) -> ThreadingHTTPServer:
    """Start the healthz/metrics (+ optional extender) server on a daemon
    thread; returns the server (``.server_address`` has the bound port,
    ``.shutdown()`` stops it).

    ``fairness`` (serving.fairness.FlowController) installs APF-style
    load shedding ahead of the handlers: extender POSTs ride the
    mutating flow and are shed with 429 + Retry-After on overload, while
    /healthz, /metrics and the /debug endpoints classify exempt — the
    probes that diagnose an overload must survive it."""

    sched = scheduler

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _respond(self, code: int, body: bytes, ctype: str,
                     headers=None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _admit(self, verb: str):
            """Flow seat or None after a 429 was sent ("" = no filter)."""
            if fairness is None:
                return ""
            from kubernetes_tpu.serving.fairness import RequestRejected

            try:
                return fairness.acquire(fairness.classify(verb, self.path))
            except RequestRejected as e:
                body = json.dumps({"error": str(e)}).encode()
                self._respond(
                    429, body, "application/json",
                    headers={"Retry-After":
                             str(max(int(round(e.retry_after_s)), 1))})
                return None

        def do_GET(self):
            seat = self._admit("GET")
            if seat is None:
                return
            try:
                self._do_get()
            finally:
                if seat and fairness is not None:
                    fairness.release(seat)

        def _do_get(self):
            if self.path == "/healthz":
                self._respond(200, b"ok", "text/plain")
            elif self.path == "/metrics":
                body = sched.metrics.registry.expose().encode()
                self._respond(200, body, "text/plain; version=0.0.4")
            elif self.path == "/version":
                from kubernetes_tpu import version_info

                self._respond(200, json.dumps(version_info()).encode(),
                              "application/json")
            elif self.path == "/debug/traces":
                # Chrome trace-event document over the retained cycle
                # traces — save and open in chrome://tracing / Perfetto
                obs = getattr(sched, "obs", None)
                if obs is None:
                    self._respond(404, b"no observability layer",
                                  "text/plain")
                else:
                    self._respond(200, obs.export_chrome_trace().encode(),
                                  "application/json")
            elif self.path == "/debug/flightrecorder":
                obs = getattr(sched, "obs", None)
                if obs is None:
                    self._respond(404, b"no observability layer",
                                  "text/plain")
                else:
                    self._respond(
                        200, json.dumps(obs.debug_payload()).encode(),
                        "application/json")
            elif self.path == "/debug/ledger":
                # the perf ledger (obs/ledger.py): per-cycle measured
                # phase distributions, measured-vs-modeled efficiency,
                # cost-model anchors, SLO watchdog state. snapshot() is
                # thread-safe like /debug/why — the scheduler thread
                # keeps observing while this handler serializes.
                obs = getattr(sched, "obs", None)
                ledger = getattr(obs, "ledger", None)
                if ledger is None:
                    self._respond(404, b"no perf ledger on this scheduler",
                                  "text/plain")
                else:
                    self._respond(
                        200, json.dumps(ledger.snapshot()).encode(),
                        "application/json")
            elif self.path == "/debug/memory":
                # the device-memory ledger (obs/memledger.py): ranked
                # residents, modeled-vs-measured watermarks, per-bucket
                # compiled peaks, preflight verdicts, and the OOM
                # forensic ring. snapshot() is thread-safe like
                # /debug/ledger.
                obs = getattr(sched, "obs", None)
                memledger = getattr(obs, "memledger", None)
                if memledger is None:
                    self._respond(404,
                                  b"no memory ledger on this scheduler",
                                  "text/plain")
                else:
                    self._respond(
                        200, json.dumps(memledger.snapshot()).encode(),
                        "application/json")
            elif self.path == "/debug/soak":
                # the day-in-the-life soak engine (soak.py), attached
                # via SoakEngine.attach(sched): current phase, per-
                # phase verdicts so far, live sentinel snapshot.
                # status() is thread-safe like /debug/ledger — the
                # soak thread keeps phasing while this serializes.
                soak = getattr(sched, "soak", None)
                if soak is None:
                    self._respond(404, b"no soak engine attached",
                                  "text/plain")
                else:
                    self._respond(
                        200, json.dumps(soak.status()).encode(),
                        "application/json")
            elif self.path.split("?", 1)[0] == "/debug/why":
                code, doc = why_payload(sched, self.path)
                self._respond(code, json.dumps(doc).encode(),
                              "application/json")
            elif self.path.split("?", 1)[0] == "/debug/journeys":
                # per-pod journey tracer (obs/journey.py): bare = the
                # slowest-K completed table + oldest in-flight rows;
                # ?pod= = one pod's full phase-decomposed timeline
                code, doc = journeys_payload(sched, self.path)
                self._respond(code, json.dumps(doc).encode(),
                              "application/json")
            elif self.path == "/debug/incidents":
                # incident autopsies (obs/incidents.py): the bounded
                # ring of correlated trigger bundles. snapshot() is
                # thread-safe like /debug/ledger.
                obs = getattr(sched, "obs", None)
                incidents = getattr(obs, "incidents", None)
                if incidents is None:
                    self._respond(
                        404, b"no incident recorder on this scheduler",
                        "text/plain")
                else:
                    self._respond(
                        200, json.dumps(incidents.snapshot()).encode(),
                        "application/json")
            elif self.path.split("?", 1)[0] == "/debug/profile":
                # on-demand jax.profiler capture of the next N cycles
                # (gated by observability.incidents.profileDir)
                code, doc = profile_payload(sched, self.path)
                self._respond(code, json.dumps(doc).encode(),
                              "application/json")
            else:
                self._respond(404, b"not found", "text/plain")

        def do_POST(self):
            seat = self._admit("POST")
            if seat is None:
                return
            try:
                if extender is None:
                    self._respond(404, b"no extender", "text/plain")
                    return
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n).decode() or "{}")
                verb = self.path.strip("/").split("/")[-1]
                result = extender.handle(verb, payload)
                self._respond(200, json.dumps(result).encode(),
                              "application/json")
            finally:
                if seat and fairness is not None:
                    fairness.release(seat)

    srv = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv
