"""Host-side volume state + per-pod volume resolution.

The reference spreads volume feasibility over five predicates
(``pkg/scheduler/algorithm/predicates/predicates.go``):

- NoDiskConflict (:275) — inline GCE-PD/EBS/RBD/ISCSI volumes conflicting
  with volumes of pods already on the node,
- MaxPDVolumeCountChecker (:404) — unique EBS/GCE-PD/AzureDisk/Cinder
  volumes vs a per-node attach limit,
- CSIMaxVolumeLimitChecker (csi_volume_predicate.go:54) — per-CSI-driver
  counts vs ``attachable-volumes-csi-<driver>`` allocatable,
- VolumeZoneChecker (:632) — bound PVs' failure-domain labels must match
  the node's,
- VolumeBindingChecker (:1666) — bound PVCs' PV node affinity satisfied;
  unbound delayed-binding PVCs matchable to an available compatible PV (or
  dynamically provisionable).

Here all five resolve host-side into token sets / constraint rows (this
module) that the fused device kernel evaluates as masked matmuls and
segment reductions over the (pods x nodes) grid
(``kubernetes_tpu.ops.predicates``).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import (
    BINDING_WAIT_FOR_FIRST_CONSUMER,
    VOL_AWS_EBS,
    VOL_AZURE_DISK,
    VOL_CINDER,
    VOL_CSI,
    VOL_GCE_PD,
    VOL_ISCSI,
    VOL_RBD,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    StorageClass,
)

# ---------------------------------------------------------------------------
# Attach-limit constants — pkg/volume/util/attach_limit.go:28-51 and
# predicates.go DefaultMaxGCEPDVolumes/DefaultMaxAzureDiskVolumes.
# ---------------------------------------------------------------------------

DEFAULT_MAX_EBS_VOLUMES = 39
DEFAULT_MAX_EBS_NITRO_VOLUMES = 25
DEFAULT_MAX_GCE_PD_VOLUMES = 16
DEFAULT_MAX_AZURE_DISK_VOLUMES = 16
DEFAULT_MAX_CINDER_VOLUMES = 256

EBS_NITRO_RE = re.compile(r"^[cmr]5.*|t3|z1d")
LABEL_INSTANCE_TYPE = "beta.kubernetes.io/instance-type"

#: the four in-tree count-checked volume kinds, in fixed column order
PD_FILTER_KINDS = (VOL_AWS_EBS, VOL_GCE_PD, VOL_AZURE_DISK, VOL_CINDER)
PD_FILTER_INDEX = {k: i for i, k in enumerate(PD_FILTER_KINDS)}
N_PD_FILTERS = len(PD_FILTER_KINDS)

#: allocatable keys overriding the defaults (AttachVolumeLimit feature)
PD_LIMIT_KEYS = (
    "attachable-volumes-aws-ebs",
    "attachable-volumes-gce-pd",
    "attachable-volumes-azure-disk",
    "attachable-volumes-cinder",
)
CSI_LIMIT_PREFIX = "attachable-volumes-csi-"

#: conflict kinds; value = read-only mounts escape the conflict
#: (isVolumeConflict, predicates.go:216: GCE/ISCSI/RBD yes, EBS no)
CONFLICT_RO_ESCAPE = {
    VOL_GCE_PD: True,
    VOL_AWS_EBS: False,
    VOL_ISCSI: True,
    VOL_RBD: True,
}

LABEL_ZONE = "failure-domain.beta.kubernetes.io/zone"
LABEL_REGION = "failure-domain.beta.kubernetes.io/region"


def node_pd_limits(node: Node) -> List[float]:
    """Per-node attach limits for the four in-tree kinds
    (getMaxVolumeFunc predicates.go:354 + allocatable override :505-510)."""
    out: List[float] = []
    itype = node.labels.get(LABEL_INSTANCE_TYPE, "")
    for i, kind in enumerate(PD_FILTER_KINDS):
        if kind == VOL_AWS_EBS:
            dflt = (
                DEFAULT_MAX_EBS_NITRO_VOLUMES
                if EBS_NITRO_RE.match(itype)
                else DEFAULT_MAX_EBS_VOLUMES
            )
        elif kind == VOL_GCE_PD:
            dflt = DEFAULT_MAX_GCE_PD_VOLUMES
        elif kind == VOL_AZURE_DISK:
            dflt = DEFAULT_MAX_AZURE_DISK_VOLUMES
        else:
            dflt = DEFAULT_MAX_CINDER_VOLUMES
        out.append(float(node.allocatable.scalars.get(PD_LIMIT_KEYS[i], dflt)))
    return out


def node_has_zone_label(node: Node) -> bool:
    """VolumeZoneChecker fast path (predicates.go:644-658): a node with
    neither failure-domain label passes every zone constraint."""
    return LABEL_ZONE in node.labels or LABEL_REGION in node.labels


def label_zones_to_set(value: str) -> Tuple[str, ...]:
    """cloud-provider volumehelpers.LabelZonesToSet: '__'-delimited list."""
    return tuple(z for z in value.split("__") if z)


def _match_requirement(labels: Dict[str, str], req) -> bool:
    """v1helper.MatchNodeSelectorTerms requirement evaluation."""
    val = labels.get(req.key)
    op = req.operator
    if op == "In":
        return val is not None and val in req.values
    if op == "NotIn":
        return val is None or val not in req.values
    if op == "Exists":
        return req.key in labels
    if op == "DoesNotExist":
        return req.key not in labels
    if op in ("Gt", "Lt"):
        try:
            lhs = int(val) if val is not None else None
            rhs = int(req.values[0])
        except (TypeError, ValueError):
            return False
        if lhs is None:
            return False
        return lhs > rhs if op == "Gt" else lhs < rhs
    return False


def match_node_selector_terms(labels: Dict[str, str], terms) -> bool:
    """ORed NodeSelectorTerms, each ANDing its requirements — how a PV's
    node affinity is checked against a node (VolumeBindingChecker,
    predicates.go:1666 → volumeutil.CheckNodeAffinity). A term with no
    match_expressions matches NOTHING (apimachinery nodeSelectorTerm
    semantics — same rule the seqref oracle's _term_matches documents;
    deliberately re-implemented here because seqref stays test-only)."""
    return any(
        bool(term.match_expressions)
        and all(_match_requirement(labels, r) for r in term.match_expressions)
        for term in terms
    )


@dataclass
class VolumeState:
    """The PVC/PV/StorageClass listers the volume predicates consult —
    the analog of the informer-fed PersistentVolume{,Claim}Info /
    StorageClassInfo caches (predicates.go:127-205)."""

    pvcs: Dict[Tuple[str, str], PersistentVolumeClaim] = field(default_factory=dict)
    pvs: Dict[str, PersistentVolume] = field(default_factory=dict)
    classes: Dict[str, StorageClass] = field(default_factory=dict)
    #: pv name -> "ns/name" of the claim the scheduler has ASSUMED onto it
    #: (the binder's pvCache assume overlay): reserved but not yet written
    assumed_claims: Dict[str, str] = field(default_factory=dict)

    @staticmethod
    def build(
        pvcs: Sequence[PersistentVolumeClaim] = (),
        pvs: Sequence[PersistentVolume] = (),
        classes: Sequence[StorageClass] = (),
    ) -> "VolumeState":
        return VolumeState(
            pvcs={(c.namespace, c.name): c for c in pvcs},
            pvs={v.name: v for v in pvs},
            classes={c.name: c for c in classes},
        )

    def pvc(self, namespace: str, name: str) -> Optional[PersistentVolumeClaim]:
        return self.pvcs.get((namespace, name))

    def pv(self, name: str) -> Optional[PersistentVolume]:
        return self.pvs.get(name)

    def storage_class(self, name: str) -> Optional[StorageClass]:
        return self.classes.get(name)

    def available_pvs(self, storage_class: str) -> List[PersistentVolume]:
        """Candidate PVs for an unbound delayed-binding claim: unclaimed and
        of the same storage class (the shape-level model of the binder's
        findMatchingVolumes; capacity/access-mode matching is out of scope
        for scheduling parity)."""
        return [
            pv
            for pv in self.pvs.values()
            if not pv.claim_ref
            and not getattr(pv, "deletion_timestamp", 0.0)
            and pv.name not in self.assumed_claims
            and pv.storage_class == storage_class
        ]


@dataclass
class ResolvedVolumes:
    """Everything the kernels need to know about one pod's volumes."""

    #: (kind, handle, read_only) for inline conflict-checked volumes
    conflict: List[Tuple[str, str, bool]] = field(default_factory=list)
    #: (filter_idx, token) unique count-checked volumes; ``token`` is
    #: "h:<handle>" for resolved volumes and "pvc:<ns>/<name>" for
    #: missing/unbound claims (counted against EVERY filter, matching the
    #: per-checker random-prefix pseudo-ids, predicates.go:414)
    pd: List[Tuple[int, str]] = field(default_factory=list)
    #: (driver, handle) CSI volumes (bound PVC -> CSI PV only)
    csi: List[Tuple[str, str]] = field(default_factory=list)
    #: zone rows: (label_key, allowed_values) — node must carry one of the
    #: allowed (key, value) labels unless it has no zone labels at all
    zone_rows: List[Tuple[str, Tuple[str, ...]]] = field(default_factory=list)
    #: bound-PV node-affinity requirements: each entry = one PV's ORed
    #: NodeSelectorTerm tuple (AND across entries)
    bound_affinity: List[Tuple] = field(default_factory=list)
    #: unbound delayed-binding clauses: each entry = list of candidate PVs'
    #: node-affinity term tuples (OR within, AND across entries); an entry
    #: may be empty = no candidate at all -> unbound-unsatisfiable
    unbound_clauses: List[List[Tuple]] = field(default_factory=list)
    #: unresolvable volume state -> scheduling error, pod fails everywhere
    #: (predicate errors abort the pod's cycle in the reference)
    error: bool = False


def resolve_pod_volumes(pod: Pod, state: VolumeState) -> ResolvedVolumes:
    """Resolve a pod's volumes through PVC -> PV with the reference's exact
    missing/unbound fallbacks (see per-field docs above)."""
    out = ResolvedVolumes()
    for v in pod.volumes:
        if not v.pvc:
            if v.kind in CONFLICT_RO_ESCAPE:
                out.conflict.append((v.kind, v.handle, v.read_only))
            fi = PD_FILTER_INDEX.get(v.kind)
            if fi is not None:
                out.pd.append((fi, "h:" + v.handle))
            continue
        pvc = state.pvc(pod.namespace, v.pvc)
        if pvc is None:
            # missing claim: scheduling error (podPassesBasicChecks /
            # CSI + zone checkers error out); still counted per checker
            out.error = True
            tok = f"pvc:{pod.namespace}/{v.pvc}"
            out.pd.extend((i, tok) for i in range(N_PD_FILTERS))
            continue
        if not pvc.volume_name:
            # unbound claim
            tok = f"pvc:{pod.namespace}/{v.pvc}"
            out.pd.extend((i, tok) for i in range(N_PD_FILTERS))
            sc = state.storage_class(pvc.storage_class) if pvc.storage_class else None
            if sc is not None and sc.binding_mode == BINDING_WAIT_FOR_FIRST_CONSUMER:
                # delayed binding: satisfiable via an available compatible
                # PV's node affinity, or dynamic provisioning
                if sc.provisionable():
                    continue  # clause trivially satisfiable -> omit
                cands = [pv.node_affinity for pv in state.available_pvs(pvc.storage_class)]
                out.unbound_clauses.append([tuple(t) for t in cands])
            else:
                # unbound immediate claim: "pod has unbound immediate
                # PersistentVolumeClaims" scheduling error
                out.error = True
            continue
        pv = state.pv(pvc.volume_name)
        if pv is None:
            # bound claim whose PV vanished: error (VolumeZone/binder);
            # counted per checker like an unknown volume
            out.error = True
            tok = f"pvc:{pod.namespace}/{v.pvc}"
            out.pd.extend((i, tok) for i in range(N_PD_FILTERS))
            continue
        for akind, a, b in attachable_tokens(pv):
            if akind == "pd":
                out.pd.append((a, b))
            else:
                out.csi.append((a, b))
        for k in (LABEL_ZONE, LABEL_REGION):
            val = pv.labels.get(k)
            if val:
                allowed = label_zones_to_set(val)
                if allowed:
                    out.zone_rows.append((k, allowed))
        if pv.node_affinity:
            out.bound_affinity.append(tuple(pv.node_affinity))
    # dedup count tokens (filterVolumes collects into a set)
    out.pd = sorted(set(out.pd))
    out.csi = sorted(set(out.csi))
    return out


def attachable_tokens(pv) -> list:
    """The ONE PV -> attach-token classification (shared by
    resolve_pod_volumes' bound-claim branch, the snapshot packer's
    residue columns, and the attach-detach controller's desired-state
    scan — three consumers that must never skew): a list of
    ``("pd", filter_index, "h:"+handle)`` and/or
    ``("csi", driver, handle)`` entries; empty = not attachable."""
    out = []
    fi = PD_FILTER_INDEX.get(pv.kind)
    if fi is not None:
        out.append(("pd", fi, "h:" + pv.handle))
    if pv.kind == VOL_CSI and pv.driver:
        out.append(("csi", pv.driver, pv.handle))
    return out


class VolumeBinder:
    """The delayed-binding PVC lifecycle inside the scheduling flow — the
    analog of ``pkg/scheduler/volumebinder/volume_binder.go:30`` wrapping
    the volume scheduling library:

    - :meth:`assume_pod_volumes` (scheduler.go:523 assumeVolumes →
      AssumePodVolumes): at assume time, pick ONE available compatible PV
      per unbound WaitForFirstConsumer claim for the chosen node and
      reserve it in the assumed overlay, so no concurrent claimant —
      in-batch or next-cycle — can take it;
    - :meth:`bind_pod_volumes` (scheduler.go:550 bindVolumes →
      BindPodVolumes): commit the reserved claims (PV.claimRef +
      PVC.volumeName) through ``writer`` — an API write in a real
      deployment, injectable so tests/sims can make it conflict;
    - :meth:`forget_pod_volumes`: roll back reservations whenever the pod's
      assumption is forgotten (Permit reject/timeout, bind failure,
      deletion while parked).
    """

    def __init__(self, packer, writer=None) -> None:
        self.packer = packer
        self.writer = writer or self._local_write
        #: pod key -> [(pvc, pv)] reserved picks awaiting bind
        self.assumed: Dict[str, List[Tuple[PersistentVolumeClaim, PersistentVolume]]] = {}

    @property
    def state(self) -> VolumeState:
        return self.packer.vol_state

    def _local_write(self, pvc: PersistentVolumeClaim, pv: PersistentVolume) -> None:
        """Default commit: mutate the local listers (the sim hub's truth)."""
        pv.claim_ref = f"{pvc.namespace}/{pvc.name}"
        pvc.volume_name = pv.name

    def assume_pod_volumes(self, pod: Pod, node: Node) -> Tuple[bool, str]:
        """Returns (ok, message). ok=True with no reservations made is the
        reference's allBound=true fast path."""
        if not any(v.pvc for v in pod.volumes):
            return True, ""
        if pod.key() in self.assumed:
            # reservation already held (e.g. a Permit-parked pod popped
            # again via a duplicate queue entry) — re-assuming would
            # overwrite and leak the prior picks
            return True, ""
        st = self.state
        picks: List[Tuple[PersistentVolumeClaim, PersistentVolume]] = []

        def rollback() -> None:
            for _, pv in picks:
                st.assumed_claims.pop(pv.name, None)

        for v in pod.volumes:
            if not v.pvc:
                continue
            pvc = st.pvc(pod.namespace, v.pvc)
            if pvc is None:
                rollback()
                return False, f'persistentvolumeclaim "{v.pvc}" not found'
            if pvc.volume_name:
                continue  # already bound
            sc = st.storage_class(pvc.storage_class) if pvc.storage_class else None
            if sc is None or sc.binding_mode != BINDING_WAIT_FOR_FIRST_CONSUMER:
                rollback()
                return False, f'pod has unbound immediate PersistentVolumeClaims ("{v.pvc}")'
            if sc.provisionable():
                continue  # dynamic provisioning satisfies it post-bind
            cand = None
            for pv in st.available_pvs(pvc.storage_class):
                if not pv.node_affinity or match_node_selector_terms(
                    node.labels, pv.node_affinity
                ):
                    cand = pv
                    break
            if cand is None:
                rollback()
                return False, (
                    f'no matching PersistentVolume for claim "{v.pvc}" on '
                    f'node "{node.name}"'
                )
            st.assumed_claims[cand.name] = f"{pod.namespace}/{pvc.name}"
            picks.append((pvc, cand))
        if picks:
            self.assumed[pod.key()] = picks
            self.packer.refresh_volume_resolutions()
        return True, ""

    def bind_pod_volumes(self, pod: Pod) -> bool:
        """Commit reserved claims. Returns True if any write happened.
        A writer failure releases the remaining reservations and re-raises
        (the pod is then Forgotten + requeued; already-committed claims
        stay bound, exactly like real API writes that landed — the next
        attempt sees those PVCs bound and only assumes the rest)."""
        picks = self.assumed.pop(pod.key(), None)
        if not picks:
            return False
        st = self.state
        try:
            for pvc, pv in picks:
                self.writer(pvc, pv)
                st.assumed_claims.pop(pv.name, None)
        except Exception:
            for pvc, pv in picks:
                if pvc.volume_name != pv.name:  # not committed
                    st.assumed_claims.pop(pv.name, None)
            self.packer.refresh_volume_resolutions()
            raise
        self.packer.refresh_volume_resolutions()
        return True

    def forget_pod_volumes(self, pod_key: str) -> None:
        picks = self.assumed.pop(pod_key, None)
        if picks:
            for _, pv in picks:
                self.state.assumed_claims.pop(pv.name, None)
            self.packer.refresh_volume_resolutions()
