"""Day-in-the-life soak: the phase engine and the leak sentinels.

Every chaos/bench arm so far is a minute-scale, single-purpose cell;
production is ONE process surviving all of it in sequence for hours.
This module is the harness for that artifact (ROADMAP item 3): a
scripted sequence of :class:`SoakPhase` s driven over one composed
``ServingRuntime`` — mixed traffic, cadence re-packing, preemption
cascades, leader kills, shard loss, network faults — separated by
CLEAN phases where the cluster must return to quiescence, plus the
instrumentation no single-purpose cell carries:

- :class:`SoakSentinels` — a sampler that snapshots, per phase
  boundary and on a fixed cadence, every unbounded-unless-maintained
  structure in the process (``Scheduler.state_sizes()``, flight
  recorder / trace-ring occupancy, jaxtel signature LRUs, reflector
  dedupe floors + tombstones, process RSS) and per-gauge freshness —
  and renders a growth verdict over the CLEAN-phase boundaries: state
  that ratchets up across windows where traffic returned to zero is a
  leak, whatever its absolute size.
- :class:`SoakEngine` — phase sequencing with arm/disarm hooks for
  the existing chaos harnesses (chaos.py fault windows open at phase
  entry and close at exit via ``injector.rules.clear()``), per-phase
  counter deltas (SLO burns, auditor violations, double binds,
  retraces), and the clean-phase criteria: on every phase of kind
  ``"clean"`` the configured counters must not move at all.

The engine attaches itself to the scheduler (``sched.soak``) so
``/debug/soak`` (server.py) can serve live progress the same
duck-typed way ``/debug/ledger`` serves the perf ledger.

Nothing here imports jax: the soak is host-side orchestration; the
devices stay behind the scheduler's existing seams.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple


def read_rss_kb() -> int:
    """Current resident set size in kB (/proc/self/status VmRSS);
    0 where /proc is unavailable — the sentinel then watches a flat
    zero line, never crashes the soak."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except (OSError, ValueError, IndexError):
        pass
    return 0


#: growth allowed across the whole clean-boundary window before a
#: monotonically-increasing series reads as a leak. Keyed by exact
#: sentinel name or by longest matching prefix; sizes without a row
#: get 0 (pod-keyed side state must RETURN to baseline when traffic
#: does). The non-zero rows are the legitimately-plateauing series:
#: vocabulary interners grow until the label/image vocabulary is
#: fully seen, signature LRUs until the shape grid is fully warmed,
#: the rings until they first fill, RSS until allocator pools settle.
DEFAULT_TOLERANCE: Dict[str, float] = {
    "rss_kb": 65536,               # 64 MB of allocator/arena settling
    "sched.interned_items": 256,
    "sched.universe_matcher_memo": 256,
    "sched.universe_owner_sets_memo": 256,
    "sched.packer_pod_table_memo": 1024,   # LRU-capped upstream
    "sched.packer_vol_table_memo": 1024,
    "sched.breakers": 8,           # lazily minted per target, bounded
    "sched.explain_reasons_seen": 32,      # label vocabulary
    # device-side flags/counters state_sizes exports for the memory
    # ledger (mirrored mem.* rows below carry the rationale)
    "sched.dev_node_table": 1,     # 0/1 flag: resident by design
    "sched.dev_score_summary": 1,  # 0/1 flag: resident by design
    "sched.mem_residents": 8,
    "sched.mem_census_arrays": 4096,
    "jax.signatures": 512,         # per-site LRU-capped upstream
    "obs.recorder_len": 4096,      # deque maxlen-capped upstream
    "obs.trace_ring_len": 4096,
    "reflector.": 8192,            # tombstone-LRU-capped upstream
    # device-memory ledger: the census plateaus once JAX's constant /
    # executable pools are fully warmed (shape grid, like jax.
    # signatures); modeled bytes plateau at the largest warmed
    # bucket's operand tables (the resident node table + score plane
    # persist across cycles BY DESIGN — that's what resident caching
    # is). mem.residents is a fixed name set (~4 structures): growth
    # past it means a drop edge leaked a registration
    "mem.residents": 8,
    "mem.census_arrays": 4096,
    "mem.modeled_bytes": 1 << 24,  # 16 MB: bucket-shape settling
    "mem.oom_records": 16,         # ring maxlen-capped upstream
    # pod-journey tracer: pending journeys are pod-keyed side state
    # and must RETURN to baseline when traffic does (tolerance 0, made
    # explicit); the completed tiers are capped upstream (slowest by
    # slow_k, sampled by its deque maxlen) and legitimately plateau as
    # the tail fills in. Mirrored sched.* rows: state_sizes() exports
    # the same numbers under its own namespace.
    "journey.pending": 0,
    "journey.slowest": 64,
    "journey.sampled": 64,
    "sched.journey_pending": 0,
    "sched.journey_slowest": 64,
    "sched.journey_sampled": 64,
    # incident ring: occupancy is deque maxlen-capped upstream and
    # plateaus once it first fills; NEW bundles during a clean window
    # are caught by the clean_zero `incidents` counter, not by ring
    # occupancy (an at-capacity ring stays the same length)
    "incident.ring": 64,
    "sched.incident_ring": 64,
}


def _tolerance(key: str, table: Dict[str, float]) -> float:
    if key in table:
        return table[key]
    best, best_len = 0.0, -1
    for prefix, tol in table.items():
        if prefix.endswith(".") and key.startswith(prefix) \
                and len(prefix) > best_len:
            best, best_len = tol, len(prefix)
    return best if best_len >= 0 else 0.0


class SoakSentinels:
    """The leak sentinel layer. ``sample()`` is cheap (dict-length
    reads + one /proc line) and thread-safe; the soak calls it from
    the serving maintenance hook (under the ingest lock) and at phase
    boundaries. Growth verdicts read ONLY clean-phase boundary
    samples: traffic phases may grow state legitimately; a clean
    window that fails to return to baseline may not.

    ``sched``: anything with ``state_sizes()`` (Scheduler).
    ``reflectors``: sim.Reflector instances (dedupe floor/tombstones).
    ``registry``: a metrics.Registry — every Gauge in it is
    fingerprinted per sample for the freshness ages.
    ``fresh_gauges``: gauge names that MUST change at least once
    within any traffic phase (checked by the engine at phase end)."""

    def __init__(self, sched=None, reflectors: Sequence = (),
                 registry=None, fresh_gauges: Sequence[str] = (),
                 rss_reader: Callable[[], int] = read_rss_kb,
                 tolerance: Optional[Dict[str, float]] = None) -> None:
        self.sched = sched
        self.reflectors = list(reflectors)
        self.registry = registry
        self.fresh_gauges = list(fresh_gauges)
        self.rss_reader = rss_reader
        self.tolerance = dict(DEFAULT_TOLERANCE)
        if tolerance:
            self.tolerance.update(tolerance)
        self.samples: List[dict] = []
        self._lock = threading.Lock()
        #: gauge name -> fingerprint of its full label/value table
        self._gauge_fp: Dict[str, int] = {}
        #: gauge name -> sample index of the last fingerprint change
        self._gauge_changed_at: Dict[str, int] = {}

    # -- collection ---------------------------------------------------------

    def collect(self) -> Dict[str, float]:
        """One flat snapshot of every watched size. Key namespaces:
        ``sched.*`` (state_sizes), ``obs.*`` (rings), ``jax.*``
        (signature LRUs), ``lock.*`` (runtime lock-sanitizer finding
        counts, when armed), ``reflector.N.*`` (dedupe floors),
        ``rss_kb``."""
        out: Dict[str, float] = {"rss_kb": float(self.rss_reader())}
        s = self.sched
        if s is not None:
            sizes = getattr(s, "state_sizes", None)
            if sizes is not None:
                for k, v in sizes().items():
                    out[f"sched.{k}"] = float(v)
            obs = getattr(s, "obs", None)
            if obs is not None:
                rec = getattr(obs, "recorder", None)
                if rec is not None:
                    # ring OCCUPANCY only — `recorded` is a cumulative
                    # counter and would read as a perpetual "leak"
                    out["obs.recorder_len"] = float(len(rec))
                traces = getattr(obs, "traces", None)
                if traces is not None:
                    out["obs.trace_ring_len"] = float(len(traces))
                jx = getattr(obs, "jax", None)
                sig = getattr(jx, "signature_count", None)
                if sig is not None:
                    out["jax.signatures"] = float(sig())
                memledger = getattr(obs, "memledger", None)
                if memledger is not None and getattr(
                        memledger, "enabled", False):
                    # device-memory sentinels: a clean window must
                    # return modeled resident bytes (and the census)
                    # to baseline — a resident surviving its drop edge
                    # is a device leak the host dicts can't see
                    out["mem.residents"] = float(
                        memledger.resident_count())
                    out["mem.modeled_bytes"] = float(
                        memledger.resident_bytes())
                    out["mem.census_arrays"] = float(
                        memledger.census_count())
                    out["mem.oom_records"] = float(
                        len(memledger.oom_records()))
                journeys = getattr(obs, "journeys", None)
                if journeys is not None and getattr(
                        journeys, "enabled", False):
                    # per-pod journey retention: pending must drain
                    # with the queues; the completed tiers are capped
                    # upstream (slow_k / deque maxlen)
                    jsz = journeys.sizes()
                    out["journey.pending"] = float(
                        jsz.get("journey_pending", 0))
                    out["journey.slowest"] = float(
                        jsz.get("journey_slowest", 0))
                    out["journey.sampled"] = float(
                        jsz.get("journey_sampled", 0))
                incidents = getattr(obs, "incidents", None)
                if incidents is not None and getattr(
                        incidents, "enabled", False):
                    # ring OCCUPANCY only — `total` is a cumulative
                    # counter and belongs to the clean_zero contract
                    out["incident.ring"] = float(len(incidents))
            san = getattr(s, "lock_sanitizer", None)
            if san is not None:
                # monotonic finding counts: the clean-window contract
                # pins order_cycles and guard_violations at zero delta —
                # a deadlock-shaped acquisition order found mid-soak is
                # a bug whatever the RSS curve says
                counts = san.counts()
                out["lock.order_cycles"] = float(
                    counts.get("order-cycle", 0))
                out["lock.held_too_long"] = float(
                    counts.get("held-too-long", 0))
                out["lock.guard_violations"] = float(
                    counts.get("guard-violation", 0))
                out["lock.total"] = float(san.total_findings())
        for i, r in enumerate(self.reflectors):
            out[f"reflector.{i}.obj_rev"] = float(
                len(getattr(r, "_obj_rev", ())))
            out[f"reflector.{i}.tombstones"] = float(
                len(getattr(r, "_gone_rev", ())))
        return out

    def _fingerprint_gauges(self, idx: int) -> None:
        reg = self.registry
        if reg is None:
            return
        from kubernetes_tpu.metrics import Gauge

        for m in getattr(reg, "_metrics", ()):
            if not isinstance(m, Gauge):
                continue
            # the write counter joins the fingerprint: a gauge that is
            # maintained every cycle but always reads 0 at sample time
            # (queue depth after a drain) must still count as FRESH —
            # freshness means "someone writes this", not "the sampled
            # value moved between two arbitrary snapshots"
            fp = hash((getattr(m, "writes", 0),
                       tuple(sorted(m._values.items()))))
            if self._gauge_fp.get(m.name) != fp:
                self._gauge_fp[m.name] = fp
                self._gauge_changed_at[m.name] = idx

    def sample(self, tag: str = "cadence", phase: Optional[str] = None,
               clean: bool = False, clock: Optional[float] = None) -> dict:
        """Take one snapshot. ``clean=True`` marks it as a clean-phase
        BOUNDARY sample — the points the growth verdict draws through."""
        values = self.collect()
        with self._lock:
            idx = len(self.samples)
            self._fingerprint_gauges(idx)
            row = {"i": idx, "t": clock, "tag": tag, "phase": phase,
                   "clean": bool(clean), "values": values}
            self.samples.append(row)
            return row

    # -- verdicts -----------------------------------------------------------

    def _clean_series(self) -> Dict[str, List[float]]:
        with self._lock:
            rows = [r for r in self.samples if r["clean"]]
        series: Dict[str, List[float]] = {}
        for r in rows:
            for k, v in r["values"].items():
                series.setdefault(k, []).append(v)
        return series

    def growth_report(self) -> Dict[str, dict]:
        """Per-sentinel verdict over the clean-phase boundary samples:
        ``growing`` is True when the series NEVER decreases, strictly
        increases at least twice, and its total rise exceeds the key's
        tolerance — the monotonic-ratchet shape of a leak, as opposed
        to a plateau (bounded cache filling) or a sawtooth (state that
        drains). Needs >= 3 clean samples to judge; fewer yields
        ``growing=False, judged=False``."""
        out: Dict[str, dict] = {}
        for key, vals in self._clean_series().items():
            judged = len(vals) >= 3
            rises = sum(1 for a, b in zip(vals, vals[1:]) if b > a)
            monotone = all(b >= a for a, b in zip(vals, vals[1:]))
            growth = (vals[-1] - vals[0]) if vals else 0.0
            tol = _tolerance(key, self.tolerance)
            out[key] = {
                "first": vals[0] if vals else 0.0,
                "last": vals[-1] if vals else 0.0,
                "growth": growth,
                "tolerance": tol,
                "judged": judged,
                "growing": bool(judged and monotone and rises >= 2
                                and growth > tol),
            }
        return out

    def leaking(self) -> List[str]:
        """Sentinel names whose clean-boundary series reads as a leak."""
        return sorted(k for k, v in self.growth_report().items()
                      if v["growing"])

    def gauge_ages(self) -> Dict[str, int]:
        """Samples since each registered gauge last changed."""
        with self._lock:
            n = len(self.samples)
            return {name: n - 1 - at
                    for name, at in self._gauge_changed_at.items()}

    def stale_since(self, idx: int) -> List[str]:
        """Which ``fresh_gauges`` have NOT changed since sample
        ``idx`` — the engine calls this at the end of each traffic
        phase with the phase's first sample index."""
        with self._lock:
            return sorted(
                name for name in self.fresh_gauges
                if self._gauge_changed_at.get(name, -1) < idx)

    def snapshot(self) -> dict:
        """JSON-shaped live view (/debug/soak)."""
        with self._lock:
            last = self.samples[-1] if self.samples else None
            n = len(self.samples)
        return {"samples": n, "last": last,
                "leaking": self.leaking(),
                "gauge_ages": self.gauge_ages()}


@dataclass
class SoakPhase:
    """One scripted phase. ``kind``:

    - ``"traffic"`` — load flows; sentinels may grow; the freshness
      rule applies (``fresh_gauges`` must move);
    - ``"chaos"`` — traffic plus an armed fault harness;
    - ``"clean"`` — recovery window: the ``clean_zero`` counters must
      not move and the boundary sample joins the growth series.

    ``arm``/``disarm`` bracket the phase (arm fault rules, start
    producers / clear rules, stop producers). ``tick(elapsed_s)`` runs
    every engine step inside the phase — drive fake-clock advances,
    kill leaders on a schedule, etc. ``probe()`` runs at phase end;
    its dict lands in the phase report (p99s, bound counts...)."""

    name: str
    duration_s: float
    kind: str = "traffic"
    arm: Optional[Callable[[], None]] = None
    disarm: Optional[Callable[[], None]] = None
    tick: Optional[Callable[[float], None]] = None
    probe: Optional[Callable[[], dict]] = None


class SoakEngine:
    """Phase sequencing + verdicts over one composed runtime.

    ``counters``: name -> zero-arg reader of a MONOTONIC total
    (watchdog burns, auditor violations, double binds, retraces...);
    read at every phase boundary, reported as per-phase deltas.
    ``clean_zero``: the counter names whose delta must be 0 on every
    clean phase. ``step_s``: engine granularity — ticks and cadence
    samples happen on this grid; ``sleep`` is injectable so the
    fake-clock test compresses hours into no wall time at all."""

    def __init__(self, phases: Sequence[SoakPhase],
                 sentinels: SoakSentinels,
                 counters: Optional[Dict[str, Callable[[], float]]] = None,
                 clean_zero: Sequence[str] = (),
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 step_s: float = 1.0,
                 sample_every_s: float = 10.0,
                 p99_drift_bound: float = 0.5,
                 log: Callable[[str], None] = lambda _m: None) -> None:
        self.phases = list(phases)
        self.sentinels = sentinels
        self.counters = dict(counters or {})
        self.clean_zero = [c for c in clean_zero if c in self.counters]
        self.clock = clock
        self.sleep = sleep
        self.step_s = max(float(step_s), 1e-6)
        self.sample_every_s = max(float(sample_every_s), self.step_s)
        self.p99_drift_bound = float(p99_drift_bound)
        self.log = log
        self.reports: List[dict] = []
        self.current: Optional[str] = None
        self._lock = threading.Lock()

    # -- one phase ----------------------------------------------------------

    def _read_counters(self) -> Dict[str, float]:
        return {name: float(read()) for name, read in self.counters.items()}

    def run_phase(self, ph: SoakPhase) -> dict:
        with self._lock:
            self.current = ph.name
        self.log(f"soak phase {ph.name} ({ph.kind}, {ph.duration_s:g}s)")
        start_sample = self.sentinels.sample(
            tag="phase-start", phase=ph.name, clock=self.clock())
        before = self._read_counters()
        t0 = self.clock()
        if ph.arm is not None:
            ph.arm()
        try:
            next_sample = t0 + self.sample_every_s
            while True:
                elapsed = self.clock() - t0
                if elapsed >= ph.duration_s:
                    break
                if ph.tick is not None:
                    ph.tick(elapsed)
                self.sleep(min(self.step_s, ph.duration_s - elapsed))
                if self.clock() >= next_sample:
                    self.sentinels.sample(
                        tag="cadence", phase=ph.name, clock=self.clock())
                    next_sample = self.clock() + self.sample_every_s
        finally:
            if ph.disarm is not None:
                ph.disarm()
        after = self._read_counters()
        delta = {k: after[k] - before.get(k, 0.0) for k in after}
        # the boundary sample is taken AFTER disarm: a clean phase's
        # point must reflect the recovered steady state, and a chaos
        # phase's point must not carry a still-armed fault window
        self.sentinels.sample(
            tag="phase-end", phase=ph.name, clean=(ph.kind == "clean"),
            clock=self.clock())
        violations: List[str] = []
        if ph.kind == "clean":
            for name in self.clean_zero:
                if delta.get(name, 0.0) != 0.0:
                    violations.append(
                        f"{name} moved by {delta[name]:g} in clean "
                        f"phase {ph.name}")
        stale: List[str] = []
        if ph.kind in ("traffic", "chaos"):
            stale = self.sentinels.stale_since(start_sample["i"])
            for name in stale:
                violations.append(
                    f"gauge {name} never changed during {ph.name}")
        report = {
            "name": ph.name, "kind": ph.kind,
            "duration_s": ph.duration_s,
            "wall_s": round(self.clock() - t0, 3),
            "counters_delta": delta,
            "stale_gauges": stale,
            "violations": violations,
            "ok": not violations,
        }
        if ph.probe is not None:
            report["probe"] = ph.probe()
        self.reports.append(report)
        return report

    # -- the full soak ------------------------------------------------------

    def run(self) -> dict:
        t0 = self.clock()
        totals0 = self._read_counters()
        for ph in self.phases:
            self.run_phase(ph)
        with self._lock:
            self.current = None
        totals = self._read_counters()
        growth = self.sentinels.growth_report()
        leaking = sorted(k for k, v in growth.items() if v["growing"])
        phase_violations = [v for r in self.reports for v in r["violations"]]
        # p99 drift: first vs last traffic-phase probe that reported one
        p99s = [(r["name"], r["probe"]["p99_s"]) for r in self.reports
                if r.get("probe") and "p99_s" in r["probe"]
                and r["probe"]["p99_s"] is not None]
        drift = None
        if len(p99s) >= 2 and p99s[0][1] > 0:
            drift = (p99s[-1][1] - p99s[0][1]) / p99s[0][1]
        drift_ok = drift is None or drift <= self.p99_drift_bound
        verdict = {
            "phases_ok": not phase_violations,
            "sentinels_flat": not leaking,
            "leaking": leaking,
            "p99_drift": drift,
            "p99_drift_ok": drift_ok,
            "ok": not phase_violations and not leaking and drift_ok,
        }
        return {
            "wall_s": round(self.clock() - t0, 3),
            "phases": self.reports,
            "counters_total": {
                k: totals[k] - totals0.get(k, 0.0) for k in totals},
            "sentinels": {
                "samples": len(self.sentinels.samples),
                "growth": growth,
            },
            "verdict": verdict,
        }

    def attach(self, sched) -> "SoakEngine":
        """Expose this engine on the scheduler for /debug/soak (the
        duck-typed pattern /debug/ledger uses)."""
        sched.soak = self
        return self

    def status(self) -> dict:
        """Live JSON view: current phase, completed reports, sentinel
        snapshot (served by /debug/soak while the soak runs)."""
        with self._lock:
            current = self.current
            done = list(self.reports)
        return {
            "current_phase": current,
            "phases_done": [
                {"name": r["name"], "kind": r["kind"], "ok": r["ok"]}
                for r in done],
            "sentinels": self.sentinels.snapshot(),
        }


def standard_counters(sched, auditor=None, extra=None
                      ) -> Dict[str, Callable[[], float]]:
    """The counter set every soak watches, wired from one scheduler:
    SLO burns (ledger watchdog), auditor violations, solve retraces,
    fenced binds, recovery drains. ``extra`` merges driver-specific
    readers (double-bind attempts from a chaos binder, ...)."""
    obs = sched.obs
    counters: Dict[str, Callable[[], float]] = {
        "slo_burns": lambda: float(obs.ledger.watchdog.burns_total()),
        "retraces": lambda: float(obs.jax.retrace_total()),
        "fenced_binds": lambda: float(
            sched.metrics.recovery_fenced_binds.value()),
    }
    incidents = getattr(obs, "incidents", None)
    if incidents is not None and getattr(incidents, "enabled", False):
        # captured incident bundles: monotonic, joins the clean-window
        # zero contract — a clean phase that trips ANY incident trigger
        # is not clean, whatever the sentinel occupancies say
        counters["incidents"] = lambda: float(incidents.total)
    journeys = getattr(obs, "journeys", None)
    if journeys is not None and getattr(journeys, "enabled", False):
        # journeys dropped at the max_pending cap: monotonic; movement
        # means the backlog outran the tracer's bounded pending table
        counters["journey_drops"] = lambda: float(journeys.dropped_total)
    if auditor is not None:
        counters["auditor_violations"] = (
            lambda: float(auditor.violations_total))
    if extra:
        counters.update(extra)
    return counters
