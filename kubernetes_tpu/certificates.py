"""certificates.k8s.io — CSR approve/sign/clean + root-CA publisher.

The reference's kubelet identity bootstrap is a four-actor flow:

- a node submits a CertificateSigningRequest carrying its requested
  subject (CN ``system:node:<name>``, O ``system:nodes``) under its
  bootstrap identity;
- the approver recognizes the two node-client CSR shapes and approves
  iff a SubjectAccessReview grants the requestor the matching
  ``certificatesigningrequests/{nodeclient,selfnodeclient}`` create
  permission (pkg/controller/certificates/approver/sarapprove.go:58
  recognizers, :74 handle);
- the signer signs approved CSRs and writes status.certificate
  (signer/cfssl_signer.go:117 sign);
- the cleaner garbage-collects finished/stale CSRs
  (cleaner/cleaner.go:40 — signed/denied after 1 h, pending after 24 h).

The TPU-native analog models the credential, not the x509: a "signed
certificate" here is an opaque revocable credential string minted from
the hub's CA secret, registered in a live lookup
(:meth:`HollowCluster.cert_user`) the authn chain consumes exactly like
service-account tokens (auth.ServiceAccountAuthenticator takes any
``credential -> UserInfo`` lookup; TLS client-cert auth is modeled as a
bearer credential on this facade). Expiry is enforced at lookup time —
an expired cert authenticates as nothing, the reference's
NotAfter semantics.

rootcacertpublisher (certificates/rootcacertpublisher/publisher.go):
every Active namespace gets a ``kube-root-ca.crt`` ConfigMap carrying
the cluster CA bundle, recreated if deleted, removed with the
namespace.
"""

from __future__ import annotations

import dataclasses
import hashlib
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from kubernetes_tpu.auth import (
    ALLOW,
    Attributes,
    Rule,
    RuleAuthorizer,
    UserInfo,
)

NODE_USER_PREFIX = "system:node:"
NODES_GROUP = "system:nodes"
BOOTSTRAPPERS_GROUP = "system:bootstrappers"

#: the exact usage set a kubelet client cert requests — any other set is
#: NOT a node-client CSR (certificate_controller_utils.go
#: IsKubeletClientCSR / kubeletClientUsages)
NODE_CLIENT_USAGES = frozenset(
    {"key encipherment", "digital signature", "client auth"})

ROOT_CA_CONFIGMAP = "kube-root-ca.crt"


@dataclass
class CertificateSigningRequest:
    """The certificates.k8s.io/v1beta1 slice the controllers consume:
    requestor identity (spec.username/groups), the requested subject
    (the parsed CSR's CN/O — we carry them as fields instead of a PEM
    blob), usages, and the approval/signing status."""

    name: str
    #: spec.username/groups — the authenticated identity that CREATED
    #: the CSR (stamped by the apiserver, not client-controlled)
    username: str = ""
    groups: Tuple[str, ...] = ()
    #: requested subject: CommonName + Organizations of the inner CSR
    request_cn: str = ""
    request_orgs: Tuple[str, ...] = ()
    usages: Tuple[str, ...] = tuple(sorted(NODE_CLIENT_USAGES))
    #: approval condition: None = pending, True = Approved, False = Denied
    approved: Optional[bool] = None
    approval_message: str = ""
    #: when the approval condition landed (the condition timestamp the
    #: cleaner keys denied-CSR age on — cleaner.go isDeniedExpired)
    decided_at: Optional[float] = None
    #: status.certificate — the minted credential (empty until signed)
    certificate: str = ""
    created_at: float = 0.0
    signed_at: float = 0.0


def node_bootstrap_csr(node_name: str, username: str = "",
                       groups: Tuple[str, ...] = (BOOTSTRAPPERS_GROUP,),
                       ) -> CertificateSigningRequest:
    """The CSR a kubelet's TLS bootstrap submits (kubeadm join path):
    subject ``system:node:<name>`` / O ``system:nodes`` under the
    bootstrap-token identity; with ``username=system:node:<name>`` and
    the nodes group it is the self-renewal shape instead."""
    return CertificateSigningRequest(
        name=f"csr-{node_name}",
        username=username or f"{BOOTSTRAPPERS_GROUP}:{node_name}",
        groups=groups,
        request_cn=f"{NODE_USER_PREFIX}{node_name}",
        request_orgs=(NODES_GROUP,),
    )


def is_node_client_csr(csr: CertificateSigningRequest) -> bool:
    """sarapprove.go isNodeClientCert: O == [system:nodes], CN has the
    node prefix, and usages are exactly the kubelet-client set."""
    return (tuple(csr.request_orgs) == (NODES_GROUP,)
            and csr.request_cn.startswith(NODE_USER_PREFIX)
            and frozenset(csr.usages) == NODE_CLIENT_USAGES)


def is_self_node_client_csr(csr: CertificateSigningRequest) -> bool:
    """sarapprove.go isSelfNodeClientCert: a node-client CSR whose
    requestor already IS that node (renewal)."""
    return is_node_client_csr(csr) and csr.username == csr.request_cn


def kubeadm_default_csr_authorizer() -> RuleAuthorizer:
    """The two RBAC bindings kubeadm installs for the bootstrap flow
    (bootstrap-tokens phase): bootstrappers may create nodeclient CSRs,
    nodes may renew their own (selfnodeclient). Resource is spelled
    ``certificatesigningrequests/<subresource>`` — the facade's
    Attributes has no subresource field, so the SAR permission rides
    the resource string."""
    return RuleAuthorizer([
        Rule(subjects=(BOOTSTRAPPERS_GROUP,), verbs=("create",),
             resources=("certificatesigningrequests/nodeclient",)),
        Rule(subjects=(NODES_GROUP,), verbs=("create",),
             resources=("certificatesigningrequests/selfnodeclient",)),
    ])


class CertificateController:
    """Approver + signer + cleaner in one reconcile pass (the reference
    runs them as three controllers over one informer; the hub's
    controller-manager tick drives all three in CSR-name order so the
    flow is deterministic under the fuzz harness)."""

    def __init__(self, hub, authorizer=None,
                 cert_duration_s: float = 365 * 24 * 3600.0,
                 signed_ttl_s: float = 3600.0,
                 pending_ttl_s: float = 24 * 3600.0) -> None:
        self.hub = hub
        self.authorizer = authorizer or kubeadm_default_csr_authorizer()
        self.cert_duration_s = cert_duration_s
        self.signed_ttl_s = signed_ttl_s
        self.pending_ttl_s = pending_ttl_s
        self.approved_total = 0
        self.denied_ignored_total = 0
        self.signed_total = 0
        self.cleaned_total = 0

    # -- approver ----------------------------------------------------------

    def _approve(self, csr: CertificateSigningRequest) -> None:
        """sarapprove.go:74 handle: skip signed/decided CSRs; recognize,
        then authorize the REQUESTOR (not the subject) against the
        recognizer's permission. Unrecognized CSRs are left pending —
        the reference never auto-denies, a human or another approver
        may still act."""
        if csr.certificate or csr.approved is not None:
            return
        recognized = (
            ("selfnodeclient", is_self_node_client_csr),
            ("nodeclient", is_node_client_csr),
        )
        user = UserInfo(name=csr.username, groups=tuple(csr.groups))
        for subresource, recognize in recognized:
            if not recognize(csr):
                continue
            a = Attributes(
                user=user, verb="create",
                resource=f"certificatesigningrequests/{subresource}",
                namespace="", name=csr.name, path="")
            if self.authorizer.authorize(a) == ALLOW:
                csr.approved = True
                csr.decided_at = self.hub.clock.t
                csr.approval_message = (
                    "Auto approving kubelet client certificate after "
                    "SubjectAccessReview.")
                self.approved_total += 1
                self.hub._commit(f"certificatesigningrequests/{csr.name}",
                                 "MODIFIED", csr)
                # CSRs are cluster-scoped: empty namespace segment (the
                # reference's involvedObject.namespace is "" here)
                self.hub.record_controller_event(
                    "CSRApproved", f"/{csr.name}",
                    csr.approval_message,
                    involved_kind="CertificateSigningRequest")
                return
            self.denied_ignored_total += 1

    # -- signer ------------------------------------------------------------

    def _sign(self, csr: CertificateSigningRequest) -> None:
        """cfssl_signer.go:117: approved + unsigned -> mint the
        credential and register it in the hub's live cert registry with
        its NotAfter."""
        if not csr.approved or csr.certificate:
            return
        hub = self.hub
        digest = hashlib.sha256(
            f"{hub.cluster_ca}|{csr.name}|{csr.request_cn}|"
            f"{hub._revision}".encode()).hexdigest()[:32]
        csr.certificate = f"nodecert:{csr.request_cn}:{digest}"
        csr.signed_at = hub.clock.t
        hub.signed_certs[csr.certificate] = (
            UserInfo(name=csr.request_cn, groups=tuple(csr.request_orgs)),
            hub.clock.t + self.cert_duration_s,
        )
        self.signed_total += 1
        hub._commit(f"certificatesigningrequests/{csr.name}",
                    "MODIFIED", csr)

    # -- cleaner -----------------------------------------------------------

    def _clean(self, csr: CertificateSigningRequest) -> bool:
        """cleaner.go:40 pollers: signed or denied CSR objects age out
        after 1 h, never-decided ones after 24 h. Cleaning deletes the
        CSR OBJECT only — the minted credential lives until expiry
        (the reference's issued certs likewise outlive their CSRs)."""
        now = self.hub.clock.t
        if csr.certificate or csr.approved is False:
            ref = (csr.signed_at if csr.certificate
                   else csr.decided_at if csr.decided_at is not None
                   else csr.created_at)
            return now - ref >= self.signed_ttl_s
        return now - csr.created_at >= self.pending_ttl_s

    def reconcile(self) -> None:
        hub = self.hub
        for name in sorted(hub.csrs):
            csr = hub.csrs[name]
            if csr.approved is not None and csr.decided_at is None:
                # externally-decided CSR (a test or operator flipped the
                # condition directly): stamp the condition time now so
                # the cleaner's TTL runs from the DECISION, not create —
                # a denial is observable for its full signed_ttl window
                csr.decided_at = hub.clock.t
            self._approve(csr)
            self._sign(csr)
            if self._clean(csr):
                del hub.csrs[name]
                self.cleaned_total += 1
                hub._commit(f"certificatesigningrequests/{name}",
                            "DELETED", None)
        # expired credentials leave the live registry (NotAfter)
        for cert in [c for c, (_, exp) in hub.signed_certs.items()
                     if hub.clock.t >= exp]:
            del hub.signed_certs[cert]


class RootCACertPublisher:
    """rootcacertpublisher/publisher.go: every Active namespace carries
    the cluster CA bundle in a ``kube-root-ca.crt`` ConfigMap so
    in-cluster clients can verify the apiserver; recreated when
    deleted/mutated, torn down with the namespace (the namespace
    drain owns that half)."""

    def __init__(self, hub) -> None:
        self.hub = hub
        self.writes_total = 0

    def reconcile(self) -> None:
        hub = self.hub
        from kubernetes_tpu.sim import NS_ACTIVE

        for ns_name, ns in hub.namespaces.items():
            if ns.phase != NS_ACTIVE:
                continue
            key = f"{ns_name}/{ROOT_CA_CONFIGMAP}"
            cm = hub.configmaps.get(key)
            want = {"ca.crt": hub.cluster_ca}
            if cm is None or cm.get("data") != want:
                hub.put_configmap(ns_name, ROOT_CA_CONFIGMAP, want)
                self.writes_total += 1
