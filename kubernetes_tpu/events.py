"""Event recording — the client-go ``tools/events``/``tools/record``
analog. The reference scheduler emits Scheduled / FailedScheduling /
Preempted events (scheduler.go:274,:335,:457) through a broadcaster that
aggregates duplicates (same object+reason+message bump a count rather than
creating new objects).

Here: a host-side :class:`EventRecorder` with the same aggregation,
fan-out to sinks (the hub shim posts them to the API; tests and the sim
read them directly)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api.types import Pod

TYPE_NORMAL = "Normal"
TYPE_WARNING = "Warning"

#: the reasons the scheduler emits (scheduler.go / eventhandlers)
REASON_SCHEDULED = "Scheduled"
REASON_FAILED = "FailedScheduling"
REASON_PREEMPTED = "Preempted"
#: degradation-ladder transitions (kubernetes_tpu/faults.py breakers):
#: a solver tier / extender breaker opened (solves now route to a
#: fallback tier) or closed again after a successful half-open probe
REASON_DEGRADED = "SchedulerDegraded"
REASON_RECOVERED = "SchedulerRecovered"
#: an assumed pod's bind confirmation never arrived within the assume
#: TTL — the cache freed its capacity and the driver requeued it
#: (scheduler._reap_expired_assumptions)
REASON_ASSUMPTION_EXPIRED = "AssumptionExpired"
#: the perf ledger's SLO watchdog (obs/ledger.py): an objective's
#: multi-window burn rate crossed the threshold (create-to-bind p99 or
#: cycle-cost drift), and the later fast-window recovery. Emitted on
#: state TRANSITIONS only, then spam-filtered by the recorder like
#: every other series — a burning hour costs a handful of sink posts.
REASON_SLO_BURN = "SchedulerSLOBurn"
REASON_SLO_RECOVERED = "SchedulerSLORecovered"
#: the state-conservation auditor (obs/audit.py) found a pod in two
#: states at once, a node over-committed by committed binds, a lost or
#: zombie-queued pod — always a correctness bug; spam-filtered by the
#: recorder like every other series so a persistent violation costs a
#: handful of sink posts, not one per audit
REASON_INVARIANT_VIOLATION = "InvariantViolation"

_REASON_TYPE = {
    REASON_SCHEDULED: TYPE_NORMAL,
    REASON_FAILED: TYPE_WARNING,
    REASON_PREEMPTED: TYPE_WARNING,
    REASON_DEGRADED: TYPE_WARNING,
    REASON_RECOVERED: TYPE_NORMAL,
    REASON_ASSUMPTION_EXPIRED: TYPE_WARNING,
    REASON_SLO_BURN: TYPE_WARNING,
    REASON_SLO_RECOVERED: TYPE_NORMAL,
    REASON_INVARIANT_VIOLATION: TYPE_WARNING,
}


@dataclass(frozen=True)
class ObjectRef:
    """A minimal involved-object handle for events about things that are
    not Pods (the scheduler component itself, a solver tier, an extender
    endpoint). Carries exactly what the recorder reads: ``key()`` and
    ``involved_kind``. Cluster-scoped refs keep an empty namespace, so
    ``involvedObject.namespace`` serves as ``""`` like the reference's
    cluster-scoped events."""

    name: str
    namespace: str = ""
    involved_kind: str = "Scheduler"

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Event:
    type: str
    reason: str
    object_key: str  # namespace/name of the involved object
    message: str
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0
    #: involvedObject.kind — the scheduler's recorder events are about
    #: Pods; controller-manager events name their own kind (Node for
    #: routes, Service for balancers, Job for TTL deletes, ...)
    involved_kind: str = "Pod"


class EventRecorder:
    """Aggregating recorder: events with the same (object, reason, message)
    within the aggregation window bump ``count`` (the
    EventAggregator/eventBroadcaster behavior that keeps event storms from
    flooding etcd).

    Sink fan-out is SPAM-FILTERED like the reference correlator
    (client-go record/events_cache.go EventSourceObjectSpamFilter): a
    recurrence of the same series bumps ``count`` in place, but sinks —
    the API writes — are notified only at exponentially spaced counts
    (1, 2, 4, 8, ...) or after ``sink_refresh_s`` of silence on the
    series. An unschedulable pod failing 50 consecutive cycles used to
    cost 50 identical FailedScheduling sink posts; now it costs 6 while
    ``count`` (and any stored Event reference — the sink hands out the
    live object) still reads 50."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        sinks: Optional[List[Callable[[Event], None]]] = None,
        max_events: int = 10000,
        sink_refresh_s: float = 300.0,
    ) -> None:
        self.clock = clock
        self.sinks = sinks or []
        self.max_events = max_events
        #: a quiet series re-notifies sinks after this long even between
        #: count milestones, so slow drips still reach the hub fresh
        self.sink_refresh_s = sink_refresh_s
        self._events: Dict[Tuple[str, str, str], Event] = {}
        #: series key -> (next count milestone, last sink-notify time)
        self._sink_state: Dict[Tuple[str, str, str], Tuple[int, float]] = {}

    def event(self, reason: str, pod: Pod, message: str) -> Event:
        now = self.clock()
        key = (pod.key(), reason, message)
        ev = self._events.get(key)
        if ev is not None:
            ev.count += 1
            ev.last_timestamp = now
        else:
            if len(self._events) >= self.max_events:
                # drop the oldest (bounded store; the hub is the real sink)
                oldest = min(self._events, key=lambda k: self._events[k].last_timestamp)
                del self._events[oldest]
                self._sink_state.pop(oldest, None)
            ev = Event(
                type=_REASON_TYPE.get(reason, TYPE_NORMAL),
                reason=reason,
                object_key=pod.key(),
                message=message,
                first_timestamp=now,
                last_timestamp=now,
                # non-Pod involved objects (ObjectRef, nodes) carry their
                # kind; plain Pods keep the default
                involved_kind=getattr(pod, "involved_kind", "Pod"),
            )
            self._events[key] = ev
        milestone, last_notify = self._sink_state.get(key, (1, -1e18))
        if ev.count >= milestone or now - last_notify >= self.sink_refresh_s:
            self._sink_state[key] = (max(milestone, ev.count * 2), now)
            for sink in self.sinks:
                sink(ev)
        return ev

    def sink(self) -> Callable[[str, Pod, str], None]:
        """Adapter matching the driver's event_sink signature."""
        return lambda reason, pod, message: self.event(reason, pod, message)

    def events(self, object_key: Optional[str] = None) -> List[Event]:
        evs = list(self._events.values())
        if object_key is not None:
            evs = [e for e in evs if e.object_key == object_key]
        return sorted(evs, key=lambda e: e.first_timestamp)
