"""Scheduling queue — host-side parity with the reference's 3-queue
``PriorityQueue`` (``pkg/scheduler/internal/queue/scheduling_queue.go``):

- ``activeQ``     — heap ordered by (priority desc, enqueue time asc), the
  pods ready to schedule (``scheduling_queue.go:107``).
- ``podBackoffQ`` — heap by backoff-expiry time; pods that failed recently
  and must wait out an exponential backoff (initial 1 s, max 10 s — the
  values the factory wires in ``factory.go``; ``pod_backoff.go:27``).
- ``unschedulableQ`` — a map of pods that failed with no cluster event since
  that could make them schedulable (``scheduling_queue.go:368`` flushes
  leftovers after 60 s: ``unschedulableQTimeInterval`` ``:52``).

The lost-wakeup defense is the pair of cycle counters
(``scheduling_queue.go:127-134``): ``schedulingCycle`` increments on every
Pop; ``moveRequestCycle`` is stamped by MoveAllToActiveQueue. A pod that
failed in cycle C goes to backoff (not unschedulableQ) if a move request
happened at/after C — the event it missed might have been the one it needs.

The nominated-pods map (``scheduling_queue.go:740`` nominatedPodMap) tracks
pods nominated onto nodes by preemption so the filter pass can run its
two-pass rule (``generic_scheduler.go:610`` podFitsOnNode).

Differences from the reference, by design: no goroutines/locks — the driver
is single-threaded around device dispatch, so flushes are explicit ``tick``
calls (the reference's 1 s/30 s wait.Until loops,
``scheduling_queue.go:202-205``), and Pop is the batched non-blocking
``pop_batch`` feeding whole-queue device scheduling.
"""

from __future__ import annotations

import heapq
import itertools
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import Pod

#: Backoff window — factory.go wires NewPodBackoffMap(1s, 10s).
INITIAL_BACKOFF_S = 1.0
MAX_BACKOFF_S = 10.0
#: scheduling_queue.go:52 unschedulableQTimeInterval.
UNSCHEDULABLEQ_FLUSH_S = 60.0


class PodBackoffMap:
    """Exponential per-pod backoff (``pod_backoff.go:27``): attempts counted
    per pod key; backoff = initial * 2^(attempts-1), capped."""

    def __init__(self, initial: float = INITIAL_BACKOFF_S, maximum: float = MAX_BACKOFF_S):
        self.initial = initial
        self.maximum = maximum
        self._attempts: Dict[str, int] = {}
        self._last_update: Dict[str, float] = {}

    def backoff_pod(self, key: str, now: float) -> None:
        self._attempts[key] = self._attempts.get(key, 0) + 1
        self._last_update[key] = now

    def backoff_time(self, key: str) -> float:
        """Absolute time the pod's backoff expires (0 if never backed off)."""
        n = self._attempts.get(key, 0)
        if n == 0:
            return 0.0
        d = min(self.initial * (2.0 ** (n - 1)), self.maximum)
        return self._last_update[key] + d

    def attempts(self, key: str) -> int:
        """Failed attempts recorded for the pod (the explain/metrics
        surface: scheduling attempts = failures + the current try)."""
        return self._attempts.get(key, 0)

    def clear_pod(self, key: str) -> None:
        self._attempts.pop(key, None)
        self._last_update.pop(key, None)


@dataclass(order=True)
class _ActiveEntry:
    sort_key: Tuple[int, float, int]
    pod: Pod = field(compare=False)


class NominatedPodMap:
    """scheduling_queue.go:740 — pods nominated to run on nodes (preemption
    victims' capacity is reserved for them while they retry)."""

    def __init__(self) -> None:
        self._by_node: Dict[str, List[Pod]] = {}
        self._node_of: Dict[str, str] = {}

    def add(self, pod: Pod, node_name: str = "") -> None:
        node = node_name or getattr(pod, "nominated_node_name", "") or ""
        if not node:
            return
        self.delete(pod)
        self._node_of[pod.key()] = node
        self._by_node.setdefault(node, []).append(pod)

    def delete(self, pod: Pod) -> None:
        node = self._node_of.pop(pod.key(), None)
        if node is None:
            return
        pods = self._by_node.get(node, [])
        self._by_node[node] = [p for p in pods if p.key() != pod.key()]
        if not self._by_node[node]:
            del self._by_node[node]

    def update(self, old: Pod, new: Pod, node_name: str = "") -> None:
        self.delete(old)
        self.add(new, node_name)

    def pods_for_node(self, node_name: str) -> List[Pod]:
        return list(self._by_node.get(node_name, ()))

    def items(self) -> List[Tuple[str, List[Pod]]]:
        return [(n, list(ps)) for n, ps in self._by_node.items()]

    def node_of(self, pod_key: str) -> Optional[str]:
        return self._node_of.get(pod_key)

    def __len__(self) -> int:
        return len(self._node_of)


class _CmpKey:
    """heapq adapter for a custom less(podA, podB) comparator; the seq
    breaks ties stably."""

    __slots__ = ("less", "pod", "seq")

    def __init__(self, less, pod: Pod, seq: int) -> None:
        self.less, self.pod, self.seq = less, pod, seq

    def __lt__(self, other: "_CmpKey") -> bool:
        if self.less(self.pod, other.pod):
            return True
        if self.less(other.pod, self.pod):
            return False
        return self.seq < other.seq


class SchedulingQueue:
    """The 3-queue priority structure. All times come from the injected
    ``clock`` so tests are deterministic."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        less: Optional[Callable[[Pod, Pod], bool]] = None,
        metrics=None,
    ) -> None:
        self.clock = clock
        self._seq = itertools.count()
        self._active: List[_ActiveEntry] = []  # heap
        self._backoff: List[Tuple[float, int, str]] = []  # (expiry, seq, key) heap
        self._unschedulable: Dict[str, Tuple[Pod, float]] = {}  # key -> (pod, added)
        self._in_active: Dict[str, Pod] = {}
        self._in_backoff: Dict[str, Pod] = {}
        self.backoff_map = PodBackoffMap()
        self.nominated = NominatedPodMap()
        self.scheduling_cycle = 0
        self.move_request_cycle = -1
        #: custom QueueSort comparator (framework queue-sort plugin,
        #: interface.go:131); None = priority desc then arrival asc.
        self._less = less
        #: optional SchedulerMetrics: the queue drives
        #: scheduler_queue_incoming_pods_total{event}, the per-sub-queue
        #: scheduler_queue_pod_age_seconds{queue} residency histograms,
        #: and keeps scheduler_pending_pods{queue} fresh on EVERY
        #: mutation (not just at cycle boundaries). The scheduler
        #: attaches its metrics object; standalone queues stay silent.
        self.metrics = metrics
        #: key -> (sub-queue, enter time) for residency accounting
        self._entered: Dict[str, Tuple[str, float]] = {}
        #: optional serving.Doorbell — rung on every incoming event that
        #: ADDS schedulable work (PodAdd/PodUpdate/BackoffComplete/
        #: the move-to-active sweeps). ScheduleAttemptFailure does not
        #: ring: it is the scheduler's own output, and ringing on it
        #: would spin the serving loop against pods no cluster event
        #: has made schedulable. The scheduler attaches it
        #: (Scheduler.attach_doorbell); standalone queues stay silent.
        self.doorbell = None
        #: optional obs.journey.JourneyTracker — fed the pod's
        #: sub-queue transitions (the phase boundaries of queue-wait /
        #: backoff time) and the pop-into-cycle edge. The scheduler
        #: attaches it (same duck pattern as metrics/doorbell);
        #: standalone queues stay silent.
        self.journeys = None

    # -- metrics plumbing --------------------------------------------------

    def _note_enter(self, key: str, queue: str) -> None:
        prev = self._entered.get(key)
        if prev is not None and prev[0] == queue:
            # in-place update / re-add within the same sub-queue: the pod
            # never left, so no exit sample and the original stamp stands
            # (same reason update() preserves queued_at)
            return
        if prev is not None and self.metrics is not None:
            q, t = prev
            self.metrics.queue_pod_age.observe(
                max(self.clock() - t, 0.0), queue=q)
        self._entered[key] = (queue, self.clock())
        if self.journeys is not None:
            self.journeys.note_queue(key, queue)

    def _note_exit(self, key: str) -> None:
        ent = self._entered.pop(key, None)
        if ent is not None and self.metrics is not None:
            q, t = ent
            self.metrics.queue_pod_age.observe(
                max(self.clock() - t, 0.0), queue=q)

    def _incoming(self, event: str, n: int = 1) -> None:
        if n and self.doorbell is not None \
                and event != "ScheduleAttemptFailure":
            self.doorbell.ring(f"queue:{event}")
        if self.metrics is not None and n:
            self.metrics.queue_incoming_pods.inc(n, event=event)

    def _sync_gauges(self) -> None:
        """scheduler_pending_pods{queue} refresh — the ONE place the
        gauge is set, called after every membership mutation so scrapes
        between cycles see the live depths."""
        if self.metrics is None:
            return
        for q, depth in self.pending_counts().items():
            self.metrics.pending_pods.set(depth, queue=q)

    # -- internal ----------------------------------------------------------

    def _push_active(self, pod: Pod) -> None:
        if self._less is None:
            key = (-pod.priority, pod.queued_at, next(self._seq))
        else:
            key = _CmpKey(self._less, pod, next(self._seq))
        heapq.heappush(self._active, _ActiveEntry(key, pod))
        self._in_active[pod.key()] = pod
        self._note_enter(pod.key(), "active")

    def _push_backoff(self, pod: Pod) -> None:
        expiry = self.backoff_map.backoff_time(pod.key())
        heapq.heappush(self._backoff, (expiry, next(self._seq), pod.key()))
        self._in_backoff[pod.key()] = pod
        self._note_enter(pod.key(), "backoff")

    def pending_pods(self) -> Dict[str, List[Pod]]:
        """Snapshot of queued pods by sub-queue (tooling/state dumps)."""
        return {
            "active": list(self._in_active.values()),
            "backoff": list(self._in_backoff.values()),
            "unschedulable": [p for p, _ in self._unschedulable.values()],
        }

    def pod(self, key: str) -> Optional[Pod]:
        """Look up a queued pod by key across the three sub-queues."""
        p = self._in_active.get(key) or self._in_backoff.get(key)
        if p is None and key in self._unschedulable:
            p = self._unschedulable[key][0]
        return p

    def _contains(self, key: str) -> bool:
        return key in self._in_active or key in self._in_backoff or key in self._unschedulable

    # -- reference API -----------------------------------------------------

    def add(self, pod: Pod) -> None:
        """Add a new pending pod to activeQ (scheduling_queue.go Add);
        removes stale copies from the other queues."""
        if not pod.queued_at:
            pod.queued_at = self.clock()
        # an informer relist re-adds every queued pod: that is not a
        # departure (keep the residency stamp — the same-queue guard in
        # _note_enter reuses it) and not a second PodAdd
        readd = self._contains(pod.key())
        if not readd and self.journeys is not None:
            self.journeys.note_created(pod.key(),
                                       getattr(pod, "uid", ""))
        self._remove_everywhere(pod.key(), observe=not readd)
        self._push_active(pod)
        self.nominated.add(pod)
        if not readd:
            self._incoming("PodAdd")
        self._sync_gauges()

    def add_if_not_present(self, pod: Pod) -> None:
        if self._contains(pod.key()):
            return
        self.add(pod)

    def add_unschedulable_if_not_present(self, pod: Pod, pod_scheduling_cycle: int) -> None:
        """scheduling_queue.go:300 — a pod that just failed goes to backoffQ
        if a move request arrived during its cycle (it may have missed the
        wakeup), else to unschedulableQ. Backoff attempts were already
        recorded by the caller via ``record_failure``."""
        if self._contains(pod.key()):
            return
        self.nominated.add(pod)
        if self.move_request_cycle >= pod_scheduling_cycle:
            self._push_backoff(pod)
        else:
            self._unschedulable[pod.key()] = (pod, self.clock())
            self._note_enter(pod.key(), "unschedulable")
        self._incoming("ScheduleAttemptFailure")
        self._sync_gauges()

    def record_failure(self, pod: Pod) -> None:
        """Bump the pod's backoff clock (the driver calls this on every
        failed scheduling attempt, mirroring podBackoff.BackoffPod in the
        error path)."""
        self.backoff_map.backoff_pod(pod.key(), self.clock())

    def pop_batch(self, max_n: int = 0) -> List[Pod]:
        """Pop up to ``max_n`` pods (0 = all) in activeQ order. Increments
        the scheduling cycle once — the whole batch shares one cycle, which
        is the batched analog of per-pod Pop (scheduling_queue.go:389)."""
        out: List[Pod] = []
        while self._active and (not max_n or len(out) < max_n):
            e = heapq.heappop(self._active)
            if self._in_active.get(e.pod.key()) is not e.pod:
                continue  # superseded entry
            del self._in_active[e.pod.key()]
            self._note_exit(e.pod.key())
            out.append(e.pod)
        if out:
            self.scheduling_cycle += 1
            if self.journeys is not None:
                for p in out:
                    self.journeys.note_popped(p.key(),
                                              self.scheduling_cycle)
            self._sync_gauges()
        return out

    def update(self, old_key: str, pod: Pod) -> None:
        """Update in place; an update to an unschedulable pod moves it to
        activeQ (the spec change may have made it schedulable —
        scheduling_queue.go Update). The original enqueue timestamp is
        preserved (the reference keeps podInfo's timestamp on Update) so a
        spec edit never jumps the FIFO order."""
        old = (
            self._in_active.get(old_key)
            or self._in_backoff.get(old_key)
            or (self._unschedulable.get(old_key) or (None,))[0]
        )
        if old is not None:
            pod.queued_at = old.queued_at
        if old_key in self._in_active:
            del self._in_active[old_key]
            self._push_active(pod)
        elif old_key in self._in_backoff:
            del self._in_backoff[old_key]
            self._push_backoff(pod)
        elif old_key in self._unschedulable:
            del self._unschedulable[old_key]
            self._push_active(pod)
        else:
            self.add(pod)
            return
        self._incoming("PodUpdate")
        self._sync_gauges()

    def delete(self, pod_key: str) -> None:
        self._remove_everywhere(pod_key)
        node = self.nominated.node_of(pod_key)
        if node is not None:
            # synthesize a minimal pod for map removal
            ns, name = pod_key.split("/", 1)
            self.nominated.delete(Pod(name=name, namespace=ns))
        self.backoff_map.clear_pod(pod_key)
        self._sync_gauges()

    def _remove_everywhere(self, key: str, observe: bool = True) -> None:
        self._in_active.pop(key, None)
        self._in_backoff.pop(key, None)
        self._unschedulable.pop(key, None)
        if observe:
            # observe=False: the caller is about to re-insert the pod
            # (relist re-add), so the residency stamp must survive
            self._note_exit(key)

    def move_all_to_active(self) -> None:
        """MoveAllToActiveQueue (scheduling_queue.go:519): every
        unschedulable pod moves to activeQ — or backoffQ if still backing
        off — and the move-request cycle is stamped."""
        now = self.clock()
        moved = 0
        for key, (pod, _) in list(self._unschedulable.items()):
            del self._unschedulable[key]
            if self.backoff_map.backoff_time(key) > now:
                self._push_backoff(pod)
            else:
                self._push_active(pod)
            moved += 1
        self.move_request_cycle = self.scheduling_cycle
        self._incoming("MoveAllToActive", moved)
        self._sync_gauges()

    def move_pods_to_active(self, keys: Sequence[str],
                            event: str = "MovePodsToActive") -> None:
        """Subset move (movePodsToActiveQueue) — used by AssignedPodAdded to
        wake only pods with matching affinity terms. ``event`` labels the
        incoming-pods counter with what triggered the move."""
        now = self.clock()
        moved = 0
        for key in keys:
            ent = self._unschedulable.pop(key, None)
            if ent is None:
                continue
            pod, _ = ent
            if self.backoff_map.backoff_time(key) > now:
                self._push_backoff(pod)
            else:
                self._push_active(pod)
            moved += 1
        self.move_request_cycle = self.scheduling_cycle
        self._incoming(event, moved)
        self._sync_gauges()

    def assigned_pod_added(self, pod: Pod) -> None:
        """AssignedPodAdded (scheduling_queue.go): an assigned pod appearing
        can satisfy pending pods' pod-affinity — move unschedulable pods
        that carry any required pod-affinity term matching the new pod's
        labels/namespace."""
        keys = [
            k
            for k, (u, _) in self._unschedulable.items()
            if _affinity_could_match(u, pod)
        ]
        if keys:
            self.move_pods_to_active(keys, event="AssignedPodAdded")

    def flush_backoff_completed(self) -> None:
        """flushBackoffQCompleted (scheduling_queue.go:334) — run each tick."""
        now = self.clock()
        moved = 0
        while self._backoff and self._backoff[0][0] <= now:
            _, _, key = heapq.heappop(self._backoff)
            pod = self._in_backoff.pop(key, None)
            if pod is not None:
                self._push_active(pod)
                moved += 1
        if moved:
            self._incoming("BackoffComplete", moved)
            self._sync_gauges()

    def flush_unschedulable_leftover(self) -> None:
        """flushUnschedulableQLeftover (scheduling_queue.go:368): pods stuck
        longer than 60 s re-enter activeQ."""
        now = self.clock()
        keys = [
            k
            for k, (_, added) in self._unschedulable.items()
            if now - added >= UNSCHEDULABLEQ_FLUSH_S
        ]
        if keys:
            self.move_pods_to_active(keys, event="UnschedulableTimeout")

    def tick(self) -> None:
        """One maintenance sweep = the reference's periodic flush goroutines."""
        self.flush_backoff_completed()
        self.flush_unschedulable_leftover()

    # -- introspection -----------------------------------------------------

    def pending_counts(self) -> Dict[str, int]:
        """Sizes per sub-queue (the pending_pods metric gauge labels)."""
        return {
            "active": len(self._in_active),
            "backoff": len(self._in_backoff),
            "unschedulable": len(self._unschedulable),
        }

    def __len__(self) -> int:
        return len(self._in_active) + len(self._in_backoff) + len(self._unschedulable)


def _affinity_could_match(unschedulable: Pod, assigned: Pod) -> bool:
    """getUnschedulablePodsWithMatchingAffinityTerm: does ``unschedulable``
    carry a required pod-affinity term whose selector+namespace matches the
    newly assigned pod?"""
    for t in unschedulable.affinity.pod_affinity_required:
        ns = t.namespaces or (unschedulable.namespace,)
        if assigned.namespace in ns and t.label_selector.matches(assigned.labels):
            return True
    return False
