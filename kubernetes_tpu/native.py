"""ctypes bindings for the native host-runtime library (``native/ktpu.cc``).

Auto-builds ``libktpu.so`` with the repo's Makefile on first use (cached);
every entry point has a pure-numpy fallback so the package works without a
toolchain — the native path is a performance tier, not a dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libktpu.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False

#: feasibility sentinel shared with the device solvers (ops/assign.NEG)
NEG = -1e30


def _load() -> Optional[ctypes.CDLL]:
    """Build (make) + dlopen the library once; None if unavailable."""
    global _lib, _lib_tried
    with _lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        try:
            if not os.path.exists(_LIB_PATH):
                subprocess.run(
                    ["make", "-s"], cwd=_NATIVE_DIR, check=True,
                    capture_output=True, timeout=120,
                )
            lib = ctypes.CDLL(_LIB_PATH)
            lib.hungarian_solve.argtypes = [
                ctypes.c_int32, ctypes.c_int32,
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ]
            lib.aggregate_usage.argtypes = [
                ctypes.c_int32, ctypes.c_int32,
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                ctypes.c_int32,
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            ]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# exact assignment
# ---------------------------------------------------------------------------


def hungarian(score: np.ndarray) -> np.ndarray:
    """Exact max-total-score assignment of rows (pods) to columns (node
    slots), one row per column. ``score`` (P, S) f32; entries <= NEG/10
    are infeasible. Returns (P,) int32 column per row, -1 = unassigned.

    The augmenting-path algorithm computes a perfect matching over rows,
    so every call pads P dummy "unassigned" columns whose score (-1e9)
    sits strictly between any real score and the infeasible sentinel:
    the optimum then maximizes cardinality first (every dummy taken costs
    more than any feasible edge), score total second — exactly the
    scheduling objective — and rows infeasible everywhere park on dummies
    instead of distorting the matching with sentinel-cost ties."""
    score = np.ascontiguousarray(score, np.float32)
    P, S = score.shape
    if P == 0 or S == 0:
        return np.full((P,), -1, np.int32)
    pad = np.full((P, P), -1e9, np.float32)
    padded = np.ascontiguousarray(np.concatenate([score, pad], axis=1))
    out = np.empty((P,), np.int32)
    lib = _load()
    if lib is not None:
        lib.hungarian_solve(P, padded.shape[1], padded, out)
    else:
        out = _hungarian_py(padded)
    out[out >= S] = -1  # dummy columns = unassigned
    return out


def _hungarian_py(score: np.ndarray) -> np.ndarray:
    """Numpy fallback: same shortest-augmenting-path algorithm."""
    BIG = 1e12
    P, S = score.shape
    # graftlint: disable=R5 -- host Hungarian oracle: f64 keeps the dual
    # potentials' tie-break ordering exact; nothing here rides the device
    cost = np.where(score <= -1e29, BIG, -score.astype(np.float64))
    u = np.zeros(P + 1)
    v = np.zeros(S + 1)
    match = np.zeros(S + 1, np.int64)
    way = np.zeros(S + 1, np.int64)
    for r in range(1, P + 1):
        minv = np.full(S + 1, np.inf)
        used = np.zeros(S + 1, bool)
        j0 = 0
        match[0] = r
        while True:
            used[j0] = True
            i0 = match[j0]
            cur = cost[i0 - 1, :] - u[i0] - v[1:]
            better = (~used[1:]) & (cur < minv[1:])
            minv[1:][better] = cur[better]
            way[1:][better] = j0
            free = ~used[1:]
            if not free.any():
                break
            j1 = 1 + int(np.argmin(np.where(free, minv[1:], np.inf)))
            delta = minv[j1]
            u[match[used]] += delta
            v[used] -= delta
            minv[1:][free] -= delta
            j0 = j1
            if match[j0] == 0:
                break
        while j0:
            j1 = way[j0]
            match[j0] = match[j1]
            j0 = j1
    out = np.full((P,), -1, np.int32)
    for j in range(1, S + 1):
        r = match[j]
        if r > 0 and cost[r - 1, j - 1] < BIG:
            out[r - 1] = j - 1
    return out


def exact_assign(
    score: np.ndarray, mask: np.ndarray, capacity: np.ndarray
) -> np.ndarray:
    """Assignment with per-node multi-capacity via slot expansion: node j
    contributes ``capacity[j]`` identical columns. ``score``/``mask``
    (P, N); ``capacity`` (N,) ints >= 0. Returns (P,) node index or -1.

    This is the exact counterpart of one batch_assign round for workloads
    where total score matters more than wall-clock (gang/offline packing);
    resource-vector feasibility beyond slot counts must be pre-encoded in
    ``mask``/``capacity`` by the caller."""
    P, N = score.shape
    cap = np.minimum(np.asarray(capacity, np.int64), P)
    cols = np.repeat(np.arange(N), cap)  # slot -> node
    if len(cols) == 0:
        return np.full((P,), -1, np.int32)
    s = np.where(mask, score, NEG)[:, cols]
    slot = hungarian(np.ascontiguousarray(s, np.float32))
    out = np.full((P,), -1, np.int32)
    ok = slot >= 0
    out[ok] = cols[slot[ok]]
    return out


# ---------------------------------------------------------------------------
# snapshot aggregation
# ---------------------------------------------------------------------------


def aggregate_usage(
    pod_req: np.ndarray,
    pod_nz: np.ndarray,
    pod_row: np.ndarray,
    out_req: np.ndarray,
    out_nz: np.ndarray,
) -> None:
    """In-place scatter-add of pod requests into node usage arrays (the
    NodeInfo.AddPod accumulation). Rows < 0 skip."""
    pod_req = np.ascontiguousarray(pod_req, np.float32)
    pod_nz = np.ascontiguousarray(pod_nz, np.float32)
    pod_row = np.ascontiguousarray(pod_row, np.int32)
    lib = _load()
    if lib is not None and len(pod_row):
        assert out_req.dtype == np.float32 and out_req.flags["C_CONTIGUOUS"]
        assert out_nz.dtype == np.float32 and out_nz.flags["C_CONTIGUOUS"]
        lib.aggregate_usage(
            len(pod_row), pod_req.shape[1], pod_req, pod_nz, pod_row,
            out_req.shape[0], out_req, out_nz,
        )
        return
    ok = pod_row >= 0
    np.add.at(out_req, pod_row[ok], pod_req[ok])
    np.add.at(out_nz, pod_row[ok], pod_nz[ok])
