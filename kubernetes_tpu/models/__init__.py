from kubernetes_tpu.models import cluster  # noqa: F401
