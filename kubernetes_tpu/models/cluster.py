"""Synthetic cluster/workload generators mirroring the reference's perf
fixtures: ``test/utils/runners.go`` node/pod strategies and the
scheduler_perf templates (``test/integration/scheduler_perf``):

- base node = 4 CPU / 32Gi / 110 pods (scheduler_test.go:49-58)
- base pod  = 100m CPU / 500Mi (runners.go:1233 MakePodSpec)

These drive unit benches, the fake-cluster E2E tests, and bench.py.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import (
    Affinity,
    LabelSelector,
    Node,
    NodeSelectorTerm,
    Pod,
    PreferredSchedulingTerm,
    Requirement,
    Resources,
    TopologySpreadConstraint,
)

GI = 2**30
MI = 2**20


def base_node(name: str, zone: Optional[str] = None, labels: Optional[Dict[str, str]] = None) -> Node:
    lab = dict(labels or {})
    lab.setdefault("kubernetes.io/hostname", name)
    if zone:
        lab["failure-domain.beta.kubernetes.io/zone"] = zone
    return Node(
        name=name,
        labels=lab,
        allocatable=Resources(cpu_milli=4000, memory=32 * GI, pods=110),
    )


def base_pod(name: str, namespace: str = "default", **kw) -> Pod:
    kw.setdefault("requests", Resources(cpu_milli=100, memory=500 * MI))
    return Pod(name=name, namespace=namespace, **kw)


def make_nodes(
    n: int,
    zones: int = 0,
    label_strategy: Optional[Tuple[str, str]] = None,
) -> List[Node]:
    """TrivialNodePrepareStrategy / LabelNodePrepareStrategy analogs."""
    out = []
    for i in range(n):
        labels = {}
        if label_strategy:
            labels[label_strategy[0]] = label_strategy[1]
        zone = f"zone-{i % zones}" if zones else None
        out.append(base_node(f"node-{i}", zone=zone, labels=labels))
    return out


def make_pods(
    n: int,
    name_prefix: str = "pod",
    assigned_round_robin_over: int = 0,
    rng: Optional[random.Random] = None,
) -> List[Pod]:
    """Uniform base pods; optionally pre-bound round-robin over nodes (the
    'existing pods' population of BenchmarkScheduling)."""
    out = []
    for i in range(n):
        p = base_pod(f"{name_prefix}-{i}")
        if assigned_round_robin_over:
            p.node_name = f"node-{i % assigned_round_robin_over}"
        out.append(p)
    return out


def make_spread_pods(
    n: int,
    n_services: int,
    name_prefix: str = "svc-pod",
) -> List[Pod]:
    """Pods owned by services (SelectorSpread workload): n pods spread over
    n_services label selectors."""
    out = []
    for i in range(n):
        svc = i % n_services
        labels = {"app": f"svc-{svc}"}
        sel = LabelSelector(match_labels=dict(labels))
        p = base_pod(f"{name_prefix}-{i}", labels=labels)
        p.spread_selectors = (sel,)
        out.append(p)
    return out


def make_affinity_pods(
    n: int,
    zones: int,
    name_prefix: str = "aff-pod",
    rng: Optional[random.Random] = None,
) -> List[Pod]:
    """NodeAffinity benchmark analog (scheduler_bench_test.go:251
    BenchmarkSchedulingNodeAffinity: pods requiring a random zone)."""
    rng = rng or random.Random(0)
    out = []
    for i in range(n):
        z = rng.randrange(zones)
        aff = Affinity(
            node_required=(
                NodeSelectorTerm(
                    (
                        Requirement(
                            "failure-domain.beta.kubernetes.io/zone",
                            "In",
                            (f"zone-{z}",),
                        ),
                    )
                ),
            )
        )
        p = base_pod(f"{name_prefix}-{i}")
        p.affinity = aff
        out.append(p)
    return out


def make_anti_affinity_pods(
    n: int,
    n_groups: int = 8,
    topology_key: str = "kubernetes.io/hostname",
    name_prefix: str = "anti-pod",
) -> List[Pod]:
    """BenchmarkSchedulingPodAntiAffinity analog
    (scheduler_bench_test.go:71): pods with required anti-affinity against
    their own group label on a topology key — at most one pod per group per
    topology domain."""
    from kubernetes_tpu.api.types import PodAffinityTerm

    out = []
    for i in range(n):
        g = i % max(n_groups, 1)
        labels = {"anti-group": f"g{g}"}
        p = base_pod(f"{name_prefix}-{i}", labels=labels)
        p.affinity = Affinity(
            pod_anti_affinity_required=(
                PodAffinityTerm(
                    label_selector=LabelSelector(match_labels=dict(labels)),
                    topology_key=topology_key,
                ),
            )
        )
        out.append(p)
    return out


def make_spread_constraint_pods(
    n: int,
    topology_key: str = "failure-domain.beta.kubernetes.io/zone",
    max_skew: int = 1,
    hard: bool = True,
    name_prefix: str = "spread-pod",
) -> List[Pod]:
    """EvenPodsSpread workload: every pod carries one spread constraint over
    ``topology_key`` against the shared app label."""
    out = []
    for i in range(n):
        labels = {"spread-app": "app"}
        p = base_pod(f"{name_prefix}-{i}", labels=labels)
        p.topology_spread = (
            TopologySpreadConstraint(
                max_skew=max_skew,
                topology_key=topology_key,
                when_unsatisfiable="DoNotSchedule" if hard else "ScheduleAnyway",
                label_selector=LabelSelector(match_labels=dict(labels)),
            ),
        )
        out.append(p)
    return out


def make_gang_pods(
    n_groups: int,
    group_size: int,
    name_prefix: str = "gang",
) -> List[Pod]:
    """Gang/coscheduling workload (BASELINE config 4): groups of pods that
    must schedule all-or-nothing."""
    out = []
    for g in range(n_groups):
        for i in range(group_size):
            p = base_pod(f"{name_prefix}-{g}-{i}")
            p.pod_group = f"{name_prefix}-{g}"
            out.append(p)
    return out


def make_pod_affinity_pods(
    n: int,
    n_groups: int = 8,
    topology_key: str = "failure-domain.beta.kubernetes.io/zone",
    name_prefix: str = "aff2-pod",
) -> List[Pod]:
    """BenchmarkSchedulingPodAffinity analog (scheduler_bench_test.go:224):
    pods with required pod affinity to their OWN group label on a topology
    key — the first pod of a group seeds a domain (the self-match escape),
    the rest co-locate."""
    from kubernetes_tpu.api.types import PodAffinityTerm

    out = []
    for i in range(n):
        g = i % max(n_groups, 1)
        labels = {"aff-group": f"g{g}"}
        p = base_pod(f"{name_prefix}-{i}", labels=labels)
        p.affinity = Affinity(
            pod_affinity_required=(
                PodAffinityTerm(
                    label_selector=LabelSelector(match_labels=dict(labels)),
                    topology_key=topology_key,
                ),
            )
        )
        out.append(p)
    return out


def make_secret_pods(
    n: int,
    name_prefix: str = "secret-pod",
) -> List[Pod]:
    """BenchmarkSchedulingSecrets analog (scheduler_bench_test.go:97):
    base pods whose spec.volumes carry a Secret — a volume that needs NO
    scheduling predicate handling (resolve_pod_volumes classifies the
    kind as neither conflict- nor limit-checked), so the variant
    measures the per-pod volume FAN-IN cost (volume tables packed and
    the volume kernels invoked per batch) against the base workload."""
    from kubernetes_tpu.api.types import PodVolume

    out = []
    for i in range(n):
        p = base_pod(f"{name_prefix}-{i}")
        # the reference's strategy mounts one shared secret named
        # "secret" in every pod
        p.volumes = (PodVolume(kind="secret", handle="secret"),)
        out.append(p)
    return out


def make_pv_pods(
    n: int,
    kind: str = "gce-pd",
    name_prefix: str = "pv-pod",
) -> Tuple[List[Pod], List["PersistentVolumeClaim"], List["PersistentVolume"]]:
    """BenchmarkSchedulingInTreePVs / BenchmarkSchedulingCSIPVs analog
    (scheduler_bench_test.go:120,:184): one pre-bound PVC/PV pair per pod
    (immediate binding), exercising the attach-limit and zone kernels.
    Returns (pods, pvcs, pvs)."""
    from kubernetes_tpu.api.types import (
        PersistentVolume,
        PersistentVolumeClaim,
        PodVolume,
    )

    pods, pvcs, pvs = [], [], []
    for i in range(n):
        pv = PersistentVolume(
            name=f"{name_prefix}-pv-{i}",
            kind=kind,
            handle=f"{name_prefix}-disk-{i}",
            driver="test.csi.driver" if kind == "csi" else "",
            claim_ref=f"default/{name_prefix}-pvc-{i}",
        )
        pvc = PersistentVolumeClaim(
            name=f"{name_prefix}-pvc-{i}",
            namespace="default",
            volume_name=pv.name,
        )
        p = base_pod(f"{name_prefix}-{i}")
        p.volumes = (PodVolume(pvc=pvc.name),)
        pods.append(p)
        pvcs.append(pvc)
        pvs.append(pv)
    return pods, pvcs, pvs
