"""``ktpu`` — the kubectl-shaped operator CLI for this framework's scope
(the `pkg/kubectl` analog restricted to what the scheduler service owns):
inspect the service's resident snapshot over the gRPC seam, EXPLAIN
scheduling decisions with the real device kernels, and mutate cluster
state through the REST registry.

Read verbs (gRPC seam, --server HOST:PORT):

    python -m kubernetes_tpu.kubectl --server 127.0.0.1:PORT get nodes
    python -m kubernetes_tpu.kubectl --server ... get pods
    python -m kubernetes_tpu.kubectl --server ... describe pod web-0
    python -m kubernetes_tpu.kubectl --server ... describe node n3
    python -m kubernetes_tpu.kubectl --server ... top nodes

Mutation verbs (REST registry, --api-server HOST:PORT — restapi.py):

    python -m kubernetes_tpu.kubectl --api-server ... create -f pod.json
    python -m kubernetes_tpu.kubectl --api-server ... delete pod web-0
    python -m kubernetes_tpu.kubectl --api-server ... delete node n3
    python -m kubernetes_tpu.kubectl --api-server ... cordon n3
    python -m kubernetes_tpu.kubectl --api-server ... uncordon n3

``describe pod`` on a pending pod runs the Filter/Prioritize verbs against
every node in the snapshot and prints the per-node failure reasons /
scores — `kubectl describe pod` events plus `kubectl get events` rolled
into the scheduler's own explanation (FitError text shapes). ``cordon``
is the kubectl drain primitive: a resourceVersion-preconditioned PUT
retried on 409, the client side of GuaranteedUpdate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional


def _fmt_table(headers: List[str], rows: List[List[str]]) -> str:
    widths = [len(h) for h in headers]
    for r in rows:
        for i, c in enumerate(r):
            widths[i] = max(widths[i], len(str(c)))
    line = lambda cells: "   ".join(
        str(c).ljust(w) for c, w in zip(cells, widths)
    ).rstrip()
    return "\n".join([line(headers)] + [line(r) for r in rows])


def _parse_mem(n: float) -> str:
    for unit, div in (("Gi", 2**30), ("Mi", 2**20), ("Ki", 2**10)):
        if n >= div:
            return f"{n / div:.1f}{unit}"
    return str(int(n))


class State:
    """Decoded GetState snapshot."""

    def __init__(self, snap) -> None:
        self.revision = snap.revision
        self.nodes = [json.loads(j) for j in snap.node_json]
        self.bound = [json.loads(j) for j in snap.pod_json]
        #: list of (queue name, pod doc) — provenance from the service
        self.pending_q = []
        for j in snap.pending_json:
            doc = json.loads(j)
            self.pending_q.append((doc["queue"], doc["pod"]))
        self.pending = [p for _, p in self.pending_q]

    def node_names(self) -> List[str]:
        return [n["metadata"]["name"] for n in self.nodes]

    def find_pod(self, name: str) -> Optional[dict]:
        ns, _, bare = name.rpartition("/")
        ns = ns or None
        for p in self.pending + self.bound:
            m = p["metadata"]
            if m["name"] == bare and (ns is None or m["namespace"] == ns):
                return p
        return None

    def usage_by_node(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for p in self.bound:
            nd = p["spec"].get("nodeName")
            if not nd:
                continue
            u = out.setdefault(nd, {"cpu": 0.0, "memory": 0.0, "pods": 0})
            for c in p["spec"].get("containers", []):
                req = (c.get("resources") or {}).get("requests") or {}
                from kubernetes_tpu.server import parse_quantity

                u["cpu"] += parse_quantity(req.get("cpu", "0"), is_cpu=True)
                u["memory"] += parse_quantity(req.get("memory", "0"))
            u["pods"] += 1
        return out


def _node_status(nd: dict) -> str:
    conds = {c["type"]: c["status"] == "True"
             for c in nd.get("status", {}).get("conditions", [])}
    parts = ["Ready" if conds.get("Ready", True) else "NotReady"]
    if nd.get("spec", {}).get("unschedulable"):
        parts.append("SchedulingDisabled")
    for k in ("MemoryPressure", "DiskPressure", "PIDPressure"):
        if conds.get(k):
            parts.append(k)
    return ",".join(parts)


def cmd_get(client, args) -> int:
    st = State(client.get_state_snapshot())
    if args.kind in ("nodes", "node", "no"):
        rows = []
        for nd in st.nodes:
            alloc = nd["status"]["allocatable"]
            taints = nd.get("spec", {}).get("taints", [])
            rows.append([
                nd["metadata"]["name"], _node_status(nd),
                str(len(taints)), alloc.get("cpu", "?"),
                _parse_mem(float(alloc.get("memory", 0))),
                alloc.get("pods", "?"),
            ])
        print(_fmt_table(
            ["NAME", "STATUS", "TAINTS", "CPU", "MEMORY", "PODS"], rows))
    elif args.kind in ("pods", "pod", "po"):
        # kubectl-parity scoping: -n selects a namespace (defaulting to
        # "default", like kubectl), -A lists every namespace
        want_ns = None if getattr(args, "all_namespaces", False) \
            else getattr(args, "namespace", None)
        rows = []
        for p in st.bound:
            m = p["metadata"]
            if want_ns and m["namespace"] != want_ns:
                continue
            rows.append([m["namespace"], m["name"], "Bound",
                         p["spec"].get("nodeName", ""),
                         str(p["spec"].get("priority", 0))])
        for q, p in st.pending_q:
            m = p["metadata"]
            if want_ns and m["namespace"] != want_ns:
                continue
            status = "Pending" if q == "active" else f"Pending({q})"
            rows.append([m["namespace"], m["name"], status, "",
                         str(p["spec"].get("priority", 0))])
        print(_fmt_table(
            ["NAMESPACE", "NAME", "STATUS", "NODE", "PRIORITY"], rows))
    else:
        print(f"error: unknown kind {args.kind!r}", file=sys.stderr)
        return 1
    return 0


def cmd_top(client, args) -> int:
    st = State(client.get_state_snapshot())
    usage = st.usage_by_node()
    from kubernetes_tpu.server import parse_quantity

    rows = []
    for nd in st.nodes:
        name = nd["metadata"]["name"]
        alloc = nd["status"]["allocatable"]
        cap_cpu = parse_quantity(alloc.get("cpu", "0"), is_cpu=True)
        cap_mem = parse_quantity(alloc.get("memory", "0"))
        u = usage.get(name, {"cpu": 0.0, "memory": 0.0, "pods": 0})
        rows.append([
            name,
            f"{u['cpu']:.0f}m",
            f"{100 * u['cpu'] / cap_cpu:.0f}%" if cap_cpu else "-",
            _parse_mem(u["memory"]),
            f"{100 * u['memory'] / cap_mem:.0f}%" if cap_mem else "-",
            str(u["pods"]),
        ])
    print(_fmt_table(
        ["NAME", "CPU(req)", "CPU%", "MEMORY(req)", "MEMORY%", "PODS"], rows))
    return 0


def _pending_breakdown(failed_nodes: Dict[str, str], n_total: int,
                       feasible: int) -> List[str]:
    """kubectl-describe enrichment for a pending pod: aggregate the
    filter verb's per-node failure reasons into the reference's
    "0/N nodes are available: <count> <reason>, ..." line (FitError
    shape, per-reason NODE counts) plus the top one-bit-away
    relaxations — a node whose failure set is a single predicate is
    opened by relaxing exactly that predicate (obs/explain.py
    semantics, recomputed client-side from the wire reasons)."""
    from kubernetes_tpu.obs.explain import reason_message
    from kubernetes_tpu.ops.predicates import PREDICATE_BITS

    predicates = set(PREDICATE_BITS)
    per_reason: Dict[str, int] = {}
    one_bit: Dict[str, int] = {}
    for _node, why in failed_nodes.items():
        names = [w for w in why.split(",") if w]
        for nm in names:
            per_reason[nm] = per_reason.get(nm, 0) + 1
        # wire sentinels ("infeasible", "node not in snapshot") stay in
        # the 0/N line but are not predicates — "relax infeasible" is
        # not actionable advice
        if len(names) == 1 and names[0] in predicates:
            one_bit[names[0]] = one_bit.get(names[0], 0) + 1
    lines: List[str] = []
    if not feasible and per_reason:
        parts = sorted(
            f"{c} {reason_message(n)}" for n, c in per_reason.items())
        lines.append(
            f"Status: 0/{n_total} nodes are available: "
            f"{', '.join(parts)}.")
    if not feasible and one_bit:
        lines.append("One-bit-away (single relaxation -> nodes opened):")
        for nm, c in sorted(one_bit.items(),
                            key=lambda kv: (-kv[1], kv[0]))[:3]:
            lines.append(f"  relax {nm}: +{c} node(s)")
    return lines


def cmd_describe(client, args) -> int:
    from kubernetes_tpu.proto import extender_pb2 as pb

    st = State(client.get_state_snapshot())
    if args.kind in ("pod", "pods", "po"):
        p = st.find_pod(args.name)
        if p is None:
            print(f'error: pod "{args.name}" not found', file=sys.stderr)
            return 1
        m = p["metadata"]
        print(f"Name:       {m['name']}")
        print(f"Namespace:  {m['namespace']}")
        print(f"Priority:   {p['spec'].get('priority', 0)}")
        print(f"Labels:     {m.get('labels') or {}}")
        node = p["spec"].get("nodeName", "")
        print(f"Node:       {node or '<none>'}")
        if not node:
            # explain: run the real Filter/Prioritize verbs over the
            # snapshot (the scheduler's own kernels answer)
            fr = client.filter(pb.ExtenderArgs(
                pod_json=json.dumps(p), node_names=st.node_names()))
            print("\nScheduling explanation (Filter):")
            if fr.error:
                print(f"  error: {fr.error}")
            for line in _pending_breakdown(
                    dict(fr.failed_nodes),
                    len(fr.node_names) + len(fr.failed_nodes),
                    len(fr.node_names)):
                print(line)
            for n in fr.node_names:
                print(f"  {n}: feasible")
            for n, why in sorted(fr.failed_nodes.items()):
                print(f"  {n}: {why}")
            if fr.node_names:
                pr = client.prioritize(pb.ExtenderArgs(
                    pod_json=json.dumps(p),
                    node_names=list(fr.node_names)))
                print("Scores (0-10):")
                for item in sorted(pr.items, key=lambda i: -i.score):
                    print(f"  {item.host}: {item.score}")
        return 0
    if args.kind in ("node", "nodes", "no"):
        nd = next((n for n in st.nodes
                   if n["metadata"]["name"] == args.name), None)
        if nd is None:
            print(f'error: node "{args.name}" not found', file=sys.stderr)
            return 1
        print(f"Name:    {nd['metadata']['name']}")
        print(f"Status:  {_node_status(nd)}")
        print(f"Labels:  {nd['metadata'].get('labels') or {}}")
        taints = nd.get("spec", {}).get("taints", [])
        print(f"Taints:  {taints or '<none>'}")
        print(f"Allocatable: {nd['status']['allocatable']}")
        u = st.usage_by_node().get(args.name)
        if u:
            print(f"Requested:   cpu {u['cpu']:.0f}m, "
                  f"memory {_parse_mem(u['memory'])}, pods {u['pods']}")
        pods = [p["metadata"]["name"] for p in st.bound
                if p["spec"].get("nodeName") == args.name]
        print(f"Pods ({len(pods)}): {', '.join(sorted(pods)) or '<none>'}")
        return 0
    print(f"error: unknown kind {args.kind!r}", file=sys.stderr)
    return 1


class RestClient:
    """HTTP client for the REST registry (restapi.py). ``token`` sends
    `Authorization: Bearer <token>` on every request — the client half
    of the facade's authentication filter."""

    def __init__(self, target: str, token=None):
        host, _, port = target.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self._headers = ({"Authorization": f"Bearer {token}"}
                         if token else {})

    def call(self, method: str, path: str, body=None, headers=None):
        import http.client

        conn = http.client.HTTPConnection(self.host, self.port, timeout=30)
        conn.request(method, path,
                     json.dumps(body) if body is not None else None,
                     {**self._headers, **(headers or {})})
        r = conn.getresponse()
        data = r.read()
        conn.close()
        return r.status, json.loads(data) if data else None


def _rest_fail(doc) -> int:
    msg = (doc or {}).get("message") or (doc or {}).get("reason") or "error"
    print(f"Error: {msg}", file=sys.stderr)
    return 1


def cmd_create(rest: RestClient, args) -> int:
    with open(args.filename) as f:
        doc = json.load(f)
    kind = doc.get("kind")
    if not kind:
        # kubectl refuses kind-less docs; guessing here could create a
        # bogus Pod out of a hand-written Node manifest
        print(f"Error: {args.filename} is missing 'kind'", file=sys.stderr)
        return 1
    if kind not in ("Pod", "Node"):
        print(f"Error: unsupported kind {kind!r}", file=sys.stderr)
        return 1
    if kind == "Node":
        code, out = rest.call("POST", "/api/v1/nodes", doc)
        what = f"node/{(doc.get('metadata') or {}).get('name', '?')}"
    else:
        ns = (doc.get("metadata") or {}).get("namespace") or args.namespace
        code, out = rest.call("POST", f"/api/v1/namespaces/{ns}/pods", doc)
        what = f"pod/{(doc.get('metadata') or {}).get('name', '?')}"
    if code != 201:
        return _rest_fail(out)
    print(f"{what} created")
    return 0


def cmd_apply(rest: RestClient, args) -> int:
    """kubectl apply -f: declarative create-or-update. Absent -> POST;
    present -> PATCH with the manifest as a JSON merge patch (the
    facade's supported patch type). One deliberate simplification vs
    kubectl: no last-applied three-way merge — fields you DROP from the
    manifest are left as-is on the server, not deleted (delete a field
    explicitly with null, RFC 7386)."""
    with open(args.filename) as f:
        doc = json.load(f)
    kind = doc.get("kind")
    name = (doc.get("metadata") or {}).get("name", "")
    if not kind or not name:
        print(f"Error: {args.filename} needs kind and metadata.name",
              file=sys.stderr)
        return 1
    ns = (doc.get("metadata") or {}).get("namespace") or args.namespace
    routes = {
        "Pod": (f"/api/v1/namespaces/{ns}/pods", f"pod/{name}"),
        "Node": ("/api/v1/nodes", f"node/{name}"),
        "Deployment": (f"/apis/apps/v1/namespaces/{ns}/deployments",
                       f"deployment.apps/{name}"),
        "Namespace": ("/api/v1/namespaces", f"namespace/{name}"),
    }
    if kind not in routes:
        print(f"Error: unsupported kind {kind!r}", file=sys.stderr)
        return 1
    collection, what = routes[kind]
    code, cur = rest.call("GET", f"{collection}/{name}")
    if code == 404:
        code, out = rest.call("POST", collection, doc)
        if code != 201:
            return _rest_fail(out)
        print(f"{what} created")
        return 0
    if code != 200:
        return _rest_fail(cur)
    if kind == "Namespace":
        print(f"{what} unchanged")  # namespaces have no mutable spec here
        return 0
    # the FULL manifest goes as the patch: a pod whose spec genuinely
    # changed gets the facade's 422 (spec changes need delete+create so
    # admission re-runs) surfaced as a real failure — never a silent
    # 'configured' that dropped the user's change
    code, out = rest.call(
        "PATCH", f"{collection}/{name}", doc,
        headers={"Content-Type": "application/merge-patch+json"})
    if code != 200:
        return _rest_fail(out)
    print(f"{what} configured")
    return 0


def cmd_get_events(rest: RestClient, args) -> int:
    """kubectl get events: the hub's Event registry over REST, newest
    last, kubectl's column shape; -A/--all-namespaces widens the scope."""
    path = ("/api/v1/events" if args.all_namespaces
            else f"/api/v1/namespaces/{args.namespace}/events")
    if getattr(args, "field_selector", ""):
        from urllib.parse import quote

        path += f"?fieldSelector={quote(args.field_selector)}"
    code, doc = rest.call("GET", path)
    if code != 200:
        return _rest_fail(doc)
    rows = [
        [
            str(it.get("count", 1)),
            it.get("type", ""),
            it.get("reason", ""),
            f"pod/{it['involvedObject']['name']}",
            it.get("message", "")[:80],
        ]
        for it in doc["items"]
    ]
    print(_fmt_table(["COUNT", "TYPE", "REASON", "OBJECT", "MESSAGE"], rows))
    return 0


def cmd_get_csr(rest: RestClient, args) -> int:
    """kubectl get csr (certificates.k8s.io/v1beta1): the CSR flow's
    observable state — requestor, subject, condition."""
    code, doc = rest.call(
        "GET", "/apis/certificates.k8s.io/v1beta1/"
               "certificatesigningrequests")
    if code != 200:
        return _rest_fail(doc)
    rows = []
    for it in doc["items"]:
        conds = [c["type"] for c in it["status"].get("conditions", [])]
        cond = ",".join(conds) or "Pending"
        if it["status"].get("certificateIssued"):
            cond += ",Issued"
        rows.append([
            it["metadata"]["name"],
            it["spec"].get("username", ""),
            it["spec"].get("request", {}).get("commonName", ""),
            cond,
        ])
    print(_fmt_table(["NAME", "REQUESTOR", "SUBJECT", "CONDITION"], rows))
    return 0


def cmd_get_configmaps(rest: RestClient, args) -> int:
    """kubectl get configmaps: name + data-key count per namespace."""
    path = ("/api/v1/configmaps" if args.all_namespaces
            else f"/api/v1/namespaces/{args.namespace}/configmaps")
    code, doc = rest.call("GET", path)
    if code != 200:
        return _rest_fail(doc)
    rows = [[it["metadata"]["namespace"], it["metadata"]["name"],
             str(len(it.get("data", {})))]
            for it in doc["items"]]
    print(_fmt_table(["NAMESPACE", "NAME", "DATA"], rows))
    return 0


def cmd_get_serviceaccounts(rest: RestClient, args) -> int:
    """kubectl get serviceaccounts: the identities the tokens
    controller maintains, with their token-secret references."""
    path = ("/api/v1/serviceaccounts" if args.all_namespaces
            else f"/api/v1/namespaces/{args.namespace}/serviceaccounts")
    code, doc = rest.call("GET", path)
    if code != 200:
        return _rest_fail(doc)
    rows = [[it["metadata"]["namespace"], it["metadata"]["name"],
             str(len(it.get("secrets", [])))]
            for it in doc["items"]]
    print(_fmt_table(["NAMESPACE", "NAME", "SECRETS"], rows))
    return 0


def cmd_get_daemonsets(rest: RestClient, args) -> int:
    """kubectl get daemonsets: desired/ready/updated per DS."""
    code, doc = rest.call("GET", "/apis/apps/v1/namespaces/default/"
                                 "daemonsets")
    if code != 200:
        return _rest_fail(doc)
    rows = [[it["metadata"]["name"],
             str(it["status"]["desiredNumberScheduled"]),
             str(it["status"]["numberReady"]),
             str(it["status"]["updatedNumberScheduled"]),
             str(it["status"]["observedRevision"])]
            for it in doc["items"]]
    print(_fmt_table(["NAME", "DESIRED", "READY", "UPDATED", "REV"], rows))
    return 0


def cmd_get_statefulsets(rest: RestClient, args) -> int:
    """kubectl get statefulsets: replicas/ready/updated per STS."""
    code, doc = rest.call("GET", "/apis/apps/v1/namespaces/default/"
                                 "statefulsets")
    if code != 200:
        return _rest_fail(doc)
    rows = [[it["metadata"]["name"],
             f'{it["status"]["readyReplicas"]}/{it["spec"]["replicas"]}',
             str(it["status"]["updatedReplicas"]),
             str(it["status"]["observedRevision"])]
            for it in doc["items"]]
    print(_fmt_table(["NAME", "READY", "UPDATED", "REV"], rows))
    return 0


def cmd_rollout_history(rest: RestClient, args) -> int:
    """kubectl rollout history: the ControllerRevision trail for one
    DS/STS (kind/name target, like rollout status)."""
    kind, _, name = args.target.partition("/")
    kind_map = {"daemonset": "DaemonSet", "ds": "DaemonSet",
                "statefulset": "StatefulSet", "sts": "StatefulSet"}
    owner_kind = kind_map.get(kind.lower())
    if owner_kind is None or not name:
        print(f"Error: rollout history target must be "
              f"daemonset/NAME or statefulset/NAME, got {args.target!r}",
              file=sys.stderr)
        return 2
    code, doc = rest.call("GET", "/apis/apps/v1/namespaces/default/"
                                 "controllerrevisions")
    if code != 200:
        return _rest_fail(doc)
    rows = [[str(it["revision"]),
             ", ".join(f"{k}={v}" for k, v in sorted(it["data"].items()))]
            for it in sorted(doc["items"], key=lambda i: i["revision"])
            if it["metadata"]["ownerReferences"][0]["kind"] == owner_kind
            and it["metadata"]["ownerReferences"][0]["name"] == name]
    if not rows:
        print(f"Error: no revisions found for {args.target}",
              file=sys.stderr)
        return 1
    print(_fmt_table(["REVISION", "TEMPLATE"], rows))
    return 0


def cmd_get_leases(rest: RestClient, args) -> int:
    """kubectl get leases (coordination.k8s.io/v1): HA state over REST —
    who holds each lock and how fresh the renewal is."""
    path = ("/apis/coordination.k8s.io/v1/leases" if args.all_namespaces
            else "/apis/coordination.k8s.io/v1/namespaces/"
                 f"{args.namespace}/leases")
    code, doc = rest.call("GET", path)
    if code != 200:
        return _rest_fail(doc)
    rows = [
        [
            it["metadata"]["namespace"],
            it["metadata"]["name"],
            it["spec"].get("holderIdentity", ""),
            str(it["spec"].get("leaseTransitions", 0)),
            f"{it['spec'].get('renewTime', 0):.1f}",
        ]
        for it in doc["items"]
    ]
    print(_fmt_table(["NAMESPACE", "NAME", "HOLDER", "TRANSITIONS",
                      "RENEWTIME"], rows))
    if not rows and not args.all_namespaces:
        # the well-known scheduler lease lives in kube-system; an empty
        # default-namespace table almost always means the wrong scope
        print(f'No leases found in namespace "{args.namespace}" '
              "(try -n kube-system or -A)", file=sys.stderr)
    return 0


def cmd_drain(rest: RestClient, args) -> int:
    """kubectl drain: cordon the node, then EVICT every pod on it
    through the Eviction subresource (PDB-guarded; a 429 is reported and
    leaves the pod — kubectl's retry loop compressed to one pass with an
    honest exit code). DaemonSet-owned pods are skipped, kubectl's
    --ignore-daemonsets posture (their controller would just repin
    them)."""
    rc = cmd_cordon(rest, args, unschedulable=True)
    if rc != 0:
        return rc
    # server-side field selector: list ONLY this node's pods (the
    # spec.nodeName selector kubelets live on, pod/strategy.go:197) —
    # listing the world and filtering client-side is the anti-pattern
    # the watch cache exists to prevent
    from urllib.parse import quote

    code, doc = rest.call(
        "GET",
        f"/api/v1/pods?fieldSelector={quote(f'spec.nodeName={args.name}')}",
    )
    if code != 200:
        return _rest_fail(doc)
    blocked = []
    for p in doc["items"]:
        m = p["metadata"]
        refs = p["metadata"].get("ownerReferences") or []
        if any(r.get("kind") == "DaemonSet" for r in refs):
            print(f"ignoring DaemonSet-managed pod {m['name']}")
            continue
        code, out = rest.call(
            "POST",
            f"/api/v1/namespaces/{m['namespace']}/pods/{m['name']}/eviction",
            {"kind": "Eviction",
             "metadata": {"name": m["name"], "namespace": m["namespace"]}},
        )
        if code == 201:
            print(f"pod/{m['name']} evicted")
        elif code == 404:
            # vanished between list and evict — exactly what drain
            # wanted; kubectl treats this as success too
            print(f"pod/{m['name']} already gone")
        elif code == 429:
            blocked.append(m["name"])
            print(f"error when evicting pod/{m['name']}: "
                  f"{out.get('message', '')}", file=sys.stderr)
        else:
            return _rest_fail(out)
    if blocked:
        print(f"drain incomplete: {len(blocked)} pod(s) blocked by "
              "disruption budgets", file=sys.stderr)
        return 1
    print(f"node/{args.name} drained")
    return 0


def cmd_get_deployments(rest: RestClient, args) -> int:
    """kubectl get deployments: rollout state over the apps/v1 routes."""
    code, doc = rest.call("GET", "/apis/apps/v1/deployments")
    if code != 200:
        return _rest_fail(doc)
    rows = []
    for it in doc["items"]:
        st = it["status"]
        rows.append([
            it["metadata"]["name"],
            f"{st.get('readyReplicas', 0)}/{it['spec'].get('replicas', 0)}",
            str(st.get("updatedReplicas", 0)),
            str(st.get("observedRevision", 0)),
            it["spec"].get("strategy", ""),
        ])
    print(_fmt_table(["NAME", "READY", "UP-TO-DATE", "REVISION",
                      "STRATEGY"], rows))
    return 0


def cmd_scale(rest: RestClient, args) -> int:
    """kubectl scale deployment/NAME --replicas=N through the /scale
    subresource (ScaleREST.Update, storage.go:230) — the same write the
    HPA performs."""
    kind, _, name = args.target.partition("/")
    if kind not in ("deployment", "deploy", "deployments") or not name:
        print(f"error: scale expects deployment/NAME, got "
              f"{args.target!r}", file=sys.stderr)
        return 2
    code, doc = rest.call(
        "PUT",
        f"/apis/apps/v1/namespaces/{args.namespace}/deployments/"
        f"{name}/scale",
        {"kind": "Scale", "spec": {"replicas": args.replicas}},
    )
    if code != 200:
        return _rest_fail(doc)
    print(f"deployment.apps/{name} scaled")
    return 0


def cmd_rollout_status(rest: RestClient, args) -> int:
    """kubectl rollout status deployment/NAME, one-shot: prints the
    current rollout state; exit 0 when complete (all replicas updated
    and ready), 1 while in progress — scriptable polling instead of
    kubectl's watch loop."""
    kind, _, name = args.target.partition("/")
    if kind not in ("deployment", "deploy", "deployments") or not name:
        print(f"error: rollout status expects deployment/NAME, got "
              f"{args.target!r}", file=sys.stderr)
        return 2
    code, doc = rest.call("GET", "/apis/apps/v1/namespaces/default/"
                                 f"deployments/{name}")
    if code != 200:
        _rest_fail(doc)
        return 2  # error, NOT "in progress": pollable scripts must stop
    want = doc["spec"].get("replicas", 0)
    st = doc["status"]
    updated, ready = st.get("updatedReplicas", 0), st.get("readyReplicas", 0)
    if updated >= want and ready >= want and st.get("replicas", 0) == want:
        print(f'deployment "{name}" successfully rolled out '
              f'({updated}/{want} updated)')
        return 0
    print(f'Waiting for deployment "{name}" rollout to finish: '
          f'{updated} of {want} updated replicas are available...')
    return 1


def cmd_describe_apps(rest: RestClient, args) -> int:
    """kubectl describe deployment/daemonset/statefulset over REST:
    spec + rollout status, the owned-ReplicaSet breakdown (deployments),
    and the object's recent events — the operator's one-stop rollout
    view."""
    kind_map = {"deployment": "deployments", "deploy": "deployments",
                "daemonset": "daemonsets", "ds": "daemonsets",
                "statefulset": "statefulsets", "sts": "statefulsets"}
    resource = kind_map[args.kind]
    code, doc = rest.call(
        "GET", f"/apis/apps/v1/namespaces/default/{resource}/{args.name}")
    if code != 200:
        return _rest_fail(doc)
    print(f"Name:       {args.name}")
    st = doc.get("status", {})
    if resource == "deployments":
        spec = doc["spec"]
        print(f"Replicas:   {spec.get('replicas', 0)} desired | "
              f"{st.get('updatedReplicas', 0)} updated | "
              f"{st.get('readyReplicas', 0)} ready")
        strategy = spec.get("strategy", "")
        if isinstance(strategy, dict):  # tolerate both doc shapes
            strategy = strategy.get("type", "RollingUpdate")
        if strategy:
            print(f"Strategy:   {strategy}")
        code, rss = rest.call(
            "GET", "/apis/apps/v1/namespaces/default/replicasets")
        if code == 200:
            owned = [it for it in rss["items"]
                     if it["metadata"].get("ownerReferences",
                                           [{}])[0].get("name")
                     == args.name]
            if owned:
                print("ReplicaSets:")
                for it in owned:
                    m, s = it["metadata"], it.get("status", {})
                    print(f"  {m['name']}: {s.get('replicas', 0)} replicas,"
                          f" revision {it.get('revision', '?')}")
    elif resource == "daemonsets":
        print(f"Desired:    {st.get('desiredNumberScheduled', 0)} | "
              f"ready {st.get('numberReady', 0)} | "
              f"updated {st.get('updatedNumberScheduled', 0)} "
              f"(rev {st.get('observedRevision', '?')})")
    else:
        print(f"Replicas:   {st.get('readyReplicas', 0)}/"
              f"{doc['spec'].get('replicas', 0)} ready | "
              f"updated {st.get('updatedReplicas', 0)} "
              f"(rev {st.get('observedRevision', '?')})")
    code, evs = rest.call(
        "GET", "/api/v1/events?fieldSelector="
               f"involvedObject.name%3D{args.name}")
    if code == 200 and evs["items"]:
        print("Events:")
        for it in evs["items"]:
            print(f"  {it['type']}\t{it['reason']}\t{it['message'][:70]}")
    return 0


def cmd_get_namespaces(rest: RestClient, args) -> int:
    """kubectl get namespaces: lifecycle phases over REST."""
    code, doc = rest.call("GET", "/api/v1/namespaces")
    if code != 200:
        return _rest_fail(doc)
    rows = [[it["metadata"]["name"], it["status"].get("phase", "")]
            for it in doc["items"]]
    print(_fmt_table(["NAME", "STATUS"], rows))
    return 0


def cmd_delete(rest: RestClient, args) -> int:
    if args.kind in ("node", "nodes"):
        code, out = rest.call("DELETE", f"/api/v1/nodes/{args.name}")
        what = f"node/{args.name}"
    else:
        code, out = rest.call(
            "DELETE", f"/api/v1/namespaces/{args.namespace}/pods/{args.name}"
        )
        what = f"pod/{args.name}"
    if code != 200:
        return _rest_fail(out)
    print(f"{what} deleted")
    return 0


def cmd_cordon(rest: RestClient, args, unschedulable: bool) -> int:
    # kubectl cordon: read-modify-write with the resourceVersion
    # precondition, retried on 409 — the client half of GuaranteedUpdate
    # (etcd3/store.go:236); bounded attempts like RetryOnConflict
    for _ in range(5):
        code, node = rest.call("GET", f"/api/v1/nodes/{args.name}")
        if code != 200:
            return _rest_fail(node)
        node.setdefault("spec", {})["unschedulable"] = unschedulable
        code, out = rest.call("PUT", f"/api/v1/nodes/{args.name}", node)
        if code == 200:
            print(f"node/{args.name} "
                  f"{'cordoned' if unschedulable else 'uncordoned'}")
            return 0
        if code != 409:
            return _rest_fail(out)
    print(f"Error: conflict updating node/{args.name} after 5 retries",
          file=sys.stderr)
    return 1


class _Client:
    """Thin wrapper adding get_state_snapshot() sugar."""

    def __init__(self, target: str, token=None):
        from kubernetes_tpu.grpc_shim import GrpcSchedulerClient
        from kubernetes_tpu.proto import extender_pb2 as pb

        self._c = GrpcSchedulerClient(target, token=token)
        self._pb = pb

    def get_state_snapshot(self):
        return self._c.get_state(self._pb.StateRequest())

    def __getattr__(self, name):
        return getattr(self._c, name)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="ktpu", description="kubectl-shaped CLI for the TPU scheduler"
    )
    p.add_argument("--server", help="gRPC service HOST:PORT (read verbs)")
    p.add_argument("--api-server",
                   help="REST registry HOST:PORT (mutation verbs)")
    p.add_argument("--token", default=os.environ.get("KTPU_TOKEN"),
                   help="bearer token for a token-gated gRPC service "
                        "(or KTPU_TOKEN env var)")
    sub = p.add_subparsers(dest="cmd", required=True)
    g = sub.add_parser("get")
    g.add_argument("kind")
    g.add_argument("-n", "--namespace", default="default")
    g.add_argument("-A", "--all-namespaces", action="store_true")
    g.add_argument("--field-selector", default="",
                   help="server-side field filter (events: reason=..., "
                        "involvedObject.name=..., type=...)")
    t = sub.add_parser("top")
    t.add_argument("kind", choices=["nodes"])
    d = sub.add_parser("describe")
    d.add_argument("kind")
    d.add_argument("name")
    c = sub.add_parser("create")
    c.add_argument("-f", "--filename", required=True)
    c.add_argument("-n", "--namespace", default="default")
    ap_ = sub.add_parser("apply")
    ap_.add_argument("-f", "--filename", required=True)
    ap_.add_argument("-n", "--namespace", default="default")
    de = sub.add_parser("delete")
    de.add_argument("kind", choices=["pod", "pods", "node", "nodes"])
    de.add_argument("name")
    de.add_argument("-n", "--namespace", default="default")
    for verb in ("cordon", "uncordon", "drain"):
        cv = sub.add_parser(verb)
        cv.add_argument("name")
    ro = sub.add_parser("rollout")
    ro.add_argument("verb", choices=["status", "history"])
    ro.add_argument("target")  # deployment/NAME
    sc = sub.add_parser("scale")
    sc.add_argument("target")  # deployment/NAME
    sc.add_argument("--replicas", type=int, required=True)
    sc.add_argument("-n", "--namespace", default="default")
    args = p.parse_args(argv)

    if args.cmd == "rollout":
        if not args.api_server:
            p.error("rollout requires --api-server")
        try:
            rest = RestClient(args.api_server, token=args.token)
        except ValueError:
            p.error(f"--api-server must be HOST:PORT, got {args.api_server!r}")
        try:
            if args.verb == "history":
                return cmd_rollout_history(rest, args)
            return cmd_rollout_status(rest, args)
        except OSError as e:
            print(f"Error: cannot reach API server {args.api_server}: {e}",
                  file=sys.stderr)
            return 2

    if (args.cmd == "get" and getattr(args, "field_selector", "")
            and args.kind != "events"):
        p.error("--field-selector is only supported for 'get events' "
                "(other kinds read the gRPC snapshot, which is "
                "unfiltered by design)")
    if args.cmd == "get" and args.kind in ("events", "leases",
                                           "namespaces", "ns",
                                           "deployments", "deploy",
                                           "csr", "configmaps", "cm",
                                           "serviceaccounts", "sa",
                                           "daemonsets", "ds",
                                           "statefulsets", "sts"):
        if not args.api_server:
            p.error(f"get {args.kind} requires --api-server")
        try:
            rest = RestClient(args.api_server, token=args.token)
        except ValueError:
            p.error(f"--api-server must be HOST:PORT, got {args.api_server!r}")
        try:
            if args.kind == "leases":
                return cmd_get_leases(rest, args)
            if args.kind in ("namespaces", "ns"):
                return cmd_get_namespaces(rest, args)
            if args.kind in ("deployments", "deploy"):
                return cmd_get_deployments(rest, args)
            if args.kind == "csr":
                return cmd_get_csr(rest, args)
            if args.kind in ("configmaps", "cm"):
                return cmd_get_configmaps(rest, args)
            if args.kind in ("serviceaccounts", "sa"):
                return cmd_get_serviceaccounts(rest, args)
            if args.kind in ("daemonsets", "ds"):
                return cmd_get_daemonsets(rest, args)
            if args.kind in ("statefulsets", "sts"):
                return cmd_get_statefulsets(rest, args)
            return cmd_get_events(rest, args)
        except OSError as e:
            print(f"Error: cannot reach API server {args.api_server}: {e}",
                  file=sys.stderr)
            return 1

    if args.cmd in ("create", "delete", "cordon", "uncordon", "drain",
                    "scale", "apply"):
        if not args.api_server:
            p.error(f"{args.cmd} requires --api-server")
        try:
            rest = RestClient(args.api_server, token=args.token)
        except ValueError:
            p.error(f"--api-server must be HOST:PORT, got {args.api_server!r}")
        try:
            if args.cmd == "create":
                return cmd_create(rest, args)
            if args.cmd == "apply":
                return cmd_apply(rest, args)
            if args.cmd == "delete":
                return cmd_delete(rest, args)
            if args.cmd == "drain":
                return cmd_drain(rest, args)
            if args.cmd == "scale":
                return cmd_scale(rest, args)
            return cmd_cordon(rest, args,
                              unschedulable=(args.cmd == "cordon"))
        except OSError as e:
            print(f"Error: cannot reach API server {args.api_server}: {e}",
                  file=sys.stderr)
            return 1

    if (args.cmd == "describe" and args.kind in (
            "deployment", "deploy", "daemonset", "ds",
            "statefulset", "sts")):
        if not args.api_server:
            p.error(f"describe {args.kind} requires --api-server")
        try:
            rest = RestClient(args.api_server, token=args.token)
        except ValueError:
            p.error(f"--api-server must be HOST:PORT, got "
                    f"{args.api_server!r}")
        try:
            return cmd_describe_apps(rest, args)
        except OSError as e:
            print(f"Error: cannot reach API server {args.api_server}: {e}",
                  file=sys.stderr)
            return 2

    if not args.server:
        p.error(f"{args.cmd} requires --server")
    import grpc

    client = _Client(args.server, token=args.token)
    try:
        if args.cmd == "get":
            return cmd_get(client, args)
        if args.cmd == "top":
            return cmd_top(client, args)
        return cmd_describe(client, args)
    except grpc.RpcError as e:
        # kubectl-style one-line failures, not tracebacks: an
        # UNAUTHENTICATED here means the service is token-gated —
        # say how to supply one
        hint = (" (pass --token or set KTPU_TOKEN)"
                if e.code() == grpc.StatusCode.UNAUTHENTICATED else "")
        print(f"Error from server: {e.code().name}: {e.details()}{hint}",
              file=sys.stderr)
        return 1
    finally:
        client.close()


if __name__ == "__main__":
    sys.exit(main())
