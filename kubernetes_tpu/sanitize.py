"""Instrumented-lock runtime sanitizer — the dynamic half of the
concurrency-discipline layer (graftlint R9/R10 are the static half).

graftlint proves lock discipline for the lock acquisitions it can SEE
lexically; everything that crosses a class boundary (the ServingLoop
holding ``loop.lock`` while ``schedule_cycle`` walks the cache, the
/debug handler thread racing the soak's phase engine) is runtime
territory. :class:`LockSanitizer` covers it TSan-style, with the
machinery this codebase already trusts: injected clocks, deterministic
bookkeeping, findings as data.

Three finding kinds, all deduplicated and bounded:

``order-cycle``
    The per-process lock-acquisition-order graph (edge A→B when some
    thread acquired B while holding A) gained a cycle — two threads
    that interleave the involved acquisitions can deadlock. Detection
    is on the ORDER GRAPH, not on live contention, so a seeded test
    catches the hazard with plain sequential execution: thread 1 takes
    A then B, thread 2 takes B then A, and the second interleaving
    closes the cycle even though nobody ever blocked.

``held-too-long``
    A lock was held longer than ``hold_budget_s`` (measured on the
    injected clock). This is the runtime shadow of graftlint R10: a
    blocking call under a lock that the static rule could not see
    (through a callback, a stub, a C extension) still shows up as hold
    time.

``guard-violation``
    Debug-mode dynamic guarded-by: code paths that declare "this runs
    with lock L held" (``assert_held`` — the runtime analog of the
    ``*_locked`` naming convention and ``# guarded-by:`` comments)
    were entered by a thread not holding L.

Zero cost when off: components take an optional ``lock_factory``
callable and default to plain ``threading.Lock``/``RLock`` when it is
None — the sanitizer object, the wrapper class, and every check only
exist when ``observability.lockSanitizer.enabled`` armed them.
:func:`assert_held` no-ops (one ``getattr``) on plain locks.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Set, Tuple


@dataclass
class LockSanitizerConfig:
    """``observability.lockSanitizer`` — arming and budgets."""

    enabled: bool = False
    #: a lock held longer than this is a ``held-too-long`` finding
    #: (injected-clock seconds); 0 disables the hold check
    hold_budget_s: float = 0.25
    #: check ``assert_held`` declarations (guard-violation findings);
    #: cheap, but on the hottest paths, so separately gated
    debug_guards: bool = True
    #: findings ring capacity — counts keep accumulating past it
    max_findings: int = 256


@dataclass(frozen=True)
class LockFinding:
    kind: str  # order-cycle | held-too-long | guard-violation
    detail: str
    locks: Tuple[str, ...]
    thread: str

    def to_json(self) -> dict:
        return {"kind": self.kind, "detail": self.detail,
                "locks": list(self.locks), "thread": self.thread}


class LockSanitizer:
    """Process-wide acquisition-order bookkeeping for every
    :class:`InstrumentedLock` built through :meth:`make_lock`.

    ``on_finding`` (when given) is called OUTSIDE the sanitizer's own
    bookkeeping lock with the finding kind — the scheduler wires it to
    ``scheduler_lock_sanitizer_findings_total{kind}`` — so a metrics
    registry that itself locks can never close a cycle through us (we
    practice the R10 discipline we police).
    """

    KINDS = ("order-cycle", "held-too-long", "guard-violation")

    def __init__(self, config: Optional[LockSanitizerConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_finding: Optional[Callable[[str], None]] = None) -> None:
        self.config = config or LockSanitizerConfig()
        self.clock = clock
        self.on_finding = on_finding
        #: meta-lock for the graph/findings — plain, never instrumented
        self._meta = threading.Lock()
        self._tls = threading.local()
        #: acquisition-order edges: name -> set of names acquired while
        #: ``name`` was held
        self._edges: Dict[str, Set[str]] = {}
        self._findings: Deque[LockFinding] = deque(
            maxlen=max(1, int(self.config.max_findings)))
        self._counts: Dict[str, int] = {k: 0 for k in self.KINDS}
        #: dedupe keys (cycle signature / lock name / site) so one bad
        #: pattern in a hot loop is one finding, not a flood
        self._seen: Set[Tuple[str, str]] = set()

    # -- lock construction --------------------------------------------------

    def make_lock(self, name: str, kind: str = "lock"):
        """An instrumented ``threading.Lock`` (``kind='lock'``) or
        ``RLock`` (``kind='rlock'``) registered under ``name``."""
        inner = threading.RLock() if kind == "rlock" else threading.Lock()
        return InstrumentedLock(self, name, inner)

    def factory(self, prefix: str = "") -> Callable[..., "InstrumentedLock"]:
        """A ``lock_factory(name, kind='lock')`` bound to this sanitizer
        — the injectable seam components accept."""
        def make(name: str, kind: str = "lock"):
            return self.make_lock(prefix + name, kind)
        return make

    # -- per-thread held stack ----------------------------------------------

    def _held(self) -> List[Tuple[str, float]]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def held_names(self) -> Tuple[str, ...]:
        """Locks the CURRENT thread holds, in acquisition order."""
        return tuple(name for name, _t in self._held())

    # -- events (called by InstrumentedLock) --------------------------------

    def note_acquired(self, name: str, reentrant: bool) -> None:
        held = self._held()
        now = self.clock()
        if reentrant:
            held.append((name, now))
            return
        holders = [h for h, _t in held]
        held.append((name, now))
        if not holders:
            return
        with self._meta:
            new_edges = [(h, name) for h in holders
                         if name not in self._edges.setdefault(h, set())]
            for h, _ in new_edges:
                self._edges[h].add(name)
            cycles = [self._find_cycle(name, h) for h, _ in new_edges]
        for cyc in cycles:
            if cyc is not None:
                self._record(
                    "order-cycle",
                    "lock acquisition order forms a cycle "
                    f"({' -> '.join(cyc)} -> {cyc[0]}): threads that "
                    "interleave these acquisitions can deadlock",
                    tuple(cyc), dedupe="/".join(sorted(set(cyc))))

    def note_released(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name:
                _n, t0 = held.pop(i)
                break
        else:
            return
        if name in (h for h, _t in held):
            return  # still reentrantly held: the outer release times it
        budget = self.config.hold_budget_s
        if budget and budget > 0:
            dt = self.clock() - t0
            if dt > budget:
                self._record(
                    "held-too-long",
                    f"`{name}` held {dt:.3f}s against a "
                    f"{budget:.3f}s budget — blocking work is "
                    "happening under this lock",
                    (name,), dedupe=name)

    def _find_cycle(self, start: str, target: str) -> Optional[List[str]]:
        """DFS path start→…→target in the edge graph; with the new edge
        target→start that path IS the cycle. Called under ``_meta``."""
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        visited: Set[str] = set()
        while stack:
            node, path = stack.pop()
            if node == target:
                return path
            if node in visited:
                continue
            visited.add(node)
            for nxt in sorted(self._edges.get(node, ())):
                stack.append((nxt, path + [nxt]))
        return None

    # -- dynamic guarded-by -------------------------------------------------

    def note_guard_violation(self, lock_name: str, site: str) -> None:
        if not self.config.debug_guards:
            return
        self._record(
            "guard-violation",
            f"`{site}` declares it runs with `{lock_name}` held, but "
            "the current thread does not hold it",
            (lock_name,), dedupe=f"{lock_name}@{site}")

    # -- findings -----------------------------------------------------------

    def _record(self, kind: str, detail: str, locks: Tuple[str, ...],
                dedupe: str) -> None:
        with self._meta:
            if (kind, dedupe) in self._seen:
                return
            self._seen.add((kind, dedupe))
            self._counts[kind] = self._counts.get(kind, 0) + 1
            self._findings.append(LockFinding(
                kind, detail, locks, threading.current_thread().name))
        cb = self.on_finding
        if cb is not None:
            cb(kind)

    def counts(self) -> Dict[str, int]:
        with self._meta:
            return dict(self._counts)

    def total_findings(self) -> int:
        with self._meta:
            return sum(self._counts.values())

    def findings(self) -> List[LockFinding]:
        with self._meta:
            return list(self._findings)

    def snapshot(self) -> dict:
        """/debug- and flight-record-shaped summary."""
        with self._meta:
            return {
                "counts": dict(self._counts),
                "edges": sum(len(v) for v in self._edges.values()),
                "findings": [f.to_json() for f in self._findings],
            }


class InstrumentedLock:
    """Drop-in ``threading.Lock``/``RLock`` wrapper that reports
    acquire/release to its :class:`LockSanitizer`. Supports the full
    context-manager + acquire/release surface the codebase uses."""

    __slots__ = ("_san", "name", "_inner", "_depth_tls")

    def __init__(self, sanitizer: LockSanitizer, name: str, inner) -> None:
        self._san = sanitizer
        self.name = name
        self._inner = inner
        self._depth_tls = threading.local()

    def _depth(self) -> int:
        return getattr(self._depth_tls, "d", 0)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            reentrant = self._depth() > 0
            self._depth_tls.d = self._depth() + 1
            self._san.note_acquired(self.name, reentrant)
        return got

    def release(self) -> None:
        self._depth_tls.d = max(0, self._depth() - 1)
        self._san.note_released(self.name)
        self._inner.release()

    def __enter__(self) -> "InstrumentedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def held_by_me(self) -> bool:
        return self._depth() > 0

    def assert_held(self, site: str) -> None:
        if not self.held_by_me():
            self._san.note_guard_violation(self.name, site)


def assert_held(lock, site: str) -> None:
    """Declare "this code runs with ``lock`` held" — the runtime analog
    of the ``*_locked`` naming convention. One no-op ``getattr`` on a
    plain ``threading`` lock; a recorded ``guard-violation`` finding on
    an instrumented one when the declaration is false."""
    check = getattr(lock, "assert_held", None)
    if check is not None:
        check(site)


def make_lock(lock_factory, name: str, kind: str = "lock"):
    """The seam components use: ``lock_factory(name, kind)`` when armed,
    a plain ``threading`` lock when ``lock_factory`` is None."""
    if lock_factory is not None:
        return lock_factory(name, kind)
    return threading.RLock() if kind == "rlock" else threading.Lock()
