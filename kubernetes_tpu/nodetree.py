"""NodeTree + adaptive node-search truncation.

- :class:`NodeTree` — zone-aware round-robin node enumeration
  (``pkg/scheduler/internal/cache/node_tree.go:31``; ``Next()`` :162):
  consecutive enumerations start where the last stopped and interleave
  zones, so a truncated search spreads load across zones between cycles.
- :func:`num_feasible_nodes_to_find` — the percentageOfNodesToScore
  subsampling rule (``generic_scheduler.go:437``; defaults
  ``api/types.go:40``): adaptive 50%→5%, minimum 100 nodes.

The dense batch solver does not need subsampling below ~5k nodes (one
fused pass scores everything), but the truncation remains available for
(a) reference-parity runs and (b) capping device work on very large
snapshots: the driver turns the subset into an extra column mask.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kubernetes_tpu.api.types import Node

#: generic_scheduler.go:53-62
MIN_FEASIBLE_NODES_TO_FIND = 100
MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND = 5
#: api/types.go:40
DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE = 50


def num_feasible_nodes_to_find(
    num_all_nodes: int, percentage: int = 0
) -> int:
    """numFeasibleNodesToFind (generic_scheduler.go:437). ``percentage``
    0 = adaptive default."""
    if (
        num_all_nodes < MIN_FEASIBLE_NODES_TO_FIND
        or percentage >= 100
    ):
        return num_all_nodes
    adaptive = percentage
    if adaptive <= 0:
        adaptive = DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE - num_all_nodes // 125
        if adaptive < MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND:
            adaptive = MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND
    num = num_all_nodes * adaptive // 100
    if num < MIN_FEASIBLE_NODES_TO_FIND:
        return MIN_FEASIBLE_NODES_TO_FIND
    return num


class NodeTree:
    """Zone -> node-name lists with a resumable round-robin cursor."""

    def __init__(self) -> None:
        self._zones: List[str] = []  # insertion-ordered zone keys
        self._nodes: Dict[str, List[str]] = {}
        self._zone_idx = 0
        self._node_idx: Dict[str, int] = {}
        self.num_nodes = 0

    @staticmethod
    def _zone_of(node: Node) -> str:
        zk = node.zone_key()
        return f"{zk[0]}:{zk[1]}" if zk else ""

    def add_node(self, node: Node) -> None:
        z = self._zone_of(node)
        if z not in self._nodes:
            self._zones.append(z)
            self._nodes[z] = []
            self._node_idx[z] = 0
        if node.name not in self._nodes[z]:
            self._nodes[z].append(node.name)
            self.num_nodes += 1

    def remove_node(self, node: Node) -> None:
        z = self._zone_of(node)
        names = self._nodes.get(z)
        if names and node.name in names:
            names.remove(node.name)
            self.num_nodes -= 1
            if not names:
                del self._nodes[z]
                self._zones.remove(z)
                self._node_idx.pop(z, None)

    def next(self) -> Optional[str]:
        """node_tree.go:162 Next(): round-robin over zones, resuming."""
        if not self._zones:
            return None
        for _ in range(len(self._zones)):
            if self._zone_idx >= len(self._zones):
                self._zone_idx = 0
            z = self._zones[self._zone_idx]
            names = self._nodes[z]
            i = self._node_idx[z]
            if i >= len(names):
                # zone exhausted this sweep: reset and move on
                self._node_idx[z] = 0
                self._zone_idx += 1
                continue
            self._node_idx[z] = i + 1
            self._zone_idx += 1
            return names[i]
        # all zones exhausted simultaneously: start a fresh sweep
        for z in self._zones:
            self._node_idx[z] = 0
        self._zone_idx = 0
        return self.next() if self.num_nodes else None

    def take(self, n: int) -> List[str]:
        """The next ``n`` distinct nodes in rotation order (≤ num_nodes)."""
        n = min(n, self.num_nodes)
        out: List[str] = []
        seen = set()
        while len(out) < n:
            name = self.next()
            if name is None:
                break
            if name in seen:
                continue
            seen.add(name)
            out.append(name)
        return out
