"""Admission chain — the kube-apiserver admission analog (SURVEY §2.2
kube-apiserver row: "REST façade over etcd; admission chain...";
reference ``staging/src/k8s.io/apiserver/pkg/admission`` interfaces and
the in-tree plugins under ``plugin/pkg/admission/``).

The chain runs on every pod CREATE entering the hub (the hollow
apiserver), in the reference's two phases: all mutating plugins first
(``admit``), then all validating plugins (``validate``) — a mutation by
a later plugin re-checked by nothing is the classic webhook-ordering
bug, and the phase split is what prevents it.

Plugins implemented (each cites its reference):

- :class:`NamespaceLifecycle` — rejects creates into terminating (or,
  in strict mode, unknown) namespaces
  (``plugin/pkg/admission/namespace/lifecycle/admission.go``).
- :class:`PriorityAdmission` — resolves ``pod.priority_class_name`` to
  the integer ``pod.priority`` + ``preemption_policy``, applies the
  global-default class, rejects unknown classes
  (``plugin/pkg/admission/priority/admission.go:79`` Admit).
- :class:`DefaultTolerationSeconds` — appends the 300 s
  not-ready/unreachable NoExecute tolerations when the pod declares
  none (``plugin/pkg/admission/defaulttolerationseconds/admission.go``).
- :class:`ResourceQuotaAdmission` — charges the pod against its
  namespace's quotas, rejecting over-quota creates
  (``plugin/pkg/admission/resourcequota/admission.go``); the paired
  :class:`QuotaController` recalculates usage from truth the way
  ``pkg/controller/resourcequota`` replenishes on deletes.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.api.types import EFFECT_NO_EXECUTE, Pod, Toleration

NS_ACTIVE = "Active"
NS_TERMINATING = "Terminating"

#: built-in system classes (pkg/apis/scheduling/types.go:29-37)
SYSTEM_CRITICAL = {
    "system-cluster-critical": 2_000_000_000,
    "system-node-critical": 2_000_001_000,
}

TAINT_NOT_READY = "node.kubernetes.io/not-ready"
TAINT_UNREACHABLE = "node.kubernetes.io/unreachable"
DEFAULT_TOLERATION_SECONDS = 300


class AdmissionError(Exception):
    """Admission rejection — the apiserver's 403 Forbidden with a plugin
    message."""


@dataclass
class Namespace:
    name: str
    phase: str = NS_ACTIVE


@dataclass
class PriorityClass:
    """scheduling.k8s.io/v1 PriorityClass slice: value, global default,
    preemption policy (PreemptionPolicy requires NonPreemptingPriority)."""

    name: str
    value: int
    global_default: bool = False
    preemption_policy: str = "PreemptLowerPriority"


@dataclass
class ResourceQuota:
    """v1.ResourceQuota slice: hard limits on pod count / cpu / memory
    requests, with live usage. ``used`` is maintained by admission
    charges and the :class:`QuotaController` recalculation."""

    name: str
    namespace: str = "default"
    hard_pods: Optional[int] = None
    hard_cpu_milli: Optional[float] = None
    hard_memory: Optional[float] = None
    used_pods: int = 0
    used_cpu_milli: float = 0.0
    used_memory: float = 0.0

    def would_exceed(self, pod: Pod) -> Optional[str]:
        if self.hard_pods is not None and self.used_pods + 1 > self.hard_pods:
            return (f"pods quota exceeded: used {self.used_pods}, "
                    f"limited {self.hard_pods}")
        if (self.hard_cpu_milli is not None
                and self.used_cpu_milli + pod.requests.cpu_milli
                > self.hard_cpu_milli + 1e-9):
            return (f"requests.cpu quota exceeded: used "
                    f"{self.used_cpu_milli}m + {pod.requests.cpu_milli}m, "
                    f"limited {self.hard_cpu_milli}m")
        if (self.hard_memory is not None
                and self.used_memory + pod.requests.memory
                > self.hard_memory + 1e-9):
            return "requests.memory quota exceeded"
        return None

    def charge(self, pod: Pod) -> None:
        self.used_pods += 1
        self.used_cpu_milli += pod.requests.cpu_milli
        self.used_memory += pod.requests.memory


# ---------------------------------------------------------------------------
# Plugins
# ---------------------------------------------------------------------------


class NamespaceLifecycle:
    """lifecycle/admission.go: block creates into namespaces on the way
    out (and, strictly, into namespaces that don't exist)."""

    def __init__(self, namespaces: Dict[str, Namespace],
                 strict: bool = False) -> None:
        self.namespaces = namespaces
        self.strict = strict

    def validate(self, pod: Pod) -> None:
        ns = self.namespaces.get(pod.namespace)
        if ns is None:
            if self.strict:
                raise AdmissionError(
                    f'namespaces "{pod.namespace}" not found')
            return
        if ns.phase == NS_TERMINATING:
            raise AdmissionError(
                f"unable to create new content in namespace "
                f"{pod.namespace} because it is being terminated")


class PriorityAdmission:
    """priority/admission.go Admit: resolve the class name; empty name ⇒
    global default class (or 0); unknown ⇒ reject. The resolved integer
    and preemption policy are what the scheduler/preemption read."""

    def __init__(self, classes: Dict[str, PriorityClass]) -> None:
        self.classes = classes

    def admit(self, pod: Pod) -> Pod:
        name = pod.priority_class_name
        if not name:
            default = next(
                (c for c in self.classes.values() if c.global_default), None)
            if default is None:
                return pod
            return dataclasses.replace(
                pod, priority_class_name=default.name, priority=default.value,
                preemption_policy=default.preemption_policy)
        if name in SYSTEM_CRITICAL:
            return dataclasses.replace(pod, priority=SYSTEM_CRITICAL[name])
        cls = self.classes.get(name)
        if cls is None:
            raise AdmissionError(
                f"no PriorityClass with name {name} was found")
        return dataclasses.replace(
            pod, priority=cls.value,
            preemption_policy=cls.preemption_policy)


class DefaultTolerationSeconds:
    """defaulttolerationseconds/admission.go: every pod gets 300 s
    not-ready/unreachable NoExecute tolerations unless it already
    declares its own for that taint."""

    def admit(self, pod: Pod) -> Pod:
        extra: List[Toleration] = []
        for key in (TAINT_NOT_READY, TAINT_UNREACHABLE):
            declared = any(
                t.key == key or (not t.key and t.operator == "Exists")
                for t in pod.tolerations
            )
            if not declared:
                extra.append(Toleration(
                    key=key, operator="Exists", effect=EFFECT_NO_EXECUTE,
                    toleration_seconds=DEFAULT_TOLERATION_SECONDS))
        if not extra:
            return pod
        return dataclasses.replace(
            pod, tolerations=pod.tolerations + tuple(extra))


@dataclass
class LimitRange:
    """v1.LimitRange slice (plugin/pkg/admission/limitranger): per-
    namespace container defaults and min/max bounds for cpu/memory
    requests. ``default_*`` fill a container that declares nothing;
    ``min_*``/``max_*`` reject out-of-bounds requests (0 = unbounded)."""

    namespace: str = "default"
    default_cpu_milli: float = 0.0
    default_memory: float = 0.0
    min_cpu_milli: float = 0.0
    min_memory: float = 0.0
    max_cpu_milli: float = 0.0
    max_memory: float = 0.0


class LimitRanger:
    """limitranger/admission.go Admit: apply the namespace's LimitRange
    defaults to request-less pods, then validate min/max. Runs BEFORE
    quota (the reference's ordering) so defaulted requests are what
    quota charges — without that ordering a request-less pod would
    charge zero and then consume a defaulted amount."""

    def __init__(self, limit_ranges: List[LimitRange]) -> None:
        self.limit_ranges = limit_ranges

    def admit(self, pod: Pod) -> Pod:
        for lr in self.limit_ranges:
            if lr.namespace != pod.namespace:
                continue
            req = pod.requests
            cpu, mem = req.cpu_milli, req.memory
            if not cpu and lr.default_cpu_milli:
                cpu = lr.default_cpu_milli
            if not mem and lr.default_memory:
                mem = lr.default_memory
            if lr.min_cpu_milli and cpu < lr.min_cpu_milli:
                raise AdmissionError(
                    f"pods \"{pod.name}\" is forbidden: minimum cpu "
                    f"usage per Container is {lr.min_cpu_milli:g}m")
            if lr.max_cpu_milli and cpu > lr.max_cpu_milli:
                raise AdmissionError(
                    f"pods \"{pod.name}\" is forbidden: maximum cpu "
                    f"usage per Container is {lr.max_cpu_milli:g}m")
            if lr.min_memory and mem < lr.min_memory:
                raise AdmissionError(
                    f"pods \"{pod.name}\" is forbidden: minimum memory "
                    f"usage per Container is {lr.min_memory:g}")
            if lr.max_memory and mem > lr.max_memory:
                raise AdmissionError(
                    f"pods \"{pod.name}\" is forbidden: maximum memory "
                    f"usage per Container is {lr.max_memory:g}")
            if (cpu, mem) != (req.cpu_milli, req.memory):
                pod = dataclasses.replace(
                    pod, requests=dataclasses.replace(
                        req, cpu_milli=cpu, memory=mem,
                        scalars=dict(req.scalars)))
        return pod


class ResourceQuotaAdmission:
    """resourcequota/admission.go: evaluate the pod against every quota
    in its namespace; any breach rejects; success charges them all."""

    def __init__(self, quotas: List[ResourceQuota]) -> None:
        self.quotas = quotas

    def validate(self, pod: Pod) -> None:
        for q in self.quotas:
            if q.namespace != pod.namespace:
                continue
            reason = q.would_exceed(pod)
            if reason:
                raise AdmissionError(
                    f"exceeded quota: {q.name}, {reason}")

    def charge(self, pod: Pod) -> None:
        for q in self.quotas:
            if q.namespace == pod.namespace:
                q.charge(pod)


# ---------------------------------------------------------------------------
# Chain
# ---------------------------------------------------------------------------


class AdmissionChain:
    """Ordered two-phase runner (apiserver/pkg/admission/chain.go):
    every plugin's ``admit`` (mutate) runs before any ``validate``."""

    def __init__(self, plugins: List[object]) -> None:
        self.plugins = plugins
        self.admitted = 0
        self.rejected = 0

    def run(self, pod: Pod) -> Pod:
        try:
            for p in self.plugins:
                admit = getattr(p, "admit", None)
                if admit is not None:
                    pod = admit(pod)
            for p in self.plugins:
                validate = getattr(p, "validate", None)
                if validate is not None:
                    validate(pod)
        except AdmissionError:
            self.rejected += 1
            raise
        # post-validation side effects (quota charge) — the apiserver
        # commits usage only once every validating plugin passed
        for p in self.plugins:
            charge = getattr(p, "charge", None)
            if charge is not None:
                charge(pod)
        self.admitted += 1
        return pod


class QuotaController:
    """pkg/controller/resourcequota replenishment: recompute ``used``
    from the live truth so deletes release quota (admission only ever
    charges)."""

    def __init__(self, hub) -> None:
        self.hub = hub

    def reconcile(self) -> None:
        for q in self.hub.quotas:
            q.used_pods = 0
            q.used_cpu_milli = 0.0
            q.used_memory = 0.0
        for pod in self.hub.truth_pods.values():
            for q in self.hub.quotas:
                if q.namespace == pod.namespace:
                    q.charge(pod)


def default_chain(namespaces: Dict[str, Namespace],
                  classes: Dict[str, PriorityClass],
                  quotas: List[ResourceQuota],
                  strict_namespaces: bool = False,
                  limit_ranges: Optional[List[LimitRange]] = None,
                  ) -> AdmissionChain:
    """The default plugin order — the slice of
    ``kubeapiserver/options/plugins.go`` AllOrderedPlugins this hub
    enforces (NamespaceLifecycle first, LimitRanger BEFORE quota so
    defaulted requests are what quota charges, quota last — the real
    ordering)."""
    return AdmissionChain([
        NamespaceLifecycle(namespaces, strict_namespaces),
        PriorityAdmission(classes),
        DefaultTolerationSeconds(),
        LimitRanger(limit_ranges if limit_ranges is not None else []),
        ResourceQuotaAdmission(quotas),
    ])
